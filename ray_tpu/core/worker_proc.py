"""Worker process: executes tasks and hosts actors.

The per-process core client (``WorkerCore``) mirrors the reference's
``CoreWorker`` execution side (src/ray/core_worker/core_worker_process.cc:63
RunTaskExecutionLoop; python/ray/_raylet.pyx:1693 execute_task): a loop that
receives task specs on the *task connection*, executes them, and writes
results either straight into the shared-memory store (large) or inline into
the completion message (small). A second *data connection* carries
synchronous worker→driver requests (get/put/submit/actor calls), which in the
reference are CoreWorker RPCs to the owner.

Launched as: python -m ray_tpu.core.worker_main
with connection info in environment variables (RTPU_ADDRESS, RTPU_AUTH,
RTPU_STORE, RTPU_NODE_ID, RTPU_WORKER_ID).
"""

from __future__ import annotations

import os
import sys
import threading
import traceback
from multiprocessing.connection import Client
from typing import Any, Dict, List, Optional

from ray_tpu.core import protocol, serialization
from ray_tpu.core.protocol import _TopLevelDep
from ray_tpu.core.ids import ActorID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core import runtime_context
from ray_tpu.core.object_store.store import ShmObjectStore
from ray_tpu.exceptions import ObjectStoreFullError, TaskError
from ray_tpu.util.debug_lock import make_lock


class WorkerCore:
    """Core client installed in worker processes."""

    def __init__(self, task_conn, data_conn, store: Optional[ShmObjectStore],
                 node_id: NodeID, worker_id: WorkerID):
        self.task_conn = task_conn
        self.data_conn = data_conn
        self.store = store
        if store is not None:
            # Store-full backpressure: ask the owner to spill cold objects
            # (only the owner knows which containers are safe to spill).
            store.need_space_hook = (
                lambda n: self._request(protocol.REQ_NEED_SPACE, n)[1])
        self.node_id = node_id
        self.worker_id = worker_id
        # task/actor context is thread-local: concurrent actor threads
        # (max_concurrency > 1) must not clobber each other's attribution
        self._ctx_tls = threading.local()
        # set by the SIGTERM handler of actors created with trap_sigterm
        # (train workers); read by train.preempted()
        self.preempted = threading.Event()
        self._data_lock = make_lock("WorkerCore._data_lock")
        self._send_lock = make_lock("WorkerCore._send_lock")
        self._async_dirty = False  # async sends since last barrier
        self._functions: Dict[bytes, Any] = {}
        self._driver_known_fns: set = set()
        self._actors: Dict[bytes, Any] = {}
        self._actor_loops: Dict[bytes, Any] = {}  # actor_id -> asyncio loop
        self._actor_pools: Dict[bytes, Any] = {}  # actor_id -> executor
        # named concurrency groups (reference:
        # concurrency_group_manager.h:34): per-group executors + the
        # method -> group routing map declared at actor creation
        self._actor_group_pools: Dict[bytes, Dict[str, Any]] = {}
        self._actor_method_group: Dict[bytes, Dict[str, str]] = {}

    @property
    def current_task_id(self) -> Optional[TaskID]:
        return getattr(self._ctx_tls, "task_id", None)

    @current_task_id.setter
    def current_task_id(self, v) -> None:
        self._ctx_tls.task_id = v

    @property
    def current_actor_id(self) -> Optional[ActorID]:
        return getattr(self._ctx_tls, "actor_id", None)

    @current_actor_id.setter
    def current_actor_id(self, v) -> None:
        self._ctx_tls.actor_id = v

    # ---- data-conn RPC ------------------------------------------------------

    def _request(self, *msg):
        from ray_tpu.core.config import config

        if config.testing_rpc_delay_ms > 0:
            # Chaos delay injection (reference: asio_chaos.cc:35).
            import random
            import time

            time.sleep(random.uniform(0, config.testing_rpc_delay_ms / 1000))
        with self._data_lock:
            # rtpu-lint: disable=L2 — _data_lock must span send+recv:
            # data_conn is shared by every thread in this worker, and the
            # lock is what pairs each request with its own response
            self.data_conn.send(msg)
            reply = self.data_conn.recv()  # rtpu-lint: disable=L2 — see above
        if reply[0] == "err":
            err = protocol.deserialize_payload(reply[1], store=self.store)
            raise err.error if isinstance(err, protocol.ErrorValue) else err
        return reply

    def _send_async(self, *msg):
        """Fire-and-forget send: the owner applies in FIFO order on this
        connection, so a later REQ_GET can never observe pre-apply state.
        Removing the reply round trip from put/submit is what lets a
        worker drive thousands of calls/s through the owner (reference:
        async task submission via the core worker's io loop). Results
        travel a DIFFERENT connection — _send_results barriers first so a
        returned ref can never reach the driver before its submission is
        applied (else ray.cancel on it would silently no-op)."""
        with self._data_lock:
            # rtpu-lint: disable=L2 — _data_lock serializes frames on the
            # shared data_conn (its whole purpose); no other lock nests here
            self.data_conn.send(msg)
        self._async_dirty = True

    # ---- core-client surface (same as driver Runtime) -----------------------

    def get_objects(self, refs: List[ObjectRef], timeout: Optional[float] = None):
        oids = [r.id for r in refs]
        values: Dict[ObjectID, Any] = {}
        missing: List[ObjectID] = []
        for oid in oids:
            if self.store is not None and self.store.contains(oid):
                values[oid] = protocol.shm_unpack(self.store, oid)
            else:
                missing.append(oid)
        if missing:
            timeout_ms = -1 if timeout is None else int(timeout * 1000)
            cur = self.current_task_id.binary() if self.current_task_id else None
            _, payloads = self._request(
                protocol.REQ_GET, [o.binary() for o in missing], timeout_ms,
                cur,
            )
            for oid in missing:
                values[oid] = protocol.deserialize_payload(
                    payloads[oid.binary()], store=self.store
                )
        out = []
        for oid in oids:
            out.append(protocol.raise_if_error(values[oid]))
        return out

    def put_object(self, value: Any) -> ObjectRef:
        oid = ObjectID.from_random()
        payload = protocol.serialize_value(value, store=self.store)
        if payload[0] == "shm":
            # Data already in shm under a scratch id; re-register under oid is
            # avoided by just using the payload's id as the object id.
            oid = ObjectID(payload[1])
            self._send_async(protocol.REQ_PUT_META_ASYNC, oid.binary(), None)
        else:
            self._send_async(protocol.REQ_PUT_META_ASYNC, oid.binary(),
                             payload)
        return ObjectRef(oid, core=self)

    def submit_task(self, fn_id: bytes, pickled_fn: Optional[bytes], args: tuple,
                    kwargs: dict, num_returns, options: dict) -> List[ObjectRef]:
        args_payload, deps, nested = _prepare_args_local(self, args, kwargs)
        send_fn = None if fn_id in self._driver_known_fns else pickled_fn
        options = dict(options)
        if num_returns == "streaming":
            # the single pre-generated return id doubles as the stream
            # seed; the owner registers the stream when it applies this
            # submission (see Runtime._apply_worker_submit)
            num_returns = 1
            options["__stream"] = True
        options["__deps"] = deps
        # span propagation: nested submissions carry the submitting
        # task's id so cross-process traces keep causality
        if self.current_task_id is not None:
            options["__parent"] = self.current_task_id.hex()
        options["__nested"] = nested
        return_ids = [ObjectID.from_random() for _ in range(num_returns)]
        self._send_async(
            protocol.REQ_SUBMIT_ASYNC, fn_id, send_fn, args_payload, {},
            [r.binary() for r in return_ids], options,
        )
        self._driver_known_fns.add(fn_id)
        return [ObjectRef(rid, core=self) for rid in return_ids]

    def submit_actor_task(self, actor_id: ActorID, method: str, args: tuple,
                          kwargs: dict, num_returns,
                          options=None) -> List[ObjectRef]:
        args_payload, deps, _nested = _prepare_args_local(self, args, kwargs)
        extra = {"__deps": deps}
        if options:
            # per-call retry options (max_task_retries/retry_exceptions)
            # resolved by the owner when it builds the spec
            extra["__opts"] = dict(options)
        if num_returns == "streaming":
            num_returns = 1
            extra["__stream"] = True
        if self.current_task_id is not None:
            extra["__parent"] = self.current_task_id.hex()
        return_ids = [ObjectID.from_random() for _ in range(num_returns)]
        self._send_async(
            protocol.REQ_ACTOR_CALL_ASYNC, actor_id.binary(), method,
            args_payload, extra, [r.binary() for r in return_ids],
        )
        return [ObjectRef(rid, core=self) for rid in return_ids]

    # ---- streaming generator consumption (ObjectRefGenerator) ---------------

    def stream_next(self, seed: bytes, index: int,
                    timeout: Optional[float] = None, owner=None):
        """Next streamed return of generator ``seed``: blocks (in short
        request slices, so cancel/SIGINT stays responsive) until the
        producer seals index ``index`` or ends the stream."""
        import time

        from ray_tpu.exceptions import ObjectTimeoutError

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            slice_ms = 200
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ObjectTimeoutError(
                        f"stream {seed.hex()} index {index} not produced "
                        f"within {timeout}s")
                slice_ms = min(slice_ms, max(1, int(remaining * 1000)))
            reply = self._request(
                protocol.REQ_STREAM_NEXT, seed, index, slice_ms, owner)
            if reply[0] != "pending":
                return reply[0], reply[1] if len(reply) > 1 else None

    def stream_consumed(self, seed: bytes, index: int, owner=None):
        self._send_async(
            protocol.REQ_STREAM_CONSUMED_ASYNC, seed, index, owner)

    def create_actor_from_worker(self, fn_id: bytes, pickled_cls: Optional[bytes],
                                 args: tuple, kwargs: dict, opts: dict) -> ActorID:
        args_payload, deps, _nested = _prepare_args_local(self, args, kwargs)
        send_cls = None if fn_id in self._driver_known_fns else pickled_cls
        _, actor_id_b = self._request(
            protocol.REQ_CREATE_ACTOR, fn_id, send_cls, args_payload, deps, opts
        )
        self._driver_known_fns.add(fn_id)
        return ActorID(actor_id_b)

    def wait(self, refs, num_returns=1, timeout=None):
        if num_returns > len(refs):
            raise ValueError("num_returns exceeds number of refs")
        by_id = {r.id.binary(): r for r in refs}
        cur = self.current_task_id.binary() if self.current_task_id else None
        _, ready_b, rest_b = self._request(
            protocol.REQ_WAIT, list(by_id.keys()), num_returns, timeout, cur
        )
        return [by_id[b] for b in ready_b], [by_id[b] for b in rest_b]

    # ---- placement groups (proxied to the driver) ---------------------------

    def create_placement_group(self, bundles, strategy, name):
        from ray_tpu.core.ids import PlacementGroupID
        from ray_tpu.core.placement_group import PlacementGroup

        _, (pg_id_b, specs) = self._request(
            protocol.REQ_PG, "create", bundles, strategy, name)
        return PlacementGroup(PlacementGroupID(pg_id_b), specs)

    def remove_placement_group(self, pg_id):
        self._request(protocol.REQ_PG, "remove", pg_id.binary())

    def placement_group_ready_ref(self, pg_id):
        _, oid_b = self._request(protocol.REQ_PG, "ready_ref", pg_id.binary())
        return ObjectRef(ObjectID(oid_b), core=self)

    def wait_placement_group(self, pg_id, timeout):
        _, ok = self._request(protocol.REQ_PG, "wait", pg_id.binary(), timeout)
        return ok

    def placement_group_chips(self, pg_id, index):
        _, chips = self._request(protocol.REQ_PG, "chips", pg_id.binary(), index)
        return chips

    def placement_group_table(self):
        _, table = self._request(protocol.REQ_PG, "table")
        return table

    def kv_op(self, op: str, key: str, value=None):
        _, result = self._request(protocol.REQ_KV, op, key, value)
        return result

    def pubsub_op(self, op: str, channel: str, arg=None,
                  timeout: float = 0.0):
        _, result = self._request(protocol.REQ_PUBSUB, op, channel, arg,
                                  timeout)
        return result

    def cancel_task(self, ref: ObjectRef, force: bool = False):
        self._request(protocol.REQ_CANCEL, ref.id.binary(), force)

    def get_actor_handle(self, name: str):
        _, payload = self._request(protocol.REQ_GET_ACTOR, name)
        return protocol.deserialize_payload(payload, store=self.store)

    def as_future(self, ref: ObjectRef):
        import asyncio

        loop = asyncio.get_event_loop()
        fut = loop.create_future()

        def resolve():
            try:
                v = self.get_objects([ref])[0]
                loop.call_soon_threadsafe(fut.set_result, v)
            except BaseException as e:  # noqa: BLE001
                loop.call_soon_threadsafe(fut.set_exception, e)

        threading.Thread(target=resolve, daemon=True).start()
        return fut

    # ---- execution ----------------------------------------------------------

    def run_loop(self):
        self.task_conn.send((protocol.MSG_READY, os.getpid()))
        while True:
            try:
                msg = self.task_conn.recv()
            except (EOFError, OSError):
                break
            tag = msg[0]
            if tag == protocol.MSG_SHUTDOWN:
                for pool in self._actor_pools.values():
                    pool.shutdown(wait=False, cancel_futures=True)
                for pools in self._actor_group_pools.values():
                    for pool in pools.values():
                        pool.shutdown(wait=False, cancel_futures=True)
                break
            elif tag == protocol.MSG_REGISTER_FN:
                _, fn_id, pickled_fn = msg
                self._functions[fn_id] = serialization.unpack(pickled_fn)
            elif tag == protocol.MSG_TASK_BATCH:
                self._execute_task_batch(msg[1])
            elif tag == protocol.MSG_CREATE_ACTOR:
                self._create_actor(msg)
            elif tag == protocol.MSG_ACTOR_CALL:
                group = self._actor_method_group.get(msg[2], {}).get(msg[3])
                pool = None
                if group is not None:
                    # named concurrency group: this method's calls share
                    # the group's own thread budget, isolated from other
                    # groups (reference: concurrency groups)
                    pool = self._actor_group_pools[msg[2]].get(group)
                if pool is None:
                    pool = self._actor_pools.get(msg[2])
                if pool is not None:
                    # max_concurrency > 1: calls overlap on pool threads
                    # (FIFO submission; completion may reorder — the
                    # reference's threaded-actor semantics)
                    pool.submit(self._execute_actor_call, msg)
                else:
                    self._execute_actor_call(msg)
            else:  # pragma: no cover
                sys.stderr.write(f"worker: unknown message {tag!r}\n")

    def _decode_args(self, args_payload, inline_values):
        args, kwargs = protocol.deserialize_payload(args_payload, store=self.store)
        dep_cache: Dict[bytes, Any] = {}

        def resolve(v):
            if isinstance(v, _TopLevelDep):
                b = v.oid_bytes
                if b not in dep_cache:
                    if b in inline_values and inline_values[b] is not None:
                        dep_cache[b] = protocol.deserialize_payload(
                            inline_values[b], store=self.store
                        )
                    else:
                        dep_cache[b] = protocol.shm_unpack(self.store, ObjectID(b))
                return protocol.raise_if_error(dep_cache[b])
            return v

        args = tuple(resolve(a) for a in args)
        kwargs = {k: resolve(v) for k, v in kwargs.items()}
        return args, kwargs

    @staticmethod
    def _split_returns(result, num_returns: int) -> list:
        if num_returns == 1:
            return [result]
        values = list(result)
        if len(values) != num_returns:
            raise ValueError(
                f"task declared num_returns={num_returns} but returned "
                f"{len(values)} values"
            )
        return values

    @staticmethod
    def _error_payload(exc: BaseException):
        """Serialize an exception, falling back to a repr-wrapped error when
        the original (or its cause chain) does not pickle."""
        err = exc if isinstance(exc, TaskError) else TaskError(
            exc, traceback.format_exc())
        try:
            return protocol.serialize_value(protocol.ErrorValue(err), store=None)
        except Exception:
            return protocol.serialize_value(
                protocol.ErrorValue(TaskError(
                    RuntimeError(repr(exc)), traceback.format_exc())),
                store=None)

    def _dag_start(self, instance, in_descs, out_descs, method: str) -> str:
        """Start a compiled-DAG resident loop: read ALL input channels (in
        edge order), invoke the bound method with those values, write the
        result to EVERY output channel. Errors are forwarded as ('e', exc)
        markers so downstream stages pass them through and the driver
        re-raises (reference: compiled DAG error propagation). Channels
        may be shm (same-node) or socket (cross-node) per edge."""
        import threading

        from ray_tpu.dag.channel import ChannelClosed, open_endpoint

        # accept the legacy single-descriptor form
        if in_descs and isinstance(in_descs, tuple) \
                and not isinstance(in_descs[0], (tuple, list)):
            in_descs, out_descs = [in_descs], [out_descs]
        fn = getattr(instance, method)

        def loop():
            import sys
            import traceback as tb

            # open INSIDE the loop thread: socket readers bind+publish
            # here, writers block until their peer publishes — neither
            # may stall the __rtpu_dag_start__ ack
            ins: list = []
            outs: list = []
            try:
                # append one by one: a failure partway must not orphan
                # the endpoints already opened (a bound socket reader has
                # published its rendezvous key by now)
                for d in in_descs:
                    ins.append(open_endpoint(d, store=self.store,
                                             kv=self.kv_op, role="reader"))
                for d in out_descs:
                    outs.append(open_endpoint(d, store=self.store,
                                              kv=self.kv_op,
                                              role="writer"))
            except Exception as e:  # noqa: BLE001
                # a real setup failure must not present as a silent hang:
                # log it, and try to push the error downstream so the
                # driver's first execute raises instead of timing out
                tb.print_exc(file=sys.stderr)
                err = RuntimeError(
                    f"DAG stage {method!r} failed to open its channels: "
                    f"{e!r}")
                for d in out_descs:
                    try:
                        outch = open_endpoint(d, store=self.store,
                                              kv=self.kv_op, role="writer",
                                              timeout_ms=5000)
                        outch.write(("e", err), timeout_ms=5000)
                        outs.append(outch)
                    # rtpu-lint: disable=L4 — best-effort error fan-out:
                    # a downstream peer that is itself dead cannot be
                    # told; the remaining descriptors still get the error
                    except Exception:  # noqa: BLE001 — peer gone too
                        pass
                for ch in ins + outs:
                    ch.release()
                return
            try:
                while True:
                    vals = []
                    err = None
                    try:
                        for inch in ins:
                            tag, value = inch.read(timeout_ms=-1)
                            if tag == "e" and err is None:
                                err = value
                            vals.append(value)
                    except ChannelClosed:
                        for outch in outs:
                            outch.close()
                        return
                    except Exception:  # noqa: BLE001 — store torn down
                        return
                    if err is not None:
                        out = ("e", err)
                    else:
                        try:
                            out = ("v", fn(*vals))
                        except BaseException as e:  # noqa: BLE001
                            out = ("e", e)
                    # infinite timeout to MATCH the infinite reads: with a
                    # pipelined call in flight, a slow downstream stage
                    # (LLM decode) can legitimately hold the ack >10s
                    for outch in outs:
                        outch.write(out, timeout_ms=-1)
            finally:
                for ch in ins + outs:
                    ch.release()

        threading.Thread(target=loop, daemon=True,
                         name=f"dag-{method}").start()
        return "ok"

    @staticmethod
    def _dag_devinfo() -> tuple:
        """(pid, is_tpu) for the __rtpu_dag_devinfo__ compile probe. TPU
        detection is env-first (the runtime pins chips into TPU actors'
        env before jax ever imports) so the probe never forces a jax
        backend init on a worker that doesn't need one."""
        import os as _os

        import sys as _sys

        is_tpu = bool(_os.environ.get("RTPU_TPU_CHIPS")
                      or _os.environ.get("TPU_VISIBLE_CHIPS"))
        if not is_tpu and "jax" in _sys.modules:
            # only consult jax if the actor already imported it — the
            # probe must not pay a cold backend init on plain actors
            try:
                is_tpu = _sys.modules["jax"].default_backend() == "tpu"
            except Exception:  # noqa: BLE001 — backend init failed: not TPU
                is_tpu = False
        return (_os.getpid(), is_tpu)

    def _send_results(self, task_id_b: bytes, result, num_returns: int,
                      return_id_bytes: List[bytes]):
        if self._async_dirty:
            # cross-connection ordering barrier: flush the owner's data
            # queue before the result (with any escaping refs) crosses
            # the task conn (see _send_async)
            self._async_dirty = False
            self._request(protocol.REQ_BARRIER)
        values = self._split_returns(result, num_returns)
        payloads = []
        for value, rid in zip(values, return_id_bytes):
            payloads.append(self._serialize_result(value, ObjectID(rid)))
        # _send_lock: actor thread pools (max_concurrency > 1) complete
        # calls concurrently; unsynchronized sends would interleave
        # Connection frames and corrupt the worker->driver protocol.
        with self._send_lock:
            # rtpu-lint: disable=L2 — _send_lock exists to serialize
            # result frames on task_conn (see comment above); leaf lock
            self.task_conn.send((protocol.MSG_DONE, task_id_b, payloads))

    def _serialize_result(self, value, rid: ObjectID):
        pickled, views, total = serialization.serialize(value)
        if (
            self.store is not None
            and total > serialization.inline_threshold()
        ):
            dst = None
            try:
                dst = self.store.create_object_with_pressure(rid, total)
                serialization.write_container(dst, pickled, views)
                # retain: the ref is adopted by the owner's tracking pin
                self.store.seal(rid, retain=True)
                return ("shm", rid.binary())
            except (ObjectStoreFullError, ValueError, OSError):
                if dst is not None:
                    # write/seal failed after allocation: abort the
                    # unsealed slot (invisible to getters, reclaimed
                    # only at close otherwise) before going inline
                    try:
                        self.store.release(rid)
                        self.store.delete(rid)
                    # rtpu-lint: disable=L4 — abort of a slot the store
                    # may have concurrently closed; inline fallback is
                    # the contract either way
                    except Exception:  # noqa: BLE001
                        pass
                # store full/closed even after spilling: go inline
        out = bytearray(total)
        serialization.write_container(memoryview(out), pickled, views)
        return ("inline", bytes(out))

    # ---- streaming generator production --------------------------------------

    def _drain_async_gen(self, agen):
        """Adapt an async generator to a sync iterator on a private loop."""
        import asyncio

        loop = asyncio.new_event_loop()
        try:
            while True:
                try:
                    yield loop.run_until_complete(agen.__anext__())
                except StopAsyncIteration:
                    return
        finally:
            loop.close()

    def _stream_report(self, task_id_b: bytes, seed: bytes, index: int,
                       rid_b: bytes, payload, is_end: bool):
        if self._async_dirty:
            # same cross-connection barrier as _send_results: a yielded
            # value carrying a just-submitted ref must not reach the
            # driver before its submission is applied
            self._async_dirty = False
            self._request(protocol.REQ_BARRIER)
        with self._send_lock:
            # rtpu-lint: disable=L2 — _send_lock serializes task_conn
            # frames against concurrent actor-thread results; leaf lock
            self.task_conn.send((protocol.MSG_STREAM_YIELD, task_id_b,
                                 seed, index, rid_b, payload, is_end))

    def _run_stream(self, task_id_b: bytes, result, stream_opts: dict):
        """Drive a ``num_returns="streaming"`` task: seal each yield under
        its deterministic index id and report it immediately, honoring the
        consumer-credit backpressure cap; finish with a _StreamEnd sentinel
        then a payload-less MSG_DONE for inflight bookkeeping."""
        import time

        seed = stream_opts["seed"]
        skip = int(stream_opts.get("skip", 0))
        cap = int(stream_opts.get("cap", 0))
        if hasattr(result, "__aiter__") and not hasattr(result, "__next__"):
            result = self._drain_async_gen(result)
        if not hasattr(result, "__next__"):
            raise TypeError(
                f"num_returns='streaming' requires the task to return a "
                f"generator/iterator, got {type(result).__name__}")
        index = 0
        for value in result:
            if index < skip:
                # replay after worker death: these indices were already
                # sealed (and survive in the owner/store); re-run the
                # generator for its state but do not re-report them
                index += 1
                continue
            rid = ObjectID(protocol.stream_index_id(seed, index))
            payload = self._serialize_result(value, rid)
            self._stream_report(task_id_b, seed, index, rid.binary(),
                                payload, False)
            index += 1
            while cap > 0:
                # producer backpressure: pause until the consumer is
                # within `cap` indices of us (instant probe + sleep keeps
                # SIGINT cancel windows off the data conn)
                _, consumed = self._request(
                    protocol.REQ_STREAM_CREDIT, seed, index)
                if index - consumed < cap:
                    break
                time.sleep(0.005)
        rid = ObjectID(protocol.stream_index_id(seed, index))
        payload = self._serialize_result(protocol._StreamEnd(index), rid)
        self._stream_report(task_id_b, seed, index, rid.binary(),
                            payload, True)
        with self._send_lock:
            # rtpu-lint: disable=L2 — _send_lock serializes task_conn
            # frames (see _send_results); leaf lock
            self.task_conn.send((protocol.MSG_DONE, task_id_b, []))

    def _execute_task_batch(self, tasks):
        """Execute a pipelined batch. The *dispatch* leg is what the batching
        amortizes (one driver→worker message for N tasks, the reference gets
        the same from leased-worker pipelining in NormalTaskSubmitter);
        results are flushed after every task so a finished result is never
        held hostage by a slow successor, and so the driver's completion
        log stays exact for crash recovery (requeue of never-started tasks).
        """
        from ray_tpu.core.config import config

        for entry in tasks:
            task_id_b, fn_id, args_payload, inline_values, return_ids = \
                entry[:5]
            runtime_env = entry[5] if len(entry) > 5 else None
            stream_opts = entry[6] if len(entry) > 6 else None
            if config.testing_kill_worker_prob > 0:
                # Chaos injection (reference: WorkerKillerActor,
                # python/ray/_private/test_utils.py:1597).
                import random

                if random.random() < config.testing_kill_worker_prob:
                    os._exit(1)
            from ray_tpu.core import fault_injection

            if fault_injection.enabled() and fault_injection.fire(
                    "task", fn_id.hex() if fn_id else "") == "exit":
                # deterministic 'task' fault site (env-armed: workers
                # inherit RTPU_FAULT_TASK from the driver)
                os._exit(1)
            self.current_task_id = TaskID(task_id_b)
            saved_env = None
            try:
                # inside the try: a failed package fetch/extract must fail
                # THIS task (and restore any partial state), not kill the
                # worker and drop the rest of the batch
                saved_env = self._apply_runtime_env(runtime_env)
                fn = self._functions[fn_id]
                args, kwargs = self._decode_args(args_payload, inline_values)
                result = fn(*args, **kwargs)
                if stream_opts is not None:
                    self._run_stream(task_id_b, result, stream_opts)
                else:
                    self._send_results(task_id_b, result, len(return_ids),
                                       return_ids)
            except BaseException as e:  # noqa: BLE001
                self._send_error(task_id_b, e)
            finally:
                _re_restore(saved_env)
                self.current_task_id = None

    def _apply_runtime_env(self, runtime_env):
        """env_vars + working_dir + py_modules; packages fetched from the
        core over REQ_PKG and cached under RTPU_PKG_DIR. Workers spawned
        FOR a pip env (their interpreter is the venv) skip re-activating
        it — and their env's modules persist across tasks."""
        from ray_tpu.core import runtime_env as _re

        if not runtime_env:
            return None
        return _re.apply(runtime_env, fetch=self._fetch_package,
                         own_pip_key=os.environ.get("RTPU_WORKER_PIP_KEY"))

    def _fetch_package(self, pkg_hash: str):
        _, data = self._request(protocol.REQ_PKG, pkg_hash)
        return data

    def register_package(self, pkg_hash: str, data: bytes) -> None:
        """Upload a package to the core (nested submissions from tasks)."""
        self._request(protocol.REQ_PKG_PUT, pkg_hash, data)

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        """Kill an actor from inside a task/actor (nested lifecycles:
        DAG-mode pipelines own their stage actors)."""
        self._request(protocol.REQ_KILL_ACTOR, actor_id.binary(),
                      no_restart)

    def free_objects(self, oid_bytes_list) -> int:
        """Eager deletion from inside a task/actor — forwarded to the
        owning core over the data conn (reference: internal_api.free is
        routed through the core worker to the owning raylet)."""
        _, n = self._request(protocol.REQ_FREE, list(oid_bytes_list))
        return n

    def prepare_runtime_env(self, runtime_env):
        from ray_tpu.core import runtime_env as _re

        return _re.prepare(self, runtime_env)

    def _send_error(self, task_id_b: bytes, exc: BaseException):
        with self._send_lock:
            # rtpu-lint: disable=L2 — _send_lock serializes frames on
            # task_conn against concurrent _send_results; leaf lock
            self.task_conn.send(
                (protocol.MSG_ERROR, task_id_b, self._error_payload(exc)))

    def _create_actor(self, msg):
        _, actor_id_b, cls_fn_id, args_payload, inline_values, opts = msg
        try:
            cls = self._functions[cls_fn_id]
            args, kwargs = self._decode_args(args_payload, inline_values)
            self.current_actor_id = ActorID(actor_id_b)
            # actor-scoped runtime_env: applied for the actor's lifetime
            # (the worker is dedicated to it)
            self._apply_runtime_env(opts.get("runtime_env"))
            if opts.get("trap_sigterm"):
                # TPU maintenance events arrive as SIGTERM; this actor
                # asked for them as a flag (train.preempted()) instead
                # of sudden death. Installed HERE because actor calls
                # run on pool threads when max_concurrency > 1 and only
                # the main thread (this recv loop) may set signal
                # handlers. Forceful teardown is unaffected: runtime
                # kills escalate to SIGKILL.
                import signal as _signal

                # rtpu-lint: disable=L6 — _create_actor runs on the
                # recv loop, which IS this worker process's main
                # thread (main() dispatches to it directly); pool
                # threads only ever run method bodies, never creation
                _signal.signal(
                    _signal.SIGTERM,
                    lambda signum, frame: self.preempted.set())
            instance = cls(*args, **kwargs)
            self._actors[actor_id_b] = instance
            mc = int(opts.get("max_concurrency") or 1)
            if mc > 1:
                from concurrent.futures import ThreadPoolExecutor

                self._actor_pools[actor_id_b] = ThreadPoolExecutor(
                    max_workers=mc, thread_name_prefix="actor-conc")
            cgs = opts.get("concurrency_groups") or {}
            if cgs:
                from concurrent.futures import ThreadPoolExecutor

                self._actor_group_pools[actor_id_b] = {
                    name: ThreadPoolExecutor(
                        max_workers=int(limit),
                        thread_name_prefix=f"actor-cg-{name}")
                    for name, limit in cgs.items()}
                self._actor_method_group[actor_id_b] = {
                    m: mo["concurrency_group"]
                    for m, mo in (opts.get("method_opts") or {}).items()
                    if mo.get("concurrency_group")}
                # the DEFAULT group gets its own executor too, so a long
                # ungrouped call can never block the recv loop from
                # feeding the named groups (reference: the default group
                # is just another concurrency group)
                if actor_id_b not in self._actor_pools:
                    self._actor_pools[actor_id_b] = ThreadPoolExecutor(
                        max_workers=mc, thread_name_prefix="actor-conc")
            if opts.get("has_async_methods"):
                import asyncio

                self._actor_loops[actor_id_b] = asyncio.new_event_loop()
            self.task_conn.send((protocol.MSG_ACTOR_READY, actor_id_b))
        except BaseException as e:  # noqa: BLE001
            err = TaskError(e, traceback.format_exc())
            self.task_conn.send(
                (protocol.MSG_ACTOR_ERROR, actor_id_b,
                 protocol.serialize_value(protocol.ErrorValue(err), store=None))
            )

    def _execute_actor_call(self, msg):
        (_, task_id_b, actor_id_b, method, args_payload, inline_values,
         return_ids) = msg[:7]
        stream_opts = msg[7] if len(msg) > 7 else None
        from ray_tpu.core import fault_injection

        kill_after = False
        if fault_injection.enabled():
            # deterministic 'actor_worker_kill' site (env-armed: the
            # worker inherits RTPU_FAULT_ACTOR_WORKER_KILL): 'exit' dies
            # before the method runs (a pure in-flight kill); 'exit_after'
            # runs the method and seals its results, then dies before the
            # DONE report flushes — the owner must adopt the sealed
            # results instead of re-executing the side effect
            act = fault_injection.fire(
                "actor_worker_kill",
                f"{ActorID(actor_id_b).hex()}:{method}")
            if act == "exit":
                os._exit(1)
            kill_after = act == "exit_after"
        self.current_task_id = TaskID(task_id_b)
        self.current_actor_id = ActorID(actor_id_b)
        try:
            instance = self._actors[actor_id_b]
            if method == "__rtpu_dag_start__":
                # compiled-DAG resident loop (ray_tpu/dag): not a method of
                # the user class — the worker hosts the loop thread
                fn = lambda in_d, out_d, m: self._dag_start(  # noqa: E731
                    instance, in_d, out_d, m)
            elif method == "__rtpu_dag_devinfo__":
                # compile-time placement probe: (pid, is_tpu). Device
                # edges require both stages in ONE process (jax Arrays
                # pass by reference), so the compiler compares pids.
                fn = lambda: self._dag_devinfo()  # noqa: E731
            else:
                fn = getattr(instance, method)
            args, kwargs = self._decode_args(args_payload, inline_values)
            result = fn(*args, **kwargs)
            if hasattr(result, "__await__"):
                import asyncio

                if actor_id_b in self._actor_pools:
                    loop = getattr(self._ctx_tls, "loop", None)
                    if loop is None:
                        loop = self._ctx_tls.loop = asyncio.new_event_loop()
                else:
                    loop = self._actor_loops.get(actor_id_b)
                    if loop is None:
                        loop = asyncio.new_event_loop()
                        self._actor_loops[actor_id_b] = loop
                result = loop.run_until_complete(result)
            if kill_after and stream_opts is None:
                # seal the results exactly as _send_results would, then
                # die without reporting: the sealed containers are the
                # evidence the owner's adoption path recovers from
                values = self._split_returns(result, len(return_ids))
                for value, rid in zip(values, return_ids):
                    self._serialize_result(value, ObjectID(rid))
                os._exit(1)
            if stream_opts is not None:
                self._run_stream(task_id_b, result, stream_opts)
            else:
                self._send_results(task_id_b, result, len(return_ids),
                                   return_ids)
        except BaseException as e:  # noqa: BLE001
            self._send_error(task_id_b, e)
        finally:
            self.current_task_id = None


def _re_restore(saved):
    from ray_tpu.core import runtime_env as _re

    _re.restore(saved)


def _prepare_args_local(core: WorkerCore, args: tuple, kwargs: dict):
    """Worker-side arg prep for nested submissions: top-level refs become
    _TopLevelDep markers; the driver re-resolves them (it owns all objects).
    Returns (args_payload, dep_oid_bytes_list)."""
    deps: List[bytes] = []

    def swap(v):
        if isinstance(v, ObjectRef):
            deps.append(v.binary())
            return _TopLevelDep(v.binary())
        return v

    args = tuple(swap(a) for a in args)
    kwargs = {k: swap(v) for k, v in kwargs.items()}
    payload, nested = protocol.serialize_args(args, kwargs, store=core.store)
    return payload, deps, [r.binary() for r in nested]


def main():
    from ray_tpu.core.config import config

    if config.fault_dump_after_s > 0:
        # Debug aid: dump all thread stacks after N seconds (hang triage).
        import faulthandler
        faulthandler.dump_traceback_later(
            config.fault_dump_after_s,
            file=open(f"/tmp/rtpu_worker_dump_{os.getpid()}.txt", "w"))
    address = os.environ["RTPU_ADDRESS"]
    authkey = bytes.fromhex(os.environ["RTPU_AUTH"])
    store_name = os.environ.get("RTPU_STORE", "")
    node_id = NodeID.from_hex(os.environ["RTPU_NODE_ID"])
    worker_id = WorkerID.from_hex(os.environ["RTPU_WORKER_ID"])

    task_conn = Client(address, authkey=authkey)
    task_conn.send(("hello", "task", worker_id.binary()))
    data_conn = Client(address, authkey=authkey)
    data_conn.send(("hello", "data", worker_id.binary()))

    store = ShmObjectStore.connect(store_name) if store_name else None
    core = WorkerCore(task_conn, data_conn, store, node_id, worker_id)
    runtime_context.set_core(core)

    # Cancellation SIGINT (ray.cancel force=False) must only interrupt task
    # execution; landing between tasks (e.g. blocked in recv) it would
    # otherwise kill the whole worker and its batched neighbours.
    import signal

    def _on_sigint(signum, frame):
        if core.current_task_id is not None:
            raise KeyboardInterrupt

    signal.signal(signal.SIGINT, _on_sigint)

    # Live profiling hook (reference role: the dashboard's py-spy stack
    # endpoint, reporter_agent.py): SIGUSR1 dumps every thread's Python
    # stack — with the CURRENT task id for attribution — to a well-known
    # file the driver collects. The handler runs between bytecodes, so a
    # busy worker can be profiled without stopping it.
    def _on_sigusr1(signum, frame):
        import sys as _sys
        import traceback as _tb

        from ray_tpu.core.proc_stats import stack_dump_path

        path = stack_dump_path(os.getpid())
        try:
            # tmp + rename: the collector polls the final path and must
            # never observe a partial write
            with open(path + ".tmp", "w") as f:
                f.write(f"pid {os.getpid()} task="
                        f"{core.current_task_id} actor="
                        f"{core.current_actor_id}\n")
                for tid, fr in _sys._current_frames().items():
                    f.write(f"\n--- thread {tid} ---\n")
                    f.write("".join(_tb.format_stack(fr)))
            os.replace(path + ".tmp", path)
        # rtpu-lint: disable=L4 — signal-handler profiling hook: a failed
        # stack dump (disk full, frames mutating underneath) must never
        # kill the worker it is inspecting
        except Exception:  # noqa: BLE001 — profiling must never kill
            pass

    signal.signal(signal.SIGUSR1, _on_sigusr1)
    try:
        core.run_loop()
    finally:
        if store is not None:
            store.close()


def zygote_main():
    """Pre-warmed worker template: fork new workers in milliseconds.

    Answers the reference's prestarted-worker pool
    (src/ray/raylet/worker_pool.h:344 PrestartWorkers, prestarted idle
    pool at :163): instead of keeping N idle full processes around, keep
    ONE warm template whose fork is ~10 ms — interpreter start and module
    imports (the ~300 ms that made actor launch slow) are paid once.
    Forked children share the template's pages copy-on-write, so a fleet
    of workers is also cheaper in RSS than N separate interpreters.

    Protocol (runtime -> zygote over stdin, replies on stdout)::

        {"wid": hex, "env": {...}, "out": path|null, "err": path|null}\\n
        -> "<pid>\\n"

    The zygote runs NO threads and holds NO locks at fork time; children
    reset signal handlers, apply their env, redirect stdio, and enter the
    normal ``main()``. EOF on stdin (runtime gone) exits the zygote;
    SIGCHLD is ignored so the kernel auto-reaps dead children.
    """
    import json
    import signal

    signal.signal(signal.SIGCHLD, signal.SIG_IGN)
    # warm everything main() touches before the first fork
    import ray_tpu.api  # noqa: F401
    from ray_tpu.core.config import config  # noqa: F401

    stdin = sys.stdin.buffer if hasattr(sys.stdin, "buffer") else sys.stdin
    stdout = sys.stdout
    print("ZYGOTE_READY", flush=True)
    for line in stdin:
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
        except ValueError:
            continue  # garbage on stdin: ignore, keep serving forks
        pid = os.fork()
        if pid == 0:
            # ---- child: become a normal worker ----
            try:
                signal.signal(signal.SIGCHLD, signal.SIG_DFL)
                os.environ.update(req.get("env") or {})
                os.environ["RTPU_WORKER_ID"] = req["wid"]
                # same non-TPU sanitization the cold-spawn path applies
                # AFTER merging extra_env: zygote children are always
                # plain CPU workers, so a user runtime_env must not drag
                # in TPU/PJRT registration (shared rules: worker_env.py)
                from ray_tpu.core.worker_env import sanitize_cpu_worker_env

                sanitize_cpu_worker_env(os.environ)
                devnull = os.open(os.devnull, os.O_RDONLY)
                os.dup2(devnull, 0)
                os.close(devnull)
                for path, fd in ((req.get("err"), 2), (req.get("out"), 1)):
                    if path:
                        f = os.open(path,
                                    os.O_WRONLY | os.O_CREAT | os.O_APPEND)
                        os.dup2(f, fd)
                        os.close(f)
                if not req.get("out"):
                    # NEVER leave fd 1 on the zygote's protocol pipe — a
                    # worker print would corrupt fork replies. No log
                    # path -> route stdout alongside stderr.
                    os.dup2(2, 1)
                main()
            except BaseException:  # noqa: BLE001
                traceback.print_exc()
            finally:
                os._exit(0)
        stdout.write(f"{pid}\n")
        stdout.flush()


if __name__ == "__main__":
    main()
