"""Control-plane wire protocol between driver and workers.

Messages are tuples ``(tag, ...)`` sent over ``multiprocessing.connection``
(pickle framing). This is the single-node analogue of the reference's gRPC
services: the task conn carries what ``CoreWorkerService.PushTask``
(src/ray/protobuf/core_worker.proto:444) carries, and the data conn carries
the worker→owner requests that in the reference go over dedicated RPCs
(get/put/submit from inside tasks).

Values travel as *payload descriptors*::

    ("inline", bytes)         - serialization container inlined in the message
    ("shm", oid_bytes)        - stored in the shared-memory object store

Args additionally carry ``inline_values``: {oid_bytes: payload} for resolved
dependencies whose values live only in the owner's memory store.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import serialization
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_ref import ObjectRef, begin_ref_collection, end_ref_collection
from ray_tpu.exceptions import ObjectStoreFullError

# driver -> worker (task conn)
MSG_REGISTER_FN = "reg_fn"         # (MSG_REGISTER_FN, fn_id, pickled_fn)
MSG_CREATE_ACTOR = "create_actor"  # (.., actor_id_b, cls_fn_id, args_payload, inline_values, opts)
MSG_ACTOR_CALL = "actor_call"      # (.., task_id_b, actor_id_b, method, args_payload, inline_values, return_id_bytes)
MSG_TASK_BATCH = "task_batch"      # (MSG_TASK_BATCH, [(task_id_b, fn_id, args_payload, inline_values, return_ids, runtime_env|None, stream_opts|None), ...])
MSG_SHUTDOWN = "shutdown"

# worker -> driver (task conn)
MSG_READY = "ready"                # (MSG_READY, pid)
MSG_DONE = "done"                  # (MSG_DONE, task_id_b, [payload, ...])
MSG_ERROR = "error"                # (MSG_ERROR, task_id_b, pickled_exc_payload)
MSG_ACTOR_READY = "actor_ready"    # (.., actor_id_b)
MSG_ACTOR_ERROR = "actor_error"    # (.., actor_id_b, pickled_exc_payload)
MSG_STREAM_YIELD = "stream_yield"  # (.., task_id_b, seed, index, rid_b, payload, is_end): one streamed return sealed

# worker -> driver (data conn, request/response)
REQ_GET = "get"                    # (REQ_GET, [oid_bytes], timeout_ms, cur_task_id_b) -> ("ok", {oid: payload}) | ("err", payload)
REQ_PUT_META = "put_meta"          # (REQ_PUT_META, oid_bytes, payload_or_none) -> ("ok",)
REQ_SUBMIT = "submit"              # (REQ_SUBMIT, fn_id, pickled_fn_or_none, args_payload, inline_values, n_returns, ref_oids) -> ("ok", [oid_bytes])
REQ_ACTOR_CALL = "actor_call"      # worker-side actor handle call -> ("ok", [oid_bytes])
REQ_WAIT = "wait"                  # (REQ_WAIT, [oid_bytes], num_returns, timeout_s) -> ("ok", ready, rest)
REQ_KV = "kv"                      # (REQ_KV, op, key, value) -> ("ok", value)
REQ_CREATE_ACTOR = "create_actor_req"  # (.., fn_id, pickled_cls_or_none, args_payload, deps, opts) -> ("ok", actor_id_bytes)
REQ_PG = "pg"                      # (REQ_PG, op, *args) -> ("ok", result); op in create/remove/ready_ref/wait/chips/table
REQ_GET_ACTOR = "get_actor"        # (REQ_GET_ACTOR, name) -> ("ok", handle_payload)
REQ_CANCEL = "cancel"              # (REQ_CANCEL, oid_bytes, force) -> ("ok",)
REQ_PKG = "pkg"                    # (REQ_PKG, hash_str) -> ("ok", bytes_or_none)
REQ_PKG_PUT = "pkg_put"            # (REQ_PKG_PUT, hash_str, bytes) -> ("ok", None)
REQ_NEED_SPACE = "need_space"      # (REQ_NEED_SPACE, nbytes) -> ("ok", freed_bool)
REQ_FREE = "free_objs"             # (REQ_FREE, [oid_bytes]) -> ("ok", count_freed)
REQ_KILL_ACTOR = "kill_actor_req"  # (REQ_KILL_ACTOR, actor_id_bytes, no_restart) -> ("ok",)
REQ_STREAM_NEXT = "stream_next"    # (REQ_STREAM_NEXT, seed, index, timeout_ms, owner) -> ("ref", rid_b) | ("end", count) | ("pending",) | ("err", payload)
REQ_STREAM_CREDIT = "stream_credit"  # (REQ_STREAM_CREDIT, seed, produced) -> ("ok", consumed): producer backpressure probe
REQ_PUBSUB = "pubsub"              # (REQ_PUBSUB, op, channel, arg, timeout) -> ("ok", result); op in publish/poll (GCS channel semantics)
# well-known pubsub channels: "freed" (eager-free tombstone broadcast),
# "node_deaths" (GCS health monitor), "actor_state" (actor-restart FSM
# transitions: {"actor_id", "state": ALIVE|RESTARTING|DEAD,
# "restarts_left", "name", ...} — published by the owning runtime on
# worker-death restarts and by the GCS on cross-node restarts)

# fire-and-forget variants (NO reply — the worker pre-generates the ids,
# so the owner's round trip leaves the submission hot path; errors land
# in the return-object entries and surface at get(), like the
# reference's async task submission through the core worker):
REQ_PUT_META_ASYNC = "put_meta_async"      # (.., oid_bytes, payload_or_none)
REQ_SUBMIT_ASYNC = "submit_async"          # (.., fn_id, pickled_fn_or_none, args_payload, inline_values, return_ids, options)
REQ_ACTOR_CALL_ASYNC = "actor_call_async"  # (.., actor_id_b, method, args_payload, extra, return_ids)
# ``extra`` on REQ_ACTOR_CALL / REQ_ACTOR_CALL_ASYNC is a dict of optional
# keys: "__deps" (top-level dep oid bytes), "__stream" (streaming call),
# "__parent" (submitting task id), "__opts" (per-call overrides —
# max_task_retries / retry_exceptions — resolved against the actor's
# class-level opts at enqueue).
REQ_STREAM_CONSUMED_ASYNC = "stream_consumed_async"  # (.., seed, index, owner): consumer advanced past index

REQ_BARRIER = "barrier"  # (REQ_BARRIER,) -> ("ok",): all earlier async sends applied

NO_REPLY = ("__no_reply__",)  # sentinel: data server sends nothing back

class ErrorValue:
    """Marker wrapping an exception stored as an object's value.

    Distinguishes "the task failed with E" from "the task returned the
    exception object E" (the reference uses RayTaskError subclassing for the
    same purpose). ``raise_if_error`` re-raises at get().
    """

    __slots__ = ("error",)

    def __init__(self, error: BaseException):
        self.error = error

    def __reduce__(self):
        return (ErrorValue, (self.error,))


def raise_if_error(value):
    if isinstance(value, ErrorValue):
        raise value.error
    return value


class _TopLevelDep:
    """Sentinel replacing a resolved top-level ObjectRef arg in transit."""

    __slots__ = ("oid_bytes",)

    def __init__(self, oid_bytes: bytes):
        self.oid_bytes = oid_bytes

    def __reduce__(self):
        return (_TopLevelDep, (self.oid_bytes,))


class _StreamEnd:
    """End-of-stream sentinel sealed at the index one past the final yield
    of a ``num_returns="streaming"`` task (the reference stores
    ``ObjectRefStreamEndOfStreamError`` the same way). ``count`` is the
    number of values the generator produced, so a consumer that attaches
    late still learns the stream length."""

    __slots__ = ("count",)

    def __init__(self, count: int):
        self.count = count

    def __reduce__(self):
        return (_StreamEnd, (self.count,))


def stream_index_id(seed: bytes, index: int) -> bytes:
    """Deterministic per-index object id for a streaming return.

    Derived from the submit-time seed so the owner, the worker, and a
    replayed generator after worker death all agree on the id of yield
    ``index`` without a round trip (the reference derives dynamic return
    ids from the task id + index the same way)."""
    import hashlib

    return hashlib.blake2b(
        seed + index.to_bytes(8, "little"), digest_size=16).digest()


Payload = Tuple[str, bytes]


def serialize_args(
    args: tuple, kwargs: dict, store=None
) -> Tuple[Payload, List[ObjectRef]]:
    """Serialize an (args, kwargs) pair, collecting nested ObjectRefs.

    Large payloads go to the shm ``store`` when provided.
    Returns (payload_descriptor, collected_refs).
    """
    refs = begin_ref_collection()
    try:
        pickled, views, total = serialization.serialize((args, kwargs))
    finally:
        end_ref_collection()
    payload = _store_or_inline(pickled, views, total, store)
    return payload, refs


def serialize_value(value: Any, store=None) -> Payload:
    pickled, views, total = serialization.serialize(value)
    return _store_or_inline(pickled, views, total, store)


def _store_or_inline(pickled, views, total, store) -> Payload:
    if store is not None and total > serialization.inline_threshold():
        oid = ObjectID.from_random()
        dst = None
        try:
            # invokes the store's need_space hook (spilling) when full;
            # retain-seal hands the creator ref to the owner's tracking pin
            dst = store.create_object_with_pressure(oid, total)
            serialization.write_container(dst, pickled, views)
            store.seal(oid, retain=True)
            return ("shm", oid.binary())
        except (ObjectStoreFullError, ValueError, OSError):
            if dst is not None:
                # allocation succeeded but the write/seal window failed:
                # an unsealed object is invisible to getters and only
                # reclaimed at store close — abort it (drop the creator
                # ref, then free) before falling back to inline
                try:
                    store.release(oid)
                    store.delete(oid)
                # rtpu-lint: disable=L4 — abort of a slot the store may
                # have concurrently closed under us; inline fallback is
                # the contract either way
                except Exception:  # noqa: BLE001
                    pass
            # store full/closed even after spilling: fall back to inline
    out = bytearray(total)
    serialization.write_container(memoryview(out), pickled, views)
    return ("inline", bytes(out))


def spilled_unpack(path_and_size) -> Any:
    """Decode a spilled payload (reference: external_storage restore,
    python/ray/_private/external_storage.py:451). Local files hold the
    same container format as a shm object and are mmap'd so large
    tensors stay file-backed until touched; fsspec URIs (s3://...) read
    through the filesystem driver.

    A missing or undecodable spill file means the value is LOST (disk
    reclaimed, torn write, bucket eviction) — that surfaces as
    ObjectLostError, the same signal as a shm-store miss, so the owner
    can attempt lineage reconstruction of the producing task."""
    from ray_tpu.core import external_storage as _ext
    from ray_tpu.exceptions import ObjectLostError

    path = path_and_size[0] if isinstance(path_and_size, tuple) else path_and_size
    try:
        buf = _ext.read_buffer(path)
    except Exception as e:  # noqa: BLE001 — missing file / backend error
        raise ObjectLostError(
            f"spill file {path} is unreadable ({type(e).__name__}: {e})"
        ) from None
    try:
        return serialization.unpack(memoryview(buf))
    except ObjectLostError:
        raise
    except Exception as e:  # noqa: BLE001 — truncated/overwritten file
        raise ObjectLostError(
            f"spill file {path} is corrupt ({type(e).__name__}: {e})"
        ) from None


class _Pin:
    """Keeps one shm object pinned until every wrapped buffer is collected."""

    __slots__ = ("_store", "_oid", "count")

    def __init__(self, store, oid, count):
        self._store = store
        self._oid = oid
        self.count = count

    def decref(self):
        self.count -= 1
        if self.count == 0:
            try:
                self._store.release(self._oid)
            # rtpu-lint: disable=L4 — runs from zero-copy buffer
            # finalizers, possibly during interpreter teardown with the
            # store already closed; a pin release must never raise there
            except Exception:  # noqa: BLE001
                pass


def shm_unpack(store, oid: ObjectID, timeout_ms: int = 0) -> Any:
    """Fetch + deserialize an object from the shm store with zero-copy
    buffers that keep the object pinned for the lifetime of the deserialized
    arrays (the reference pins plasma objects-in-use per worker the same way:
    src/ray/core_worker/store_provider/plasma_store_provider.h).

    Callers only reach this once the owner reports the object sealed, so a
    miss means it was LRU-evicted -> ObjectLostError (the reference raises
    the same). The owning Runtime catches that signal for task-produced
    objects and resubmits the producing task from its lineage table (up to
    config.max_reconstructions attempts, budgeted by
    config.lineage_max_bytes); put/freed/lineage-evicted objects stay
    lost and the error propagates to the caller.
    """
    import ctypes
    import weakref

    from ray_tpu.exceptions import ObjectLostError, ObjectTimeoutError

    try:
        mv = store.get(oid, timeout_ms=timeout_ms)
    except ObjectTimeoutError:
        raise ObjectLostError(
            f"object {oid} was evicted from the object store before it was "
            f"read (store under memory pressure)"
        ) from None
    wrapped_count = 0
    pin_box = []

    def wrap(chunk: memoryview):
        nonlocal wrapped_count
        # ctypes arrays are weakref-able buffer-protocol objects; a numpy
        # array reconstructed over one keeps it (and thus the pin) alive.
        blk = (ctypes.c_uint8 * chunk.nbytes).from_buffer(chunk)
        wrapped_count += 1
        pin_box.append(blk)
        return blk

    try:
        value = serialization.unpack(mv, wrap_buffer=wrap)
    except Exception:
        store.release(oid)
        raise
    if wrapped_count == 0:
        store.release(oid)
    else:
        pin = _Pin(store, oid, wrapped_count)
        for blk in pin_box:
            weakref.finalize(blk, pin.decref)
    return value


def deserialize_payload(payload: Payload, store=None) -> Any:
    """Decode a payload descriptor (zero-copy + pinned for shm payloads)."""
    kind, data = payload
    if kind == "inline":
        return serialization.unpack(data)
    if kind == "shm":
        return shm_unpack(store, ObjectID(data))
    if kind == "spilled":
        return spilled_unpack(data)
    raise ValueError(f"unknown payload kind {kind!r}")


def schema() -> str:
    """The complete wire schema, assembled from this module's message
    constants (driver↔worker link) and the RPC servers' op handlers
    (node/GCS planes) — the single-language analogue of the reference's
    .proto files (`python -m ray_tpu.core.protocol` prints it)."""
    import inspect
    import re

    lines = ["ray_tpu wire schema", "=" * 60, "",
             "driver <-> worker (framed pickle over pipes)", "-" * 60]
    src = inspect.getsource(inspect.getmodule(schema))
    for m in re.finditer(
            r'^(MSG_|REQ_)(\w+) = "([^"]+)"[ \t]*(?:#[ \t]*(.*))?$',
            src, re.M):
        kind, name, tag, doc = m.groups()
        lines.append(f"  {kind}{name:<18} {tag!r:<22} {doc or ''}".rstrip())

    for title, cls_path in (
            ("node server RPC ops", "ray_tpu.core.cluster.node_server"),
            ("GCS server RPC ops", "ray_tpu.core.cluster.gcs")):
        lines += ["", title + " (authkey'd framed-pickle TCP)", "-" * 60]
        import importlib

        mod = importlib.import_module(cls_path)
        for cls in vars(mod).values():
            if not inspect.isclass(cls):
                continue
            ops = [(n[len("_op_"):], f) for n, f in vars(cls).items()
                   if n.startswith("_op_")]
            for op, f in sorted(ops):
                doc = (inspect.getdoc(f) or "").split("\n")[0]
                sig = str(inspect.signature(f)).replace("(self, ", "(")
                lines.append(f"  {op:<22} {sig:<40} {doc}".rstrip())
    return "\n".join(lines)


if __name__ == "__main__":
    print(schema())
