"""@remote functions (reference: python/ray/remote_function.py:40)."""

from __future__ import annotations

import functools
from typing import Any, List, Optional, Union

from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core import runtime_context


class RemoteFunction:
    """A function decorated with ``@ray_tpu.remote``.

    Call with ``.remote(*args)`` → ObjectRef(s); ``.options(...)`` overrides
    per-call options (num_returns, num_cpus, resources, scheduling_strategy).
    """

    def __init__(self, fn, default_options: Optional[dict] = None):
        self._fn = fn
        self._default_options = dict(default_options or {})
        self._fn_id = None  # lazily registered per runtime
        self._fn_id_core = None
        self._pickled = None
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._fn.__name__!r} cannot be called directly; "
            f"use {self._fn.__name__}.remote()."
        )

    def options(self, **opts) -> "_OptionWrapper":
        merged = dict(self._default_options)
        merged.update(opts)
        return _OptionWrapper(self, merged)

    def remote(self, *args, **kwargs) -> Union[ObjectRef, List[ObjectRef]]:
        return self._remote(args, kwargs, self._default_options)

    def _remote(self, args, kwargs, options) -> Union[ObjectRef, List[ObjectRef]]:
        core = runtime_context.get_core()
        num_returns = options.get("num_returns", 1)
        streaming = num_returns == "streaming"
        opts = {k: v for k, v in options.items() if k != "num_returns"}
        if opts.get("runtime_env") and hasattr(core, "prepare_runtime_env"):
            # package working_dir/py_modules paths into hash references
            opts["runtime_env"] = core.prepare_runtime_env(
                opts["runtime_env"])
        if hasattr(core, "submit_task") and hasattr(core, "register_function"):
            # driver path
            if self._fn_id is None or self._fn_id_core is not core:
                self._fn_id = core.register_function(self._fn)
                self._fn_id_core = core
            refs = core.submit_task(self._fn_id, args, kwargs,
                                    num_returns=num_returns, options=opts)
        else:
            # worker path: ship the pickled function on first use
            if self._pickled is None:
                from ray_tpu.core import serialization
                import hashlib

                self._pickled = serialization.pack(self._fn)
                self._fn_id = hashlib.blake2b(
                    self._pickled, digest_size=16
                ).digest()
            refs = core.submit_task(self._fn_id, self._pickled, args, kwargs,
                                    num_returns, opts)
        if streaming:
            return _make_generator(core, refs[0].binary())
        return refs[0] if num_returns == 1 else refs

    @property
    def underlying_function(self):
        return self._fn

    def __reduce__(self):
        # Exclude runtime-bound state (fn_id cache holds the Runtime, which
        # is not picklable) so remote functions can be captured by other
        # remote functions' closures.
        return (_rebuild, (self._fn, self._default_options))


def _rebuild(fn, default_options):
    return RemoteFunction(fn, default_options)


def _make_generator(core, seed: bytes):
    """Wrap a streaming submission's seed id in an ObjectRefGenerator,
    capturing the producing node address when the core is cluster-aware
    (so the generator keeps working after being pickled cross-node)."""
    from ray_tpu.core.object_ref import ObjectRefGenerator

    owner_of = getattr(core, "stream_owner", None)
    owner = owner_of(seed) if callable(owner_of) else None
    return ObjectRefGenerator(seed, core=core, owner=owner)


class _OptionWrapper:
    def __init__(self, rf: RemoteFunction, options: dict):
        self._rf = rf
        self._options = options

    def remote(self, *args, **kwargs):
        return self._rf._remote(args, kwargs, self._options)
