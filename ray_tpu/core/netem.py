"""Deterministic network-emulation (netem) shim for the RPC substrate.

``cluster/rpc.py`` weaves this module into the client send/recv path and
the server dispatch loop, so per-edge wire faults — partitions, message
loss, delay, duplication, reorder, slow links — can be injected into the
REAL transport code paths (retry whitelist, ``maybe_applied`` tagging,
nonce dedup, HA ride-through) without monkeypatching. It is the
wire-level sibling of ``core/fault_injection.py`` (which models crash
and drop at *application* sites) and of the interleaving fuzzer
(``tools/race``, which perturbs thread schedules): same arming style,
same seeded-replay contract.

Rule grammar
------------
``RTPU_NETEM=<seed>:<rule>[;<rule>...]`` where each rule is::

    <src> -> <dst> = <kind>[,key=value...]     (one direction)
    <src> <-> <dst> = <kind>[,key=value...]    (both directions)

``src``/``dst`` select edge endpoints: ``*`` (any), a role tag
(``driver`` / ``gcs`` / ``node``), a ``host:port`` address, or a bare
port. Roles come from :func:`set_identity` (each cluster process
declares what it is) and :func:`tag_peer` (``HaGcsClient`` tags its
target ``gcs``); an untagged peer defaults to ``node`` — the only
servers in a cluster are the GCS and node servers, and the driver is
never a destination (nothing dials it).

Policy kinds (``KINDS``):

- ``drop`` — the send fails with :class:`NetemFault` *before* any bytes
  move (the transport sees an unsent message and retries safely);
- ``partition`` / ``blackhole`` — same mechanics as ``drop``; by
  convention armed unlimited (``times`` defaults to -1) to model a
  severed edge until :func:`clear`/``heal`` removes the rule;
- ``delay`` — sleep ``ms`` (+ ``jitter`` ms scaled by a seeded draw);
- ``reorder`` — seeded hold-back within an ``ms`` window, letting a
  concurrent message on another connection overtake this one;
- ``bw`` — sleep ``size_hint / kbps`` to model a slow link;
- ``dup`` — the request is sent TWICE on the same connection (the
  server applies it twice back-to-back; nonce dedup / idempotent ops
  must make the second apply a no-op);
- ``lost_reply`` — the request is sent, then the reply is discarded by
  raising :class:`NetemFault` before the receive (the transport sees
  ``sent=True``: only whitelist-idempotent ops may retry, and the
  server-side dedup must absorb the retry).

Common params: ``p=<prob>`` (fire probability, seeded draw; default 1),
``times=<n>`` (stop after n matches; -1 = unlimited, the default),
``at=server`` (apply in the receiving server's dispatch loop instead of
the sending client — server-marked rules never fire client-side, so a
rule is applied exactly once per message).

Determinism
-----------
Every probabilistic decision draws from a per-rule
``random.Random(f"{seed}\\x00{src}->{dst}={kind}")`` stream, so the
delivery schedule is a pure function of the seed, the rule table, and
each rule's own sequence of matches — never of wall-clock timing. The
recorded schedule (:func:`schedule`) is asserted identical across runs
of the same seeded workload in ``tests/test_netem.py``; export the
printed seed back through ``RTPU_NETEM`` to replay a failure, exactly
like ``RTPU_INTERLEAVE``.

Partitions are usually armed programmatically via the cluster fixture's
``partition(a, b, oneway=...)`` / ``heal()`` helpers, which deliver
rules into node/GCS processes over unaffected edges with the ``netem``
control RPC (:func:`control`).
"""

from __future__ import annotations

import os
import random
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.util.debug_lock import make_lock

ENV = "RTPU_NETEM"

#: every policy kind the shim can arm. rtpu-lint L3 parses this tuple
#: and requires each kind to be armed by at least one test.
KINDS = ("drop", "delay", "dup", "reorder", "bw", "partition",
         "blackhole", "lost_reply")

#: kinds that sever the edge outright (raise before any bytes move)
_FAULT_KINDS = ("drop", "partition", "blackhole")

#: schedule recording cap — enough for any seeded test workload while
#: bounding memory if a long-lived process stays armed
_SCHEDULE_CAP = 100_000


class NetemFault(OSError):
    """Injected wire fault. Subclasses :class:`OSError` so the
    transport's existing failure handling (pool teardown, retry
    whitelist, ``maybe_applied`` tagging) treats it exactly like a real
    socket error — the whole point is to exercise those paths."""


class _Rule:
    __slots__ = ("src", "dst", "kind", "params", "times", "rng", "env",
                 "rule_id")

    def __init__(self, src: str, dst: str, kind: str,
                 params: Optional[Dict[str, Any]], seed: int,
                 rule_id: int, env: bool = False):
        if kind not in KINDS:
            raise ValueError(
                f"unknown netem policy kind {kind!r}; kinds: {KINDS}")
        self.src = src
        self.dst = dst
        self.kind = kind
        self.params = dict(params or {})
        self.times = int(self.params.pop("times", -1))
        self.env = env
        self.rule_id = rule_id
        # per-rule deterministic stream: decisions are a pure function
        # of (seed, rule spec, this rule's own match counter)
        self.rng = random.Random(f"{seed}\x00{src}->{dst}={kind}")

    def spec(self) -> str:
        return f"{self.src}->{self.dst}={self.kind}"


_lock = make_lock("netem._lock")
_rules: List[_Rule] = []
_armed = False          # lock-free fast-path guard, like fault_injection
_seed = 0
_next_rule_id = 0
_identity_role = "?"
_identity_addr: Optional[str] = None
_peer_roles: Dict[str, str] = {}
_schedule: List[Tuple[str, str, str]] = []


def _addr_str(addr: Any) -> str:
    if isinstance(addr, str):
        return addr
    return f"{addr[0]}:{addr[1]}"


def enabled() -> bool:
    """Cheap guard for the transport hot path: one global load."""
    return _armed


def set_identity(role: str, address: Any = None) -> None:
    """Declare what this process is (``driver``/``gcs``/``node``) and,
    for servers, its listen address — rule ``src`` selectors match
    against these. Last caller wins (in-process multi-server tests)."""
    global _identity_role, _identity_addr
    with _lock:
        _identity_role = role
        _identity_addr = _addr_str(address) if address else None


def tag_peer(address: Any, role: str) -> None:
    """Record a peer address's role so ``dst`` selectors can match by
    role (``HaGcsClient`` tags its target ``gcs``; untagged peers
    default to ``node``)."""
    with _lock:
        _peer_roles[_addr_str(address)] = role


def _match(sel: str, role: Optional[str], addr: Optional[str]) -> bool:
    if sel == "*" or sel == role:
        return True
    if addr is None:
        return False
    return sel == addr or (sel.isdigit() and addr.endswith(":" + sel))


def _record(rule: _Rule, peer: str, decision: str) -> None:
    # caller holds _lock
    if len(_schedule) < _SCHEDULE_CAP:
        _schedule.append((f"{_identity_role}->{peer}", rule.spec(),
                          decision))


def arm(seed: int, rules: Optional[List[dict]] = None) -> None:
    """Reset the shim and arm a fresh rule table under ``seed``. Rule
    dicts carry ``src``/``dst``/``kind``/``params`` (the shape
    :func:`parse_spec` produces)."""
    global _seed, _next_rule_id, _armed
    with _lock:
        _seed = int(seed)
        _rules[:] = []
        _schedule[:] = []
        _next_rule_id = 0
        _armed = False
    for r in rules or []:
        add_rule(r["src"], r["dst"], r["kind"], dict(r.get("params") or {}))


def add_rule(src: str, dst: str, kind: str,
             params: Optional[Dict[str, Any]] = None,
             env: bool = False) -> int:
    """Append one rule; returns its id. First matching fault rule wins;
    shaping rules (delay/reorder/bw) and dup/lost_reply compose."""
    global _next_rule_id, _armed
    with _lock:
        rid = _next_rule_id
        _next_rule_id += 1
        _rules.append(_Rule(src, dst, kind, params, _seed, rid, env=env))
        _armed = True
        return rid


def clear(src: Optional[str] = None, dst: Optional[str] = None,
          kind: Optional[str] = None) -> int:
    """Remove rules matching every given selector (all rules with no
    arguments — full disarm). Returns the number removed."""
    global _armed
    with _lock:
        keep = [r for r in _rules
                if not ((src is None or r.src == src)
                        and (dst is None or r.dst == dst)
                        and (kind is None or r.kind == kind))]
        removed = len(_rules) - len(keep)
        _rules[:] = keep
        _armed = bool(_rules)
        return removed


def _size_hint(msg: Any) -> int:
    """Cheap top-level payload size estimate for ``bw`` shaping: framed
    overhead plus any bytes/str elements one or two levels deep (task
    payloads and object chunks live there)."""
    n = 64
    if isinstance(msg, tuple):
        for x in msg:
            if isinstance(x, (bytes, bytearray, str)):
                n += len(x)
            elif isinstance(x, (list, tuple)):
                for y in x:
                    if isinstance(y, (bytes, bytearray, str)):
                        n += len(y)
    return n


def plan_send(dst_addr: Any, msg: Any) -> Optional[str]:
    """Client-side hook, called before EACH request send — including the
    transport's built-in same-address retry, so a partition blocks the
    retry too. Sleeps for shaping rules, raises :class:`NetemFault` for
    fault rules, and returns ``"dup"`` / ``"lost_reply"`` for the two
    policies the transport must cooperate on."""
    dst = _addr_str(dst_addr)
    sleep_s = 0.0
    verdict: Optional[str] = None
    fault: Optional[str] = None
    with _lock:
        role = _peer_roles.get(dst, "node")
        for r in _rules:
            if r.times == 0 or r.params.get("at") == "server":
                continue
            if not _match(r.src, _identity_role, _identity_addr):
                continue
            if not _match(r.dst, role, dst):
                continue
            p = float(r.params.get("p", 1.0))
            if p < 1.0 and r.rng.random() >= p:
                _record(r, dst, "pass")
                continue
            if r.times > 0:
                r.times -= 1
            if r.kind in _FAULT_KINDS:
                fault = r.kind
                _record(r, dst, r.kind)
                break
            if r.kind == "delay":
                d = float(r.params.get("ms", 1.0)) / 1000.0
                d += (float(r.params.get("jitter", 0.0)) / 1000.0
                      * r.rng.random())
                sleep_s += d
                _record(r, dst, f"delay:{d * 1000:.3f}ms")
            elif r.kind == "reorder":
                d = (float(r.params.get("ms", 5.0)) / 1000.0
                     * r.rng.random())
                sleep_s += d
                _record(r, dst, f"reorder:{d * 1000:.3f}ms")
            elif r.kind == "bw":
                kbps = float(r.params.get("kbps", 1024.0))
                d = _size_hint(msg) / (kbps * 1024.0)
                sleep_s += d
                _record(r, dst, f"bw:{d * 1000:.3f}ms")
            elif r.kind == "dup":
                verdict = "dup"
                _record(r, dst, "dup")
            elif r.kind == "lost_reply":
                verdict = "lost_reply"
                _record(r, dst, "lost_reply")
    if sleep_s > 0.0:
        time.sleep(sleep_s)
    if fault is not None:
        raise NetemFault(
            f"netem {fault}: edge {_identity_role} -> {dst} is severed")
    return verdict


def plan_dispatch() -> None:
    """Server-side hook, called as a request is dequeued and before the
    handler runs. Applies only rules marked ``at=server`` whose ``dst``
    matches this process: ``delay`` sleeps inside the dispatch loop;
    fault kinds sever the connection mid-exchange (the client observes
    a sent-but-unanswered request — the ``maybe_applied`` path)."""
    sleep_s = 0.0
    fault: Optional[str] = None
    with _lock:
        for r in _rules:
            if r.times == 0 or r.params.get("at") != "server":
                continue
            if not _match(r.dst, _identity_role, _identity_addr):
                continue
            p = float(r.params.get("p", 1.0))
            if p < 1.0 and r.rng.random() >= p:
                _record(r, _identity_role, "pass")
                continue
            if r.times > 0:
                r.times -= 1
            if r.kind in _FAULT_KINDS:
                fault = r.kind
                _record(r, _identity_role, "inbound:" + r.kind)
                break
            if r.kind == "delay":
                d = float(r.params.get("ms", 1.0)) / 1000.0
                d += (float(r.params.get("jitter", 0.0)) / 1000.0
                      * r.rng.random())
                sleep_s += d
                _record(r, _identity_role, f"inbound-delay:{d * 1000:.3f}ms")
    if sleep_s > 0.0:
        time.sleep(sleep_s)
    if fault is not None:
        raise NetemFault(
            f"netem inbound {fault} at {_identity_role}: "
            f"request discarded before dispatch")


def schedule() -> List[Tuple[str, str, str]]:
    """The recorded delivery schedule: ordered ``(edge, rule-spec,
    decision)`` triples. Identical across runs of the same seeded
    workload — the replay contract the determinism test asserts."""
    with _lock:
        return list(_schedule)


def rules() -> List[str]:
    """Human-readable armed rule table (debugging/fixture asserts)."""
    with _lock:
        return [f"{r.spec()} params={r.params} times={r.times}"
                for r in _rules]


def parse_spec(raw: str) -> Tuple[int, List[dict]]:
    """Parse ``<seed>:<rule>[;<rule>...]`` (grammar in the module
    docstring) into ``(seed, rule dicts)``. Raises ``ValueError`` on a
    malformed spec — a silently ignored chaos plan is worse than a
    crash."""
    raw = (raw or "").strip()
    if not raw:
        raise ValueError("empty netem spec")
    head, _, tail = raw.partition(":")
    seed = int(head)
    out: List[dict] = []
    for item in tail.split(";"):
        item = item.strip()
        if not item:
            continue
        edge, _, policy = item.partition("=")
        if not policy:
            raise ValueError(f"netem rule {item!r} has no '=<kind>' policy")
        two_way = "<->" in edge
        src, _, dst = edge.partition("<->" if two_way else "->")
        src = src.strip() or "*"
        dst = dst.strip() or "*"
        parts = policy.split(",")
        kind = parts[0].strip()
        if kind not in KINDS:
            raise ValueError(
                f"unknown netem policy kind {kind!r}; kinds: {KINDS}")
        params: Dict[str, str] = {}
        for kv in parts[1:]:
            k, _, v = kv.partition("=")
            params[k.strip()] = v.strip()
        out.append({"src": src, "dst": dst, "kind": kind, "params": params})
        if two_way:
            out.append({"src": dst, "dst": src, "kind": kind,
                        "params": dict(params)})
    return seed, out


def load_env(env: Optional[Dict[str, str]] = None) -> int:
    """Arm from ``RTPU_NETEM`` (called once at import, so every cluster
    subprocess inheriting the env arms itself; tests that mutate
    ``os.environ`` call it again). Env-loaded rules replace prior
    env-loaded rules; programmatically armed rules are kept. Returns
    the number of rules armed."""
    global _seed, _armed
    src = os.environ if env is None else env
    raw = (src.get(ENV) or "").strip()
    if not raw:
        return 0
    seed, specs = parse_spec(raw)
    with _lock:
        _seed = seed
        _rules[:] = [r for r in _rules if not r.env]
    for s in specs:
        add_rule(s["src"], s["dst"], s["kind"], s["params"], env=True)
    with _lock:
        _armed = bool(_rules)
    return len(specs)


def control(cmd: str, *args: Any) -> Any:
    """Remote-control entry backing the ``netem`` RPC op on node/GCS
    servers: the cluster fixture arms partitions inside other processes
    by sending control messages over (still-healthy) edges."""
    if cmd == "add":
        return add_rule(*args)
    if cmd == "clear":
        return clear(*args)
    if cmd == "schedule":
        return schedule()
    if cmd == "rules":
        return rules()
    raise ValueError(f"unknown netem control command {cmd!r}")


load_env()
