"""Spill storage backends: local disk (mmap fast path) or any fsspec
URI.

Reference: python/ray/_private/external_storage.py:451 — the reference
spills to the filesystem or to S3 (smart_open); here the same split is
local-path vs fsspec URI (s3://, gs://, memory://, file://...), chosen
by ``RTPU_SPILL_DIR``. Local spill files are mmap'd on read (large
tensors stay file-backed until touched); URI spills read through
fsspec.
"""

from __future__ import annotations

import os
from typing import Tuple


def is_uri(path: str) -> bool:
    return "://" in path


def _fs_and_path(uri: str):
    import fsspec

    fs, _, paths = fsspec.get_fs_token_paths(uri)
    return fs, paths[0]


def _join_uri(base: str, name: str) -> str:
    """URI join that survives bare-root bases: 'memory://'.rstrip('/')
    would collapse to 'memory:' and silently stop being a URI."""
    return base + name if base.endswith("://") \
        else base.rstrip("/") + "/" + name


def spill_dir_for(base: str, session: str) -> str:
    """Session-scoped spill location under the configured base."""
    if is_uri(base):
        return _join_uri(base, session)
    return os.path.join(base, session)


def write(spill_dir: str, name: str, view) -> Tuple[str, int]:
    """Write one spilled payload; returns (path_or_uri, size)."""
    if is_uri(spill_dir):
        uri = _join_uri(spill_dir, name)
        fs, p = _fs_and_path(uri)
        fs.makedirs(os.path.dirname(p), exist_ok=True)
        with fs.open(p, "wb") as f:
            # buffer-protocol write: no full bytes() copy of a payload
            # being spilled precisely because memory is tight
            f.write(view)
        return uri, view.nbytes
    os.makedirs(spill_dir, exist_ok=True)
    path = os.path.join(spill_dir, name)
    with open(path, "wb") as f:
        f.write(view)
    return path, view.nbytes


def read_buffer(path: str):
    """The spilled payload as a buffer. Local files mmap (file-backed
    until touched); URIs read through fsspec."""
    if is_uri(path):
        fs, p = _fs_and_path(path)
        with fs.open(p, "rb") as f:
            return f.read()
    import mmap as _mmap

    with open(path, "rb") as f:
        return _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)


def read_range(path: str, offset: int, length: int) -> bytes:
    if is_uri(path):
        fs, p = _fs_and_path(path)
        with fs.open(p, "rb") as f:
            f.seek(offset)
            return f.read(length)
    with open(path, "rb") as f:
        f.seek(offset)
        return f.read(length)


def size(path: str):
    try:
        if is_uri(path):
            fs, p = _fs_and_path(path)
            return fs.size(p)
        return os.path.getsize(path)
    except Exception:  # noqa: BLE001
        return None


def corrupt(path: str, nbytes: int = 64) -> bool:
    """Overwrite the head of a spill file with garbage in place (fault
    injection: a torn write / bad sector stand-in). The file keeps its
    size so only content validation — not existence checks — can tell.
    Returns False when the file is missing or the backend can't seek."""
    junk = b"\xde\xad\xbe\xef" * (nbytes // 4 + 1)
    try:
        if is_uri(path):
            fs, p = _fs_and_path(path)
            data = bytearray(fs.cat_file(p))
            n = min(len(data), nbytes)
            data[:n] = junk[:n]
            with fs.open(p, "wb") as f:
                f.write(bytes(data))
        else:
            with open(path, "r+b") as f:
                end = f.seek(0, 2)
                f.seek(0)
                f.write(junk[:min(end, nbytes)])
        return True
    except Exception:  # noqa: BLE001
        return False


def delete(path: str):
    try:
        if is_uri(path):
            fs, p = _fs_and_path(path)
            fs.rm(p)
        else:
            os.remove(path)
    # rtpu-lint: disable=L4 — best-effort delete of a spill file that may
    # already be gone; fsspec backends raise backend-specific types, not
    # a common base
    except Exception:  # noqa: BLE001
        pass


def cleanup_dir(spill_dir: str):
    """Remove a session's whole spill location (local tree or remote
    prefix) — shutdown must not leak spilled objects into the bucket."""
    try:
        if is_uri(spill_dir):
            fs, p = _fs_and_path(spill_dir)
            fs.rm(p, recursive=True)
        else:
            import shutil

            shutil.rmtree(spill_dir, ignore_errors=True)
    # rtpu-lint: disable=L4 — shutdown cleanup: a missing prefix or a
    # backend-specific fsspec error must not fail the teardown
    except Exception:  # noqa: BLE001
        pass
