"""Central configuration registry: every core tunable in one table.

Analogue of the reference's RayConfig x-macro flag system
(src/ray/common/ray_config_def.h:22 — 215 ``RAY_CONFIG(type, name,
default)`` entries, overridable per-process via ``RAY_<name>`` env vars).
Here the table is a list of ``Flag`` rows; each flag is overridable via the
``RTPU_<NAME>`` environment variable (upper-cased flag name), read once at
import and refreshable with ``config.reload()`` (tests) — so a flag set in
the driver's environment propagates to workers, which inherit the env.

Usage::

    from ray_tpu.core.config import config
    if config.fault_dump_after_s > 0: ...

``python -m ray_tpu.core.config`` prints the full table with docs,
defaults, and current values.

This table is *enforced*: ``python -m ray_tpu.tools.lint`` (rule L3)
statically checks that every ``config.<attr>`` read in the package
resolves to a ``Flag`` row here, that no row is dead (unread), and
that every literal ``RTPU_*`` env read elsewhere maps to a flag's
env var, a fault-injection site, or ``WIRING_ENV_VARS`` below — the
Python stand-in for the build error an unknown ``RAY_CONFIG`` name
raises in the reference.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, List


@dataclass(frozen=True)
class Flag:
    name: str
    type: type
    default: Any
    doc: str

    @property
    def env_var(self) -> str:
        return "RTPU_" + self.name.upper()


def _parse_bool(s: str) -> bool:
    return s.strip().lower() not in ("", "0", "false", "no", "off")


# The table. Keep alphabetized within each section.
_FLAGS: List[Flag] = [
    # ---- core runtime ----------------------------------------------------
    Flag("assume_tpu", bool, False,
         "Treat this host as having a TPU even when libtpu detection "
         "fails (CI containers, forced-TPU test paths). Read at call "
         "time directly from RTPU_ASSUME_TPU in resources.detect(), not "
         "via config resolution, so late env changes take effect."),
    Flag("fault_dump_after_s", float, 0.0,
         "If > 0, every worker dumps all thread stacks to "
         "/tmp/rtpu_worker_dump_<pid>.txt after this many seconds "
         "(hang triage; reference analogue: RAY_testing_asio_delay_us "
         "class of debug knobs)."),
    Flag("inline_threshold_bytes", int, 100 * 1024,
         "Results/args at or below this size travel inline in control "
         "messages; larger values go through the shm object store "
         "(reference: max_direct_call_object_size, ray_config_def.h)."),
    Flag("max_dispatch_batch", int, 32,
         "Upper bound on tasks pipelined to one worker in a single "
         "dispatch message (amortizes the driver->worker message cost; "
         "reference analogue: leased-worker pipelining)."),
    Flag("object_store_memory_fraction", float, 0.3,
         "Default shm store capacity as a fraction of system RAM when "
         "object_store_memory is not passed to init() (reference: "
         "object_store_memory default heuristic in services.py)."),
    Flag("store_lib", str, "",
         "Path to a prebuilt object-store shared library, overriding "
         "the bundled/compiled one (store-corruption tests, custom "
         "builds). Read at call time from RTPU_STORE_LIB in "
         "object_store.store._load_lib, not via config resolution, "
         "because store subprocesses receive it through their env."),
    Flag("streaming_generator_backpressure", int, 16,
         "Max in-flight (produced-but-unconsumed) returns a "
         "num_returns='streaming' generator may buffer before its worker "
         "blocks waiting for the consumer to catch up; 0 disables "
         "backpressure (reference: "
         "_generator_backpressure_num_objects, _raylet.pyx)."),
    Flag("tpu_topology", str, "",
         "Override the detected TPU topology string (e.g. '2x2x1'), "
         "for scheduling tests on hosts without the real topology. "
         "Read at call time from RTPU_TPU_TOPOLOGY in "
         "resources.detect(), not via config resolution."),
    Flag("worker_register_timeout_s", float, 30.0,
         "How long wait_for_workers waits for the pool to come up."),
    Flag("worker_shutdown_grace_s", float, 2.0,
         "Grace period for workers to exit at shutdown before SIGKILL."),
    # ---- compiled dags ---------------------------------------------------
    Flag("dag_compile_actor_wait_s", float, 5.0,
         "compile_dag deadline for a bound actor to finish registering "
         "with the cluster (actor creation is async; the DAG compiler "
         "races it). Lookup failures past the deadline name the actor."),
    Flag("dag_device_channels", str, "auto",
         "On-device DAG edges: 'auto' uses a DeviceChannel (jax Array "
         "handed off on device, doorbell-only shm) for edges between "
         "stages of the same TPU actor process, falling back to shm "
         "channels on CPU; 'off' forces shm everywhere; 'force' uses "
         "device edges for any same-process edge regardless of backend "
         "(tests exercise the handoff under JAX_PLATFORMS=cpu)."),
    Flag("dag_spin_us", int, 50,
         "Busy-poll budget in microseconds for compiled-DAG channel "
         "waits before falling back to the condvar (0 = pure block). "
         "The spin loop yields the CPU each poll round, so the default "
         "is safe on 1-core hosts; raise toward ~200 on multi-core "
         "hosts where the peer runs truly in parallel."),
    # ---- observability ---------------------------------------------------
    Flag("log_to_driver", bool, True,
         "Stream worker stdout/stderr lines to the driver's stderr with "
         "(worker=<id> out|err) prefixes (reference: ray.init "
         "log_to_driver + log_monitor.py)."),
    Flag("log_monitor_interval_s", float, 0.2,
         "Poll interval of the driver/node log monitor thread."),
    Flag("worker_log_redirect", bool, True,
         "Redirect each worker's stdout/stderr to per-worker files under "
         "the session log dir (worker-<id8>.out|err). Disabling inherits "
         "the parent's terminal (debug)."),
    Flag("task_events_enabled", bool, False,
         "Record task lifecycle events (submit/dispatch/done per task) "
         "for ray_tpu.timeline() chrome-trace export (reference: "
         "RAY_task_events_* flags + ray.timeline, "
         "python/ray/_private/state.py chrome_tracing_dump)."),
    Flag("usage_stats_enabled", bool, False,
         "Opt IN to the local usage-stats stub (reference: "
         "RAY_usage_stats_enabled, usage_stats_head.py — but inverted "
         "to opt-in, and nothing ever leaves the machine). Read at call "
         "time from RTPU_USAGE_STATS_ENABLED in usage_stats.enabled(), "
         "not via config resolution, so tests can flip it per-call."),
    # ---- fault tolerance -------------------------------------------------
    Flag("actor_restart_buffer_max", int, 1000,
         "How many calls may queue on a RESTARTING actor before new "
         "submissions raise ActorUnavailableError instead of buffering "
         "(reference: the bounded client queue in "
         "actor_task_submitter.h)."),
    Flag("actor_restart_timeout_s", float, 30.0,
         "Deadline for one actor restart: calls buffered longer than "
         "this (and new calls submitted past it) fail with "
         "ActorUnavailableError while the restart keeps going "
         "(reference: timeout_ms on the GCS actor restart path)."),
    Flag("task_max_retries", int, 3,
         "Default retry budget for tasks whose worker died mid-execution "
         "(reference: max_retries / task_retry_delay_ms, "
         "src/ray/core_worker/task_manager.h). Application exceptions are "
         "not retried."),
    Flag("max_reconstructions", int, 3,
         "How many times the driver resubmits a task to reconstruct a "
         "lost object before giving up (reference: "
         "object_recovery_manager.h)."),
    Flag("spill_dir", str, "/tmp/ray_tpu_spill",
         "Spill location under store memory pressure: a local directory "
         "(mmap'd reads) or any fsspec URI (s3://..., gs://...). URI "
         "backends must be reachable from EVERY process — memory:// is "
         "driver-process-only, for tests (reference: "
         "object_spilling_config + external_storage.py S3 spilling)."),
    Flag("lineage_max_bytes", int, 256 << 20,
         "Byte budget for the driver's lineage table (serialized task "
         "descriptions kept for object reconstruction); oldest entries "
         "are evicted past it (reference: max_lineage_bytes)."),
    # ---- train / elastic gangs -------------------------------------------
    Flag("elastic_grow_cooldown_s", float, 3.0,
         "Minimum spacing between attempts to grow an elastic training "
         "gang back toward its target world size. Each attempt probes "
         "for capacity by creating one replacement worker; the cooldown "
         "keeps a capacity-starved cluster from paying a probe (and a "
         "failed placement) every step."),
    Flag("elastic_grow_probe_timeout_s", float, 10.0,
         "How long a grow attempt waits for the probe worker to come up "
         "in its placement bundle before concluding capacity has not "
         "returned (the probe actor is killed and the gang stays at its "
         "current size)."),
    Flag("train_pg_ready_timeout_s", float, 60.0,
         "How long WorkerGroup.start waits for the gang's placement "
         "group before failing with PlacementGroupError; the error "
         "names the first bundle the cluster cannot satisfy."),
    # ---- serve / overload ------------------------------------------------
    Flag("serve_affinity_load_penalty", float, 64.0,
         "Cache-affinity load discount: estimated matched-prefix tokens "
         "a replica's score loses per router-local in-flight request on "
         "it. Higher values make affinity defer to load balance sooner "
         "(a replica must hold that many MORE cached prefix tokens to "
         "beat a one-request-lighter peer); 0 routes to the best cache "
         "holder regardless of load."),
    Flag("serve_affinity_min_prefix_tokens", int, 16,
         "Minimum estimated matched-prefix tokens before cache-affinity "
         "routing overrides power-of-two choices. Below this, the "
         "prefill saved is too small to justify skewing load — the "
         "request routes blind. Must be at least one page to ever "
         "match (prefix fingerprints cover full pages only)."),
    Flag("serve_cache_affinity", bool, False,
         "Prefix-cache-aware routing: engine replicas publish a bounded "
         "digest of their cached KV prefix fingerprints; the router "
         "scores candidates by estimated matched-prefix tokens minus a "
         "load penalty (serve_affinity_load_penalty) and routes to the "
         "best holder when the match clears "
         "serve_affinity_min_prefix_tokens. Off (default) keeps the "
         "seed power-of-two router byte-identical — no digest polling, "
         "no extra RNG draws."),
    Flag("serve_dag_spin_us", int, -1,
         "Busy-poll budget for serve dag_mode pipelines (the replica->"
         "engine hot path compiled onto DAG channels); -1 inherits "
         "dag_spin_us, 0 forces pure-block channels for serve only."),
    Flag("serve_disagg", bool, False,
         "Prefill/decode disaggregation for paged engine replicas: "
         "prompts longer than the largest prefill bucket divert to "
         "dedicated prefill workers whose finished KV pages stream to "
         "the decode engine over a DeviceChannel (device arrays handed "
         "off by reference; in-process queue fallback without a store) "
         "and are adopted as cached prefixes — heavy-tail prompts stop "
         "stealing decode ITL. Off (default) prefills inline, exactly "
         "the seed engine. serve.disagg.engine_class() resolves the "
         "flag for deployments."),
    Flag("serve_eject_ttft_ratio", float, 3.0,
         "Gray-replica detection bar (serve_replica_ejection on): a "
         "replica whose TTFT EWMA exceeds this multiple of the median "
         "of its peers' EWMAs (after a minimum observation count) is "
         "ejected from the router's pick set until the hysteresis "
         "cooldown expires or the controller replaces it."),
    Flag("serve_max_queue_depth", int, 0,
         "Default per-deployment admission cap: router-local requests in "
         "flight (admitted, not yet completed) beyond which new requests "
         "are shed with BackpressureError, lowest priority class first "
         "(low sheds at 1/3 of the cap, normal at 2/3, high at the full "
         "cap). 0 = unbounded — admission is a no-op, exactly the "
         "pre-QoS behavior. Per-deployment 'max_queue_depth' config "
         "overrides this default."),
    Flag("serve_prefill_workers", int, 1,
         "Dedicated prefill workers per disaggregated engine replica "
         "(serve_disagg on): each owns a private staging KV pool and "
         "prefills diverted prompts concurrently with decode, handing "
         "finished pages off as they complete. More workers overlap "
         "more heavy prompts at the cost of staging-pool HBM."),
    Flag("serve_replay_max_attempts", int, 3,
         "Total dispatch attempts per request under serve_request_replay "
         "(first try + replays). Every replay re-picks a replica via the "
         "affinity scorer; an exhausted budget surfaces "
         "ReplicaUnavailableError carrying the attempt count and the "
         "last cause."),
    Flag("serve_replica_ejection", bool, False,
         "Gray-replica ejection: the router scores per-replica health "
         "(TTFT EWMA outlier vs the deployment median, consecutive "
         "dispatch-failure streak, engine-poll staleness) and stops "
         "picking ejected replicas; routers report ejections with their "
         "load reports and the controller probes and replaces "
         "persistently gray replicas (reports that stop refreshing "
         "restore the replica instead). Off (default) keeps the pick "
         "path byte-identical to the seed pow-2 router."),
    Flag("serve_replica_wait_s", float, 30.0,
         "How long the router waits for a running replica to appear "
         "before failing the request with ReplicaUnavailableError "
         "(deployment deleted, never deployed, or all replicas down)."),
    Flag("serve_request_replay", bool, False,
         "Durable request replay: every unary/batch/call_method request "
         "carries a dedup nonce recorded in the router's request "
         "ledger; on replica death or call timeout the router re-picks "
         "(affinity-aware) and replays up to serve_replay_max_attempts, "
         "with replica-side nonce dedup collapsing at-least-once "
         "execution to exactly-once results. Also enables mid-stream "
         "resume: an engine token stream that loses its replica "
         "resubmits prompt + delivered tokens to the best affinity "
         "candidate and splices at the delivered-token watermark. Off "
         "(default) keeps the seed 3-attempt retry loops and the wire "
         "payloads byte-identical."),
    Flag("serve_shutdown_grace_s", float, 15.0,
         "How long serve controller shutdown waits for backgrounded "
         "replica stops (graceful_shutdown + kill) to finish before "
         "returning; past it, stop threads are abandoned."),
    Flag("serve_ttft_ewma_alpha", float, 0.3,
         "Smoothing factor for the router's per-replica TTFT EWMA (the "
         "admission-control wait estimator): higher reacts faster to "
         "load shifts, lower resists outliers."),
    Flag("serve_ttft_slo_ms", float, 0.0,
         "Serving TTFT SLO for the autoscaler demand signal: when > 0, "
         "a deployment whose recent TTFT p99 (published by the serve "
         "controller on the 'serve:demand' KV key) exceeds this counts "
         "as cluster demand even with an empty task queue. 0 disables "
         "the SLO signal (queue depth still counts)."),
    Flag("serve_worker_poll_deadline_s", float, 12.0,
         "In-worker routers drain the controller long-poll ref with "
         "non-blocking probes for at most this long before re-arming "
         "(a blocking get would head-of-line block the replica's "
         "serialized owner connection)."),
    # ---- cluster plane ---------------------------------------------------
    Flag("fetch_chunk_bytes", int, 16 << 20,
         "Chunk size for ranged node-to-node object transfer "
         "(reference: object manager 64MB chunked pushes)."),
    Flag("fetch_parallel_threshold_bytes", int, 64 << 20,
         "Objects at or above this size transfer as parallel ranged "
         "chunks over multiple connections (the DCN bulk path); smaller "
         "ones use a single fetch call. 0 disables ranged transfer."),
    Flag("fetch_parallelism", int, 4,
         "Concurrent connections per large-object fetch."),
    Flag("push_max_inflight_bytes", int, 64 << 20,
         "Sender-side flow control: max bytes of outbound object chunks "
         "being copied/served concurrently per node; excess chunk "
         "requests queue (reference: push_manager.h caps chunks in "
         "flight on the sending side). 0 disables the cap."),
    Flag("locality_aware_scheduling", bool, True,
         "Score resource-feasible nodes by the bytes of task arguments "
         "already resident on each (args >= locality_min_arg_bytes), so "
         "tasks chase their data instead of pulling it (reference: "
         "locality-aware leasing, lease_policy.h / Ownership NSDI'21). "
         "Placement-group and node-affinity strategies keep precedence; "
         "off = pure resource-fit + load + round-robin."),
    Flag("locality_cache_ttl_s", float, 5.0,
         "Driver-side object-location cache max staleness. Entries are "
         "invalidated eagerly on free (the GCS 'freed' channel) and node "
         "death; the TTL bounds staleness from eviction/spill, which "
         "only ever costs scheduling quality, not correctness."),
    Flag("locality_load_penalty_bytes", int, 16 << 20,
         "Queue-depth tradeoff for locality scoring: each queued task on "
         "a node discounts its local-argument bytes by this much, so a "
         "deeply backlogged holder loses to an idle peer once the "
         "transfer it saves is cheaper than the wait."),
    Flag("locality_min_arg_bytes", int, 1 << 20,
         "Arguments at or above this size participate in locality "
         "scoring; smaller ones are cheaper to ship than to chase."),
    Flag("gcs_heartbeat_interval_s", float, 0.2,
         "Node -> GCS heartbeat period (reference: "
         "raylet_report_resources_period_milliseconds)."),
    Flag("gcs_heartbeat_timeout_s", float, 3.0,
         "A node missing heartbeats for this long is marked DEAD "
         "(reference: health_check_timeout_ms, "
         "gcs_health_check_manager.h)."),
    Flag("pull_acquire_timeout_s", float, 120.0,
         "How long a bulk object pull waits for admission (store-memory "
         "reservation) before timing out and re-planning from fresh "
         "locations. Shrink in partition tests so a blocked pull fails "
         "over in seconds, not minutes; errors name the peer address."),
    Flag("pull_admission_fraction", float, 0.5,
         "Fraction of object-store capacity that concurrent bulk pulls "
         "may reserve; excess pulls queue by priority task-args > get > "
         "wait (reference: pull_manager.h:52)."),
    Flag("memory_monitor_enabled", bool, True,
         "Kill workers under node memory pressure instead of letting the "
         "kernel OOM the node (reference: memory_monitor.h:52)."),
    Flag("memory_monitor_interval_s", float, 0.25,
         "Memory monitor poll period (reference: "
         "memory_monitor_refresh_ms)."),
    Flag("memory_usage_threshold", float, 0.95,
         "Usage fraction above which the kill policy fires (reference: "
         "memory_usage_threshold)."),
    Flag("memory_limit_bytes", int, 0,
         "When >0, bound the WORKER TREE's summed RSS by this many bytes "
         "instead of watching host/cgroup usage — deterministic for "
         "tests, and a fence on shared hosts."),
    Flag("task_oom_retries", int, 3,
         "OOM kills a retriable task survives without consuming its "
         "max_retries budget; past this, callers get OutOfMemoryError "
         "(reference: task_oom_retries, -1 = infinite)."),
    Flag("worker_zygote", bool, True,
         "Fork new workers from a pre-warmed zygote template (~10ms) "
         "instead of cold interpreter starts (~300ms). TPU workers always "
         "cold-spawn (reference: PrestartWorkers, "
         "raylet/worker_pool.h:344)."),
    Flag("worker_ready_timeout_s", float, 300.0,
         "A spawned worker that neither connects (MSG_READY) nor exits "
         "within this window is presumed wedged: killed and handled as "
         "a pre-ready death (env pools count it toward their "
         "crash-loop bound). Raise on hosts with very slow cold "
         "starts."),
    Flag("gcs_wal_fsync", bool, False,
         "fsync the GCS write-ahead log on every append. Default off: "
         "durability then covers GCS process crashes (the common failure), "
         "not host/OS crashes. Turn on for strict durability at ~ms/append "
         "cost (reference: gcs_storage durability knobs)."),
    Flag("gcs_reconnect_timeout_s", float, 15.0,
         "How long GCS clients (driver ClusterCore, node servers) keep "
         "buffering and retrying calls while the head is unreachable "
         "before failing them with GcsUnavailableError. Covers a SIGKILL "
         "+ restart of the GCS process (reference: "
         "gcs_rpc_server_reconnect_timeout_s)."),
    Flag("gcs_op_buffer_max", int, 512,
         "Max GCS calls a single client parks in the ride-through buffer "
         "while the head is down; calls beyond this raise "
         "GcsUnavailableError immediately instead of piling up threads "
         "(mirror of actor_restart_buffer_max at the cluster level)."),
    Flag("gcs_recovery_grace_s", float, 5.0,
         "After a GCS restart that recovered prior state, suppress "
         "death-marking of known nodes/drivers for this long so they can "
         "heartbeat back in before the health loop declares them DEAD "
         "(reference: gcs_failover_worker_reconnect_timeout)."),
    Flag("rpc_handshake_timeout_s", float, 15.0,
         "Hard deadline on the cluster RPC authkey handshake (client and "
         "server side): a half-open peer that stalls mid-challenge is "
         "cut off after this long instead of wedging the connect path "
         "(see rpc._timed_handshake). Timeout errors name the peer."),
    Flag("driver_heartbeat_interval_s", float, 0.5,
         "Driver -> GCS owner-liveness heartbeat period."),
    Flag("driver_heartbeat_timeout_s", float, 3.0,
         "A driver missing heartbeats this long is declared dead; its "
         "objects are reclaimed cluster-wide and its non-detached actors "
         "stop restarting (reference: owner-failure semantics, "
         "core_worker/reference_count.h:61, gcs_job_manager.h)."),
    Flag("cluster_view_refresh_s", float, 0.25,
         "Driver-side cluster view (node table + loads) max staleness "
         "before re-fetching from the GCS."),
    Flag("node_drain_grace_s", float, 10.0,
         "Bounded grace window for a DRAINING node: the scheduler stops "
         "placing new work immediately, restartable/detached actors "
         "migrate, and running tasks get this long to finish before the "
         "GCS declares the node DRAINED (reference: DrainNodeRequest "
         "deadline, gcs_node_manager). A drained node deregisters "
         "cleanly — no death event, no lineage reconstruction."),
    Flag("quarantine_score_threshold", float, 2.0,
         "Per-node health score (heartbeat-interval jitter EWMA + "
         "task-failure-rate EWMA + peer suspicion reports) above which "
         "the GCS auto-QUARANTINES a gray-failing node: cordoned from "
         "scheduling, existing work allowed to finish, periodically "
         "probed for recovery. 0 disables quarantining."),
    Flag("quarantine_recover_s", float, 1.0,
         "Hysteresis window for un-quarantine: a QUARANTINED node "
         "returns to ALIVE only after its health score has stayed below "
         "half the quarantine threshold for this long AND the GCS's "
         "periodic liveness probe succeeds — so a flapping node cannot "
         "oscillate in and out of the schedulable set."),
    Flag("job_lease_ttl_s", float, 2.0,
         "Heartbeat lease a job agent holds on every claimed job; the "
         "agent renews it each poll tick, and the GCS orphan detector "
         "re-queues (or fails, per the job's max_restarts policy) any "
         "RUNNING job whose lease expired — a SIGKILLed agent can no "
         "longer strand jobs forever."),
    Flag("job_max_restarts_default", int, 0,
         "Default max_restarts for submit_job when the caller does not "
         "pass one: how many times a crash-looping entrypoint (nonzero "
         "exit, or an orphaned claim) is re-queued with exponential "
         "backoff + full jitter before the job goes FAILED."),
    # ---- chaos / testing -------------------------------------------------
    Flag("testing_rpc_delay_ms", int, 0,
         "If > 0, injects a uniform random delay up to this many ms into "
         "worker<->driver control messages (reference: asio_chaos.cc:35)."),
    Flag("testing_kill_worker_prob", float, 0.0,
         "If > 0, each task execution exits the worker with this "
         "probability before running (chaos; reference: WorkerKillerActor "
         "test_utils.py:1597)."),
    Flag("fault_injection", str, "",
         "Deterministic fault plan: comma-separated "
         "'<site>=<action>[:<times>[:<match>]]' specs armed at named "
         "sites (see ray_tpu/core/fault_injection.py for the site and "
         "action tables). Equivalent per-site env form: "
         "RTPU_FAULT_<SITE>=<action>[:<times>[:<match>]]. Unlike the "
         "probabilistic testing_* knobs above, these target a chosen "
         "object/task and fire an exact number of times."),
]

_BY_NAME: Dict[str, Flag] = {f.name: f for f in _FLAGS}

# Per-process plumbing injected by whichever process spawns another:
# addresses, auth material, identities. These are NOT user tunables (no
# Flag row, no default, no reload()); they exist so the rtpu-lint L3
# analyzer — and readers — can tell a registered wiring variable from a
# stray/undeclared RTPU_* env read. Keep alphabetized.
WIRING_ENV_VARS: Dict[str, str] = {
    "RTPU_ADDRESS": "driver/GCS RPC address handed to spawned workers "
                    "and attached drivers (host:port)",
    "RTPU_AUTH": "hex authkey for the driver<->worker control plane, "
                 "generated per session by the spawner",
    "RTPU_CLUSTER_AUTHKEY": "hex authkey shared by every cluster "
                            "process (see rpc.cluster_authkey: no "
                            "default, deliberately)",
    "RTPU_NETEM": "seeded deterministic network-fault plan "
                  "'<seed>:<spec>' armed at import in every cluster "
                  "process (rule grammar and replay protocol in "
                  "core/netem.py; wire-level sibling of RTPU_FAULT_*)",
    "RTPU_NODE_ID": "id of the node a spawned worker belongs to",
    "RTPU_PKG_DIR": "working-dir package root a worker unpacked its "
                    "runtime env into (set by runtime_env activation)",
    "RTPU_SANITIZE": "arm the lock-order sanitizer: util/debug_lock.py "
                     "wraps core locks, raises on acquisition-order "
                     "inversions and callbacks fired under a tracked "
                     "lock (read at import, inherited by workers)",
    "RTPU_STORE": "object-store shm segment name handed to workers",
    "RTPU_TPU_CHIPS": "comma-separated TPU chip ids the runtime pinned "
                      "into a TPU actor's worker (set at spawn alongside "
                      "TPU_VISIBLE_CHIPS; the DAG device-placement probe "
                      "reads it to tag the actor as TPU-resident)",
    "RTPU_WORKER_ID": "id the spawner assigned this worker process",
    "RTPU_WORKER_PIP_KEY": "cache key of the pip runtime env a worker "
                           "was launched under (env pool accounting)",
}


class _Config:
    """Singleton holding resolved flag values as attributes."""

    def __init__(self):
        self.reload()

    def reload(self, env: Dict[str, str] = None):
        """Re-resolve every flag from the environment (tests, or after
        mutating os.environ in-process)."""
        env = os.environ if env is None else env
        for f in _FLAGS:
            raw = env.get(f.env_var)
            if raw is None:
                value = f.default
            elif f.type is bool:
                value = _parse_bool(raw)
            else:
                value = f.type(raw)
            object.__setattr__(self, f.name, value)

    def to_dict(self) -> Dict[str, Any]:
        return {f.name: getattr(self, f.name) for f in _FLAGS}

    def describe(self) -> List[Dict[str, Any]]:
        return [
            {"name": f.name, "env": f.env_var, "type": f.type.__name__,
             "default": f.default, "value": getattr(self, f.name),
             "doc": f.doc}
            for f in _FLAGS
        ]


config = _Config()


def flags() -> List[Flag]:
    return list(_FLAGS)


if __name__ == "__main__":
    for row in config.describe():
        star = "" if row["value"] == row["default"] else "  *"
        print(f"{row['name']} ({row['env']}, {row['type']}) = "
              f"{row['value']!r} [default {row['default']!r}]{star}")
        print(f"    {row['doc']}")
