"""ObjectRef: a distributed future (reference: python/ray/includes/object_ref.pxi).

Serialization contract: pickling an ObjectRef emits a resolver call so that a
ref nested inside task args / put objects is reconstructed on the receiving
process bound to *that* process's core client (reference nests refs the same
way via CoreWorker serialization context). While the driver serializes task
args it also *collects* every ref it encounters so the scheduler can wait on
dependencies (reference: LocalDependencyResolver).
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional

from ray_tpu.core.ids import ObjectID

_collect_ctx = threading.local()


def begin_ref_collection() -> List["ObjectRef"]:
    refs: List[ObjectRef] = []
    _collect_ctx.refs = refs
    return refs


def end_ref_collection():
    _collect_ctx.refs = None


def _resolve_ref(oid_bytes: bytes) -> "ObjectRef":
    """Unpickle hook: rebuild the ref bound to the local core client."""
    from ray_tpu.core import runtime_context

    return ObjectRef(ObjectID(oid_bytes), core=runtime_context.get_core_or_none())


class ObjectRef:
    __slots__ = ("_id", "_core", "__weakref__")

    def __init__(self, oid: ObjectID, core=None):
        self._id = oid
        self._core = core

    @property
    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def get(self, timeout: Optional[float] = None) -> Any:
        from ray_tpu.core import runtime_context

        core = self._core or runtime_context.get_core()
        return core.get_objects([self], timeout=timeout)[0]

    def __reduce__(self):
        refs = getattr(_collect_ctx, "refs", None)
        if refs is not None:
            refs.append(self)
        return (_resolve_ref, (self._id.binary(),))

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __await__(self):
        """Allow ``await ref`` inside async actors."""
        from ray_tpu.core import runtime_context

        core = self._core or runtime_context.get_core()
        fut = core.as_future(self)
        return fut.__await__()
