"""ObjectRef: a distributed future (reference: python/ray/includes/object_ref.pxi).

Serialization contract: pickling an ObjectRef emits a resolver call so that a
ref nested inside task args / put objects is reconstructed on the receiving
process bound to *that* process's core client (reference nests refs the same
way via CoreWorker serialization context). While the driver serializes task
args it also *collects* every ref it encounters so the scheduler can wait on
dependencies (reference: LocalDependencyResolver).
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional

from ray_tpu.core.ids import ObjectID

_collect_ctx = threading.local()


def begin_ref_collection() -> List["ObjectRef"]:
    refs: List[ObjectRef] = []
    _collect_ctx.refs = refs
    return refs


def end_ref_collection():
    _collect_ctx.refs = None


def _resolve_ref(oid_bytes: bytes) -> "ObjectRef":
    """Unpickle hook: rebuild the ref bound to the local core client."""
    from ray_tpu.core import runtime_context

    return ObjectRef(ObjectID(oid_bytes), core=runtime_context.get_core_or_none())


class ObjectRef:
    __slots__ = ("_id", "_core", "__weakref__")

    def __init__(self, oid: ObjectID, core=None):
        self._id = oid
        self._core = core

    @property
    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def get(self, timeout: Optional[float] = None) -> Any:
        from ray_tpu.core import runtime_context

        core = self._core or runtime_context.get_core()
        return core.get_objects([self], timeout=timeout)[0]

    def __reduce__(self):
        refs = getattr(_collect_ctx, "refs", None)
        if refs is not None:
            refs.append(self)
        return (_resolve_ref, (self._id.binary(),))

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __await__(self):
        """Allow ``await ref`` inside async actors."""
        from ray_tpu.core import runtime_context

        core = self._core or runtime_context.get_core()
        fut = core.as_future(self)
        return fut.__await__()


_STREAM_DONE = object()


def _resolve_generator(seed: bytes, owner) -> "ObjectRefGenerator":
    """Unpickle hook: rebind the generator to the local core client."""
    from ray_tpu.core import runtime_context

    return ObjectRefGenerator(
        seed, core=runtime_context.get_core_or_none(), owner=owner)


class ObjectRefGenerator:
    """Iterator over the returns of a ``num_returns="streaming"`` task
    (reference: ObjectRefGenerator, python/ray/_raylet.pyx:263).

    Each ``next()`` blocks until the producing generator has sealed the
    next yield, then hands back an ``ObjectRef`` — so consumption starts
    while the task is still running. Advancing the iterator reports the
    previous index consumed, releasing producer backpressure credit.
    A mid-stream task failure surfaces as a final ref whose ``get()``
    raises, followed by ``StopIteration``.
    """

    def __init__(self, seed: bytes, core=None, owner=None):
        self._seed = seed
        self._core = core
        self._owner = owner  # producing node addr hint (cluster path)
        self._index = 0
        self._end: Optional[int] = None

    @property
    def seed(self) -> bytes:
        return self._seed

    def __iter__(self):
        return self

    def __next__(self) -> "ObjectRef":
        return self.next_ref(timeout=None)

    def next_ref(self, timeout: Optional[float] = None) -> "ObjectRef":
        """Blocking next; raises StopIteration at end of stream and
        ObjectTimeoutError if ``timeout`` (seconds) elapses first."""
        if self._end is not None and self._index >= self._end:
            raise StopIteration
        from ray_tpu.core import runtime_context

        core = self._core or runtime_context.get_core()
        kind, detail = core.stream_next(
            self._seed, self._index, timeout=timeout, owner=self._owner)
        if kind == "end":
            self._end = detail
            raise StopIteration
        ref = ObjectRef(ObjectID(detail), core=core)
        core.stream_consumed(self._seed, self._index, owner=self._owner)
        self._index += 1
        return ref

    def _next_or_done(self):
        try:
            return self.__next__()
        except StopIteration:
            return _STREAM_DONE

    def __aiter__(self):
        return self

    async def __anext__(self) -> "ObjectRef":
        import asyncio

        loop = asyncio.get_running_loop()
        res = await loop.run_in_executor(None, self._next_or_done)
        if res is _STREAM_DONE:
            raise StopAsyncIteration
        return res

    def __reduce__(self):
        return (_resolve_generator, (self._seed, self._owner))

    def __repr__(self):
        return (f"ObjectRefGenerator(seed={self._seed.hex()}, "
                f"next_index={self._index})")
