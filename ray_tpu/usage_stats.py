"""Usage stats: OPT-IN, local-file-only session records.

Reference: python/ray/_private/usage/usage_lib.py (phones home unless
disabled). This framework inverts the default — nothing is recorded
unless RTPU_USAGE_STATS_ENABLED=1, and records only ever go to a local
JSON file (no network reporting exists)."""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict

USAGE_FILE = "/tmp/ray_tpu_usage.json"


def enabled() -> bool:
    return os.environ.get("RTPU_USAGE_STATS_ENABLED", "0") == "1"


def record(event: str, **fields: Any) -> None:
    if not enabled():
        return
    entry: Dict[str, Any] = {"event": event, "ts": time.time(), **fields}
    try:
        with open(USAGE_FILE, "a") as f:
            f.write(json.dumps(entry) + "\n")
    except OSError:
        pass
