"""Built-in vectorized environments (numpy, no gym dependency).

The reference wraps gymnasium; this image ships no gym, so the standard
benchmark env is implemented directly. The interface is the vectorized
subset RLlib's EnvRunner needs: reset() -> obs [N, obs_dim];
step(actions [N]) -> (obs, reward [N], done [N]).
"""

from __future__ import annotations

import numpy as np


class CartPoleVec:
    """Classic CartPole-v1 dynamics (Barto-Sutton-Anderson), vectorized.

    Matches the gymnasium implementation's constants: episode ends on
    |x| > 2.4, |theta| > 12deg, or 500 steps; reward 1 per step. Done envs
    auto-reset.
    """

    obs_dim = 4
    num_actions = 2
    max_steps = 500

    def __init__(self, num_envs: int, seed: int = 0):
        self.n = num_envs
        self.rng = np.random.default_rng(seed)
        self.state = np.zeros((num_envs, 4), np.float64)
        self.steps = np.zeros(num_envs, np.int64)
        self.reset()

    def _sample_state(self, n: int) -> np.ndarray:
        return self.rng.uniform(-0.05, 0.05, size=(n, 4))

    def reset(self) -> np.ndarray:
        self.state = self._sample_state(self.n)
        self.steps[:] = 0
        return self.state.astype(np.float32)

    def step(self, actions: np.ndarray):
        gravity, masscart, masspole = 9.8, 1.0, 0.1
        total_mass = masscart + masspole
        length = 0.5
        polemass_length = masspole * length
        force_mag, tau = 10.0, 0.02

        x, x_dot, theta, theta_dot = self.state.T
        force = np.where(actions == 1, force_mag, -force_mag)
        costheta, sintheta = np.cos(theta), np.sin(theta)
        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (gravity * sintheta - costheta * temp) / (
            length * (4.0 / 3.0 - masspole * costheta**2 / total_mass))
        xacc = temp - polemass_length * thetaacc * costheta / total_mass

        x = x + tau * x_dot
        x_dot = x_dot + tau * xacc
        theta = theta + tau * theta_dot
        theta_dot = theta_dot + tau * thetaacc
        self.state = np.stack([x, x_dot, theta, theta_dot], axis=1)
        self.steps += 1

        done = (np.abs(x) > 2.4) | (np.abs(theta) > 12 * np.pi / 180) | (
            self.steps >= self.max_steps)
        reward = np.ones(self.n, np.float32)
        if done.any():
            idx = np.nonzero(done)[0]
            self.state[idx] = self._sample_state(len(idx))
            self.steps[idx] = 0
        return self.state.astype(np.float32), reward, done


ENVS = {"CartPole-v1": CartPoleVec}


def make_env(name: str, num_envs: int, seed: int = 0):
    try:
        return ENVS[name](num_envs, seed=seed)
    except KeyError:
        raise ValueError(
            f"unknown env {name!r}; registered: {sorted(ENVS)}") from None
