"""Built-in vectorized environments (numpy, no gym dependency).

The reference wraps gymnasium; this image ships no gym, so the standard
benchmark env is implemented directly. The interface is the vectorized
subset RLlib's EnvRunner needs, with the gymnasium termination split:
reset() -> obs [N, obs_dim]; step(actions [N]) -> (obs, reward [N],
terminated [N], truncated [N]). Done envs auto-reset; the TRUE final
observation of a finished episode is stashed in ``final_obs`` (the
post-reset obs goes into the returned batch), so learners can bootstrap
through time-limit truncations instead of treating them as terminal.
"""

from __future__ import annotations

import numpy as np


class CartPoleVec:
    """Classic CartPole-v1 dynamics (Barto-Sutton-Anderson), vectorized.

    Matches the gymnasium implementation's constants: episode ends on
    |x| > 2.4, |theta| > 12deg, or 500 steps; reward 1 per step. Done envs
    auto-reset.
    """

    obs_dim = 4
    num_actions = 2
    max_steps = 500

    def __init__(self, num_envs: int, seed: int = 0):
        self.n = num_envs
        self.rng = np.random.default_rng(seed)
        self.state = np.zeros((num_envs, 4), np.float64)
        self.steps = np.zeros(num_envs, np.int64)
        self.reset()

    def _sample_state(self, n: int) -> np.ndarray:
        return self.rng.uniform(-0.05, 0.05, size=(n, 4))

    def reset(self) -> np.ndarray:
        self.state = self._sample_state(self.n)
        self.steps[:] = 0
        return self.state.astype(np.float32)

    def step(self, actions: np.ndarray):
        gravity, masscart, masspole = 9.8, 1.0, 0.1
        total_mass = masscart + masspole
        length = 0.5
        polemass_length = masspole * length
        force_mag, tau = 10.0, 0.02

        x, x_dot, theta, theta_dot = self.state.T
        force = np.where(actions == 1, force_mag, -force_mag)
        costheta, sintheta = np.cos(theta), np.sin(theta)
        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (gravity * sintheta - costheta * temp) / (
            length * (4.0 / 3.0 - masspole * costheta**2 / total_mass))
        xacc = temp - polemass_length * thetaacc * costheta / total_mass

        x = x + tau * x_dot
        x_dot = x_dot + tau * xacc
        theta = theta + tau * theta_dot
        theta_dot = theta_dot + tau * thetaacc
        self.state = np.stack([x, x_dot, theta, theta_dot], axis=1)
        self.steps += 1

        terminated = (np.abs(x) > 2.4) | (np.abs(theta) > 12 * np.pi / 180)
        truncated = ~terminated & (self.steps >= self.max_steps)
        done = terminated | truncated
        reward = np.ones(self.n, np.float32)
        self.final_obs = self.state.astype(np.float32)
        if done.any():
            idx = np.nonzero(done)[0]
            self.state[idx] = self._sample_state(len(idx))
            self.steps[idx] = 0
        return self.state.astype(np.float32), reward, terminated, truncated


class PendulumVec:
    """Classic Pendulum-v1 swing-up (continuous torque), vectorized.

    Matches the gymnasium constants: g=10, m=1, l=1, dt=0.05, torque
    clipped to [-2, 2], theta_dot clipped to [-8, 8], 200-step episodes,
    reward = -(angle^2 + 0.1*thetadot^2 + 0.001*torque^2). obs is
    [cos(theta), sin(theta), theta_dot]. Done envs auto-reset.
    """

    obs_dim = 3
    action_dim = 1
    action_low = -2.0
    action_high = 2.0
    max_steps = 200

    def __init__(self, num_envs: int, seed: int = 0):
        self.n = num_envs
        self.rng = np.random.default_rng(seed)
        self.theta = np.zeros(num_envs)
        self.theta_dot = np.zeros(num_envs)
        self.steps = np.zeros(num_envs, np.int64)
        self.reset()

    def _obs(self) -> np.ndarray:
        return np.stack([np.cos(self.theta), np.sin(self.theta),
                         self.theta_dot], axis=1).astype(np.float32)

    def _sample(self, n: int):
        return (self.rng.uniform(-np.pi, np.pi, size=n),
                self.rng.uniform(-1.0, 1.0, size=n))

    def reset(self) -> np.ndarray:
        self.theta, self.theta_dot = self._sample(self.n)
        self.steps[:] = 0
        return self._obs()

    def step(self, actions: np.ndarray):
        g, m, length, dt = 10.0, 1.0, 1.0, 0.05
        u = np.clip(np.asarray(actions, np.float64).reshape(self.n, -1)[:, 0],
                    self.action_low, self.action_high)
        th = ((self.theta + np.pi) % (2 * np.pi)) - np.pi  # normalize
        cost = th**2 + 0.1 * self.theta_dot**2 + 0.001 * u**2

        acc = (3 * g / (2 * length) * np.sin(self.theta)
               + 3.0 / (m * length**2) * u)
        self.theta_dot = np.clip(self.theta_dot + acc * dt, -8.0, 8.0)
        self.theta = self.theta + self.theta_dot * dt
        self.steps += 1

        truncated = self.steps >= self.max_steps  # never terminates
        terminated = np.zeros(self.n, bool)
        self.final_obs = self._obs()
        if truncated.any():
            idx = np.nonzero(truncated)[0]
            th0, thd0 = self._sample(len(idx))
            self.theta[idx], self.theta_dot[idx] = th0, thd0
            self.steps[idx] = 0
        return self._obs(), (-cost).astype(np.float32), terminated, truncated


class CatchPixelsVec:
    """Procedural PIXEL-observation env (the image ships no ALE; this is
    the Atari-shaped stand-in the CNN path trains on): a ball falls down
    a GRID x GRID frame, a 3-cell paddle slides along the bottom row,
    reward +1 on catch / -1 on miss at the bottom, episode length = GRID-1
    steps. Observations are raw pixels, flattened [N, GRID*GRID] float32
    (module reshapes to (H, W, 1) — see rl_module.CNNModule). Random play
    scores ~-0.25; a learned policy approaches +1.
    """

    GRID = 10
    obs_dim = GRID * GRID
    obs_shape = (GRID, GRID, 1)
    num_actions = 3  # left, stay, right
    max_steps = GRID - 1

    def __init__(self, num_envs: int, seed: int = 0):
        self.n = num_envs
        self.rng = np.random.default_rng(seed)
        self.ball = np.zeros((num_envs, 2), np.int64)   # row, col
        self.paddle = np.zeros(num_envs, np.int64)      # center col
        self.reset()

    def _respawn(self, idx):
        self.ball[idx, 0] = 0
        self.ball[idx, 1] = self.rng.integers(0, self.GRID, size=len(idx))
        self.paddle[idx] = self.rng.integers(1, self.GRID - 1,
                                             size=len(idx))

    def _render(self) -> np.ndarray:
        g = self.GRID
        frame = np.zeros((self.n, g, g), np.float32)
        env_i = np.arange(self.n)
        frame[env_i, self.ball[:, 0], self.ball[:, 1]] = 1.0
        for d in (-1, 0, 1):
            cols = np.clip(self.paddle + d, 0, g - 1)
            frame[env_i, g - 1, cols] = 0.5
        return frame.reshape(self.n, -1)

    def reset(self) -> np.ndarray:
        self._respawn(np.arange(self.n))
        return self._render()

    def step(self, actions: np.ndarray):
        self.paddle = np.clip(self.paddle + (actions.astype(np.int64) - 1),
                              1, self.GRID - 2)
        self.ball[:, 0] += 1
        at_bottom = self.ball[:, 0] >= self.GRID - 1
        caught = at_bottom & (np.abs(self.ball[:, 1] - self.paddle) <= 1)
        reward = np.where(at_bottom,
                          np.where(caught, 1.0, -1.0), 0.0
                          ).astype(np.float32)
        terminated = at_bottom
        truncated = np.zeros(self.n, bool)
        self.final_obs = self._render()
        if at_bottom.any():
            self._respawn(np.nonzero(at_bottom)[0])
        return self._render(), reward, terminated, truncated


ENVS = {"CartPole-v1": CartPoleVec, "Pendulum-v1": PendulumVec,
        "CatchPixels-v0": CatchPixelsVec}


def make_env(name: str, num_envs: int, seed: int = 0):
    try:
        return ENVS[name](num_envs, seed=seed)
    except KeyError:
        raise ValueError(
            f"unknown env {name!r}; registered: {sorted(ENVS)}") from None
