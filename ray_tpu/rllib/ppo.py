"""PPO (reference: rllib/algorithms/ppo/ppo.py:401), JAX Learner path."""

from __future__ import annotations

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import PPOLearner
from ray_tpu.rllib.rl_module import build_pv_module


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.train_kwargs = {
            "clip": 0.2, "vf_coef": 0.5, "ent_coef": 0.01,
            "num_epochs": 10, "minibatch_size": 256, "lam": 0.95,
            "max_grad_norm": 0.5,
        }

    def build(self) -> "PPO":
        return PPO(self)


class PPO(Algorithm):
    def _build_learner(self) -> PPOLearner:
        cfg = self.config
        kw = dict(cfg.train_kwargs)
        kw.pop("lam", None)
        return PPOLearner(build_pv_module(self.module_spec), lr=cfg.lr,
                          seed=cfg.seed, **kw)
