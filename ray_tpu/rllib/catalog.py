"""Model catalog: spaces -> default RLModules.

Reference: rllib/models/catalog.py (ModelCatalog) — the single place
that maps (observation space, action space, model_config) onto a
concrete model, with a registry for user-supplied custom models. gym
isn't a dependency here, so the catalog ships its own minimal space
types; ``Catalog.spaces_of(env)`` derives them from the vec-env
attribute convention (obs_dim / obs_shape / num_actions / action_dim)
used across ``rllib/envs.py``.

Selection rules (same shape logic the reference's catalog applies):

- 3-D Box obs + Discrete actions  -> ``CNNModule`` (conv encoder)
- 1-D Box obs + Discrete actions  -> ``MLPModule`` (policy+value)
- 1-D Box obs + Box actions       -> ``SquashedGaussianModule``
- Q-networks via ``get_q_module``: Discrete -> ``QMLPModule``,
  Box -> ``TwinQModule`` (twin critics)
- ``model_config={"custom_model": name}`` routes to a registered
  factory (reference: ModelCatalog.register_custom_model)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

from ray_tpu.rllib.rl_module import (
    CNNModule,
    MLPModule,
    QMLPModule,
    SquashedGaussianModule,
    TwinQModule,
)


class Discrete:
    """n distinct actions (reference: gym.spaces.Discrete)."""

    def __init__(self, n: int):
        self.n = int(n)

    def __repr__(self):
        return f"Discrete({self.n})"


class Box:
    """Real-valued tensor space (reference: gym.spaces.Box)."""

    def __init__(self, shape: Sequence[int], low: float = -float("inf"),
                 high: float = float("inf")):
        self.shape = tuple(int(s) for s in shape)
        self.low = float(low)
        self.high = float(high)

    def __repr__(self):
        return f"Box(shape={self.shape})"


class Catalog:
    _custom: Dict[str, Callable] = {}

    @classmethod
    def register_custom_model(cls, name: str, factory: Callable):
        """factory(obs_space, action_space, model_config) -> module."""
        cls._custom[name] = factory

    @classmethod
    def spaces_of(cls, env) -> Tuple[Box, Any]:
        """Derive (obs_space, action_space) from a vec env's attribute
        convention (envs.py: obs_dim / optional obs_shape pixel layout /
        num_actions or action_dim)."""
        obs_shape = getattr(env, "obs_shape", None)
        obs = Box(obs_shape if obs_shape else (env.obs_dim,))
        if getattr(env, "num_actions", None):
            act: Any = Discrete(env.num_actions)
        else:
            act = Box((env.action_dim,), low=-1.0, high=1.0)
        return obs, act

    @classmethod
    def get_module(cls, obs_space: Box, action_space,
                   model_config: Optional[dict] = None):
        """Default policy(+value) module for the space pair."""
        mc = dict(model_config or {})
        custom = mc.pop("custom_model", None)
        if custom is not None:
            return cls._custom[custom](obs_space, action_space, mc)
        hidden = tuple(mc.get("hidden", (64, 64)))
        if isinstance(action_space, Discrete):
            if len(obs_space.shape) == 3:
                kw = {k: mc[k] for k in ("channels", "kernels", "strides")
                      if k in mc}
                return CNNModule(obs_space.shape, action_space.n,
                                 hidden=mc.get("hidden", (128,)), **kw)
            if len(obs_space.shape) == 1:
                return MLPModule(obs_space.shape[0], action_space.n,
                                 hidden=hidden)
            raise ValueError(
                f"no default model for obs shape {obs_space.shape}")
        if isinstance(action_space, Box):
            if len(obs_space.shape) != 1:
                raise ValueError(
                    "continuous control needs flat observations; got "
                    f"{obs_space.shape}")
            return SquashedGaussianModule(
                obs_space.shape[0], action_space.shape[0],
                action_low=action_space.low, action_high=action_space.high,
                hidden=mc.get("hidden", (128, 128)))
        raise ValueError(f"unsupported action space {action_space!r}")

    @classmethod
    def get_q_module(cls, obs_space: Box, action_space,
                     model_config: Optional[dict] = None):
        """Default Q-network for the space pair (DQN / SAC critics)."""
        mc = dict(model_config or {})
        hidden = tuple(mc.get("hidden", (128, 128)))
        if isinstance(action_space, Discrete):
            return QMLPModule(obs_space.shape[0], action_space.n,
                              hidden=hidden)
        if isinstance(action_space, Box):
            return TwinQModule(obs_space.shape[0], action_space.shape[0],
                               hidden=hidden)
        raise ValueError(f"unsupported action space {action_space!r}")
