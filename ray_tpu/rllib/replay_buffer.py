"""Replay buffers for off-policy algorithms (DQN / SAC).

Reference analogues: rllib/utils/replay_buffers/replay_buffer.py and
prioritized_episode_buffer — there, lists of episode objects; here flat
preallocated numpy rings (cheap vectorized sampling feeds a single jitted
multi-minibatch update, see dqn.py). Wrap in ``ray_tpu.remote`` for a
shared buffer actor when runners and learner live in different processes.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np


class ReplayBuffer:
    """Uniform ring buffer over transition columns."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = int(capacity)
        self._cols: Optional[Dict[str, np.ndarray]] = None
        self._size = 0
        self._next = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        if self._cols is None:
            self._cols = {
                k: np.empty((self.capacity,) + np.asarray(v).shape[1:],
                            np.asarray(v).dtype)
                for k, v in batch.items()
            }
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._cols[k][idx] = v
        self._next = int((self._next + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))

    def sample_indices(self, batch_size: int) -> np.ndarray:
        return self._rng.integers(0, self._size, size=batch_size)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self.sample_indices(batch_size)
        return {k: v[idx] for k, v in self._cols.items()}

    def sample_many(self, num_batches: int, batch_size: int
                    ) -> Dict[str, np.ndarray]:
        """Stacked [U, B, ...] columns for one-dispatch scan updates."""
        idx = self._rng.integers(0, self._size,
                                 size=(num_batches, batch_size))
        return {k: v[idx] for k, v in self._cols.items()}


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (PER, Schaul et al. 2015).

    Priorities are kept as a flat numpy array; sampling is a single
    vectorized choice over p^alpha — O(n) per sample round, fine for the
    <=1e6-entry buffers this framework targets (no sum-tree needed to feed
    a TPU-rate learner).
    """

    def __init__(self, capacity: int, alpha: float = 0.6,
                 beta: float = 0.4, seed: int = 0):
        super().__init__(capacity, seed=seed)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self._prio = np.zeros(capacity, np.float64)
        self._max_prio = 1.0

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        idx = (self._next + np.arange(n)) % self.capacity
        super().add_batch(batch)
        self._prio[idx] = self._max_prio

    def _probs(self) -> np.ndarray:
        p = self._prio[: self._size] ** self.alpha
        return p / p.sum()

    def sample_many(self, num_batches: int, batch_size: int
                    ) -> Dict[str, np.ndarray]:
        probs = self._probs()
        idx = self._rng.choice(self._size, size=(num_batches, batch_size),
                               p=probs)
        weights = (self._size * probs[idx]) ** (-self.beta)
        weights /= weights.max()
        out = {k: v[idx] for k, v in self._cols.items()}
        out["weights"] = weights.astype(np.float32)
        out["_indices"] = idx
        return out

    def update_priorities(self, indices: np.ndarray,
                          td_errors: np.ndarray) -> None:
        prio = np.abs(np.asarray(td_errors, np.float64)).reshape(-1) + 1e-6
        self._prio[np.asarray(indices).reshape(-1)] = prio
        self._max_prio = max(self._max_prio, float(prio.max()))
