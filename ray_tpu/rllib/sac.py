"""SAC: soft actor-critic for continuous control, JAX Learner path.

Reference: rllib/algorithms/sac/sac.py. Twin Q critics with polyak
targets, tanh-squashed Gaussian actor with the reparameterization trick,
and automatic temperature tuning toward target entropy -|A| (Haarnoja et
al. 2018). As in dqn.py, a train iteration runs all U minibatch updates
(critic + actor + alpha + polyak) inside ONE jitted ``lax.scan``.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import AlgorithmConfig, RunnerDriver
from ray_tpu.rllib.replay_buffer import ReplayBuffer
from ray_tpu.rllib.rl_module import (SquashedGaussianModule, TwinQModule,
                                     to_numpy)


class SACLearner:
    def __init__(self, actor: SquashedGaussianModule, critic: TwinQModule,
                 lr: float = 3e-4, gamma: float = 0.99, tau: float = 0.005,
                 init_alpha: float = 0.1, seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        self.actor = actor
        self.critic = critic
        self.pi_params = actor.init_params(seed)
        self.q_params = critic.init_params(seed + 1)
        self.q_target = jax.tree_util.tree_map(jnp.array, self.q_params)
        self.log_alpha = jnp.log(jnp.asarray(init_alpha))
        self.pi_tx = optax.adam(lr)
        self.q_tx = optax.adam(lr)
        self.a_tx = optax.adam(lr)
        self.pi_opt = self.pi_tx.init(self.pi_params)
        self.q_opt = self.q_tx.init(self.q_params)
        self.a_opt = self.a_tx.init(self.log_alpha)
        self._gamma = gamma
        self._tau = tau
        self._target_entropy = -float(actor.action_dim)
        self._rng = jax.random.PRNGKey(seed + 2)
        self._update = jax.jit(self._update_impl,
                               donate_argnums=(0, 1, 2, 3, 4, 5, 6))

    # ---- squashed-Gaussian sample + logp (jax) -------------------------------

    def _pi_sample(self, pi_params, obs, key):
        import jax
        import jax.numpy as jnp

        mu, log_std = self.actor.apply(pi_params, obs)
        std = jnp.exp(log_std)
        pre = mu + std * jax.random.normal(key, mu.shape)
        a_tanh = jnp.tanh(pre)
        # diag-Gaussian logp + tanh change-of-variables correction
        logp = (-0.5 * (((pre - mu) / std) ** 2 + 2 * log_std
                        + jnp.log(2 * jnp.pi))).sum(-1)
        logp -= (2 * (jnp.log(2.0) - pre
                      - jax.nn.softplus(-2 * pre))).sum(-1)
        # change-of-variables for the affine rescale to the env's bounds
        logp -= jnp.log(self.actor.action_scale) * self.actor.action_dim
        action = a_tanh * self.actor.action_scale + self.actor.action_center
        return action, logp

    def _update_impl(self, pi_params, q_params, q_target, log_alpha,
                     pi_opt, q_opt, a_opt, batches, rng):
        import jax
        import jax.numpy as jnp

        def q_loss(q_params, pi_params, q_target, alpha, mb, key):
            a_next, logp_next = self._pi_sample(pi_params, mb["next_obs"],
                                                key)
            tq1, tq2 = self.critic.apply(q_target, mb["next_obs"], a_next)
            target = jax.lax.stop_gradient(
                mb["rewards"] + self._gamma * (1.0 - mb["dones"])
                * (jnp.minimum(tq1, tq2) - alpha * logp_next))
            q1, q2 = self.critic.apply(q_params, mb["obs"], mb["actions"])
            return (jnp.square(q1 - target).mean()
                    + jnp.square(q2 - target).mean())

        def pi_loss(pi_params, q_params, alpha, mb, key):
            a, logp = self._pi_sample(pi_params, mb["obs"], key)
            q1, q2 = self.critic.apply(q_params, mb["obs"], a)
            return (alpha * logp - jnp.minimum(q1, q2)).mean(), logp

        def alpha_loss(log_alpha, logp):
            return -(jnp.exp(log_alpha)
                     * jax.lax.stop_gradient(
                         logp + self._target_entropy)).mean()

        def step(carry, xs):
            (pi_params, q_params, q_target, log_alpha, pi_opt, q_opt,
             a_opt) = carry
            mb, key = xs
            kq, kp = jax.random.split(key)
            alpha = jnp.exp(log_alpha)

            ql, qg = jax.value_and_grad(q_loss)(
                q_params, pi_params, q_target, alpha, mb, kq)
            qu, q_opt = self.q_tx.update(qg, q_opt, q_params)
            q_params = jax.tree_util.tree_map(lambda p, u: p + u,
                                              q_params, qu)

            (pl, logp), pg = jax.value_and_grad(pi_loss, has_aux=True)(
                pi_params, q_params, alpha, mb, kp)
            pu, pi_opt = self.pi_tx.update(pg, pi_opt, pi_params)
            pi_params = jax.tree_util.tree_map(lambda p, u: p + u,
                                               pi_params, pu)

            al, ag = jax.value_and_grad(alpha_loss)(log_alpha, logp)
            au, a_opt = self.a_tx.update(ag, a_opt, log_alpha)
            log_alpha = log_alpha + au

            q_target = jax.tree_util.tree_map(
                lambda t, p: t + self._tau * (p - t), q_target, q_params)
            metrics = {"q_loss": ql, "pi_loss": pl, "alpha": alpha,
                       "entropy": -logp.mean()}
            return (pi_params, q_params, q_target, log_alpha, pi_opt,
                    q_opt, a_opt), metrics

        U = batches["rewards"].shape[0]
        keys = jax.random.split(rng, U)
        carry = (pi_params, q_params, q_target, log_alpha, pi_opt, q_opt,
                 a_opt)
        carry, metrics = jax.lax.scan(step, carry, (batches, keys))
        metrics = jax.tree_util.tree_map(lambda a: a[-1], metrics)
        return carry + (metrics,)

    def update_many(self, batches: Dict[str, np.ndarray]
                    ) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        self._rng, key = jax.random.split(self._rng)
        jb = {k: jnp.asarray(v) for k, v in batches.items()}
        if jb["actions"].ndim == 2:   # [U, B] -> [U, B, 1]
            jb["actions"] = jb["actions"][..., None]
        (self.pi_params, self.q_params, self.q_target, self.log_alpha,
         self.pi_opt, self.q_opt, self.a_opt, metrics) = self._update(
            self.pi_params, self.q_params, self.q_target, self.log_alpha,
            self.pi_opt, self.q_opt, self.a_opt, jb, key)
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        return to_numpy(self.pi_params)


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.env_name = "Pendulum-v1"
        self.lr = 3e-4
        self.num_env_runners = 2
        self.num_envs_per_runner = 8
        self.rollout_len = 16
        self.module_hidden = (128, 128)
        self.train_kwargs = {
            "buffer_size": 100_000,
            "learning_starts": 1_000,
            "batch_size": 128,
            "updates_per_iter": 16,
            "tau": 0.005,
            "init_alpha": 0.1,
        }

    def build(self) -> "SAC":
        return SAC(self)


class SAC(RunnerDriver):
    def __init__(self, config: SACConfig):
        from ray_tpu.rllib.env_runner import OffPolicyRunner
        from ray_tpu.rllib.envs import make_env

        self.config = config
        kw = config.train_kwargs
        probe = make_env(config.env_name, 1)
        self.module_spec = {
            "obs_dim": probe.obs_dim, "action_dim": probe.action_dim,
            "action_low": probe.action_low, "action_high": probe.action_high,
            "hidden": config.module_hidden,
        }
        actor = SquashedGaussianModule(**self.module_spec)
        critic = TwinQModule(probe.obs_dim, probe.action_dim,
                             hidden=config.module_hidden)
        self.learner = SACLearner(actor, critic, lr=config.lr,
                                  gamma=config.gamma, tau=kw["tau"],
                                  init_alpha=kw["init_alpha"],
                                  seed=config.seed)
        self.buffer = ReplayBuffer(kw["buffer_size"], seed=config.seed)
        self.runners = [
            OffPolicyRunner.remote(config.env_name,
                                   config.num_envs_per_runner,
                                   self.module_spec, kind="sac",
                                   seed=config.seed + 1000 * i)
            for i in range(config.num_env_runners)
        ]
        self._init_driver()

    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        kw = self.config.train_kwargs
        w_ref = ray_tpu.put(self.learner.get_weights())
        batches = ray_tpu.get(
            [r.sample_transitions.remote(w_ref, self.config.rollout_len)
             for r in self.runners], timeout=300)
        for b in batches:
            self._record_returns(b)
            self.env_steps += len(b["rewards"])
            self.buffer.add_batch(b)

        metrics: Dict[str, float] = {}
        if len(self.buffer) >= kw["learning_starts"]:
            stacked = self.buffer.sample_many(kw["updates_per_iter"],
                                              kw["batch_size"])
            metrics = self.learner.update_many(stacked)
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": self._mean_return(),
            "num_env_steps_sampled": self.env_steps,
            "time_this_iter_s": time.perf_counter() - t0,
            **metrics,
        }
