"""External-env policy serving: train from environments that live
OUTSIDE the cluster.

Reference: rllib/env/policy_server_input.py (server side) and
rllib/env/policy_client.py (client side). An external process — a game,
a simulator farm, a production system — connects over the cluster RPC
plane (authkey'd framed-pickle TCP, the same substrate the node/GCS
links ride), asks the server for actions, and reports rewards. The
server runs inference with the CURRENT weights, logs the transitions,
and hands the trainer time-major [T, 1] batches in the same schema the
IMPALA/APPO learners consume — so off-policy correction covers the
client's action-to-training lag exactly like runner lag.
"""

from __future__ import annotations

import collections
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.core.cluster.rpc import RpcClient, RpcServer
from ray_tpu.rllib.rl_module import build_pv_module, to_numpy


class PolicyServerInput:
    """Action server + transition collector for external envs."""

    def __init__(self, module_spec: dict, host: str = "127.0.0.1",
                 port: int = 0, authkey: Optional[bytes] = None,
                 seed: int = 0):
        self.module = build_pv_module(module_spec)
        self._weights = to_numpy(self.module.init_params(seed))
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()
        self._episodes: Dict[str, dict] = {}   # eid -> open step state
        self._steps: collections.deque = collections.deque()
        self._returns: collections.deque = collections.deque(maxlen=64)
        self._next_eid = 0
        self._authkey = authkey or os.urandom(16)
        self._server = RpcServer(self._handle, self._authkey,
                                 host=host, port=port)
        self.address = self._server.address

    @property
    def authkey(self) -> bytes:
        return self._authkey

    # ------------------------------------------------------------ RPC side

    def _handle(self, msg, ctx):
        op = msg[0]
        if op == "start_episode":
            import time as _time

            with self._lock:
                # GC abandoned episodes (client died mid-episode): a
                # long-lived serving deployment must not leak one dict
                # (plus pending obs/logits) per crashed client
                now = _time.monotonic()
                for eid in [e for e, st in self._episodes.items()
                            if now - st.get("ts", now) > 600.0]:
                    del self._episodes[eid]
                while len(self._episodes) > 4096:
                    self._episodes.pop(next(iter(self._episodes)))
                eid = f"ep_{self._next_eid}"
                self._next_eid += 1
                self._episodes[eid] = {"pending": None, "return": 0.0,
                                       "ts": now}
            return eid
        if op == "get_action":
            _, eid, obs = msg
            obs = np.asarray(obs, np.float32)
            logits, _ = self.module.apply_np(self._weights, obs[None])
            logits = logits[0]
            g = self._rng.gumbel(size=logits.shape)
            action = int(np.argmax(logits + g))
            import time as _time

            with self._lock:
                ep = self._episodes[eid]
                ep["ts"] = _time.monotonic()
                self._close_step(ep, next_obs=obs, done=False)
                ep["pending"] = {"obs": obs, "action": action,
                                 "logits": logits, "reward": 0.0}
            return action
        if op == "log_returns":
            _, eid, reward = msg
            with self._lock:
                ep = self._episodes[eid]
                if ep["pending"] is not None:
                    ep["pending"]["reward"] += float(reward)
                ep["return"] += float(reward)
            return True
        if op == "end_episode":
            _, eid, last_obs = msg
            with self._lock:
                ep = self._episodes.pop(eid)
                self._close_step(ep, np.asarray(last_obs, np.float32),
                                 done=True)
                self._returns.append(ep["return"])
            return True
        if op == "ping":
            return "pong"
        raise ValueError(f"unknown policy-server op {op!r}")

    def _close_step(self, ep: dict, next_obs: np.ndarray, done: bool):
        """The previous pending step learns its successor (lock held)."""
        p = ep["pending"]
        if p is None:
            return
        self._steps.append((p["obs"], next_obs, p["action"], p["logits"],
                            p["reward"], done, done))
        ep["pending"] = None

    # -------------------------------------------------------- trainer side

    def set_weights(self, weights):
        self._weights = weights

    def steps_ready(self) -> int:
        return len(self._steps)

    def episode_returns(self) -> List[float]:
        with self._lock:
            out = list(self._returns)
            self._returns.clear()
        return out

    def next_batch(self, rollout_len: int) -> Optional[Dict[str, Any]]:
        """[T, 1] time-major batch in the IMPALA/APPO learner schema, or
        None until enough client steps accumulated."""
        with self._lock:
            if len(self._steps) < rollout_len:
                return None
            steps = [self._steps.popleft() for _ in range(rollout_len)]
        obs, nxt, act, logits, rew, term, done = zip(*steps)
        return {
            "obs": np.stack(obs)[:, None, :],
            "next_obs": np.stack(nxt)[:, None, :],
            "actions": np.asarray(act, np.int32)[:, None],
            "behavior_logits": np.stack(logits)[:, None, :],
            "rewards": np.asarray(rew, np.float32)[:, None],
            "terminateds": np.asarray(term, bool)[:, None],
            "dones": np.asarray(done, bool)[:, None],
        }

    def close(self):
        self._server.close()


class PolicyClient:
    """External-process client (reference: rllib/env/policy_client.py).

    Drives episodes against a remote PolicyServerInput:

        client = PolicyClient(addr, authkey)
        eid = client.start_episode()
        a = client.get_action(eid, obs)
        client.log_returns(eid, reward)
        client.end_episode(eid, last_obs)
    """

    def __init__(self, address: Tuple[str, int], authkey: bytes):
        self._client = RpcClient(tuple(address), authkey)

    def start_episode(self) -> str:
        return self._client.call(("start_episode",))

    def get_action(self, episode_id: str, obs) -> int:
        return self._client.call(
            ("get_action", episode_id,
             np.asarray(obs, np.float32).tolist()))

    def log_returns(self, episode_id: str, reward: float):
        self._client.call(("log_returns", episode_id, float(reward)))

    def end_episode(self, episode_id: str, obs):
        self._client.call(
            ("end_episode", episode_id,
             np.asarray(obs, np.float32).tolist()))

    def close(self):
        self._client.close()
