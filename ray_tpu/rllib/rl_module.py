"""RLModule: the model abstraction (reference: rllib/core/rl_module/).

Pure-function design: a module is (init_params, apply) over a jax pytree —
the same params run in the Learner (jitted update on TPU/CPU) and in
EnvRunners (host-side numpy inference), with no framework object to ship.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import numpy as np


class MLPModule:
    """Policy+value MLP with shared trunk (discrete actions)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hidden: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init_params(self, seed: int = 0) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        keys = jax.random.split(jax.random.PRNGKey(seed),
                                len(self.hidden) + 2)
        sizes = (self.obs_dim,) + self.hidden
        params = {"trunk": []}
        for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
            w = jax.random.normal(keys[i], (a, b)) * np.sqrt(2.0 / a)
            params["trunk"].append({"w": w, "b": jnp.zeros((b,))})
        h = sizes[-1]
        params["pi"] = {
            "w": jax.random.normal(keys[-2], (h, self.num_actions)) * 0.01,
            "b": jnp.zeros((self.num_actions,)),
        }
        params["v"] = {"w": jax.random.normal(keys[-1], (h, 1)) * 1.0,
                       "b": jnp.zeros((1,))}
        return params

    def apply(self, params, obs) -> Tuple[Any, Any]:
        """obs [B, obs_dim] -> (logits [B, A], value [B]). jax-traceable."""
        import jax.numpy as jnp

        x = obs
        for layer in params["trunk"]:
            x = jnp.tanh(x @ layer["w"] + layer["b"])
        logits = x @ params["pi"]["w"] + params["pi"]["b"]
        value = (x @ params["v"]["w"] + params["v"]["b"])[..., 0]
        return logits, value

    # -- host-side (EnvRunner) inference: numpy mirror of apply ------------

    def apply_np(self, params_np, obs: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray]:
        x = obs
        for layer in params_np["trunk"]:
            x = np.tanh(x @ layer["w"] + layer["b"])
        logits = x @ params_np["pi"]["w"] + params_np["pi"]["b"]
        value = (x @ params_np["v"]["w"] + params_np["v"]["b"])[..., 0]
        return logits, value


def to_numpy(params) -> Any:
    import jax

    return jax.tree_util.tree_map(lambda a: np.asarray(a), params)
