"""RLModule: the model abstraction (reference: rllib/core/rl_module/).

Pure-function design: a module is (init_params, apply) over a jax pytree —
the same params run in the Learner (jitted update on TPU/CPU) and in
EnvRunners (host-side numpy inference), with no framework object to ship.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import numpy as np


class MLPModule:
    """Policy+value MLP with shared trunk (discrete actions)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hidden: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init_params(self, seed: int = 0) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        keys = jax.random.split(jax.random.PRNGKey(seed),
                                len(self.hidden) + 2)
        sizes = (self.obs_dim,) + self.hidden
        params = {"trunk": []}
        for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
            w = jax.random.normal(keys[i], (a, b)) * np.sqrt(2.0 / a)
            params["trunk"].append({"w": w, "b": jnp.zeros((b,))})
        h = sizes[-1]
        params["pi"] = {
            "w": jax.random.normal(keys[-2], (h, self.num_actions)) * 0.01,
            "b": jnp.zeros((self.num_actions,)),
        }
        params["v"] = {"w": jax.random.normal(keys[-1], (h, 1)) * 1.0,
                       "b": jnp.zeros((1,))}
        return params

    def apply(self, params, obs) -> Tuple[Any, Any]:
        """obs [B, obs_dim] -> (logits [B, A], value [B]). jax-traceable."""
        import jax.numpy as jnp

        x = obs
        for layer in params["trunk"]:
            x = jnp.tanh(x @ layer["w"] + layer["b"])
        logits = x @ params["pi"]["w"] + params["pi"]["b"]
        value = (x @ params["v"]["w"] + params["v"]["b"])[..., 0]
        return logits, value

    # -- host-side (EnvRunner) inference: numpy mirror of apply ------------

    def apply_np(self, params_np, obs: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray]:
        x = obs
        for layer in params_np["trunk"]:
            x = np.tanh(x @ layer["w"] + layer["b"])
        logits = x @ params_np["pi"]["w"] + params_np["pi"]["b"]
        value = (x @ params_np["v"]["w"] + params_np["v"]["b"])[..., 0]
        return logits, value


class CNNModule:
    """Policy+value CONV encoder for pixel observations (reference:
    rllib/core/models/torch/encoder.py:107 TorchCNNEncoder + the Atari
    PPO/IMPALA configs). TPU-first: the convs are lax.conv NHWC programs
    that jit into the same single-program learners as the MLP; observations
    travel FLAT [B, H*W*C] through the runner/learner plumbing (so buffers
    and batching stay shape-agnostic) and are reshaped inside apply.

    Host-side inference jits the same pure function on CPU once per
    process — a hand-written numpy conv would be slower than the XLA CPU
    kernel it duplicates."""

    def __init__(self, obs_shape: Sequence[int], num_actions: int,
                 channels: Sequence[int] = (16, 32),
                 kernels: Sequence[int] = (4, 3),
                 strides: Sequence[int] = (2, 1),
                 hidden: Sequence[int] = (128,), obs_dim: int = 0):
        del obs_dim  # derived from obs_shape; accepted for spec parity
        self.obs_shape = tuple(obs_shape)      # (H, W, C)
        self.obs_dim = int(np.prod(obs_shape))
        self.num_actions = num_actions
        self.channels = tuple(channels)
        self.kernels = tuple(kernels)
        self.strides = tuple(strides)
        self.hidden = tuple(hidden)
        self._apply_cpu = None

    def _conv_out_size(self) -> int:
        h, w, _ = self.obs_shape
        for k, s in zip(self.kernels, self.strides):
            h = (h - k) // s + 1
            w = (w - k) // s + 1
        return h * w * self.channels[-1]

    def init_params(self, seed: int = 0) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        n_conv = len(self.channels)
        keys = jax.random.split(jax.random.PRNGKey(seed),
                                n_conv + len(self.hidden) + 2)
        params: Dict[str, Any] = {"conv": []}
        cin = self.obs_shape[-1]
        for i, (cout, k) in enumerate(zip(self.channels, self.kernels)):
            fan_in = k * k * cin
            params["conv"].append({
                "w": jax.random.normal(keys[i], (k, k, cin, cout))
                * np.sqrt(2.0 / fan_in),
                "b": jnp.zeros((cout,)),
            })
            cin = cout
        sizes = (self._conv_out_size(),) + self.hidden
        params["trunk"] = []
        for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
            params["trunk"].append({
                "w": jax.random.normal(keys[n_conv + i], (a, b))
                * np.sqrt(2.0 / a),
                "b": jnp.zeros((b,)),
            })
        h = sizes[-1]
        params["pi"] = {
            "w": jax.random.normal(keys[-2], (h, self.num_actions)) * 0.01,
            "b": jnp.zeros((self.num_actions,)),
        }
        params["v"] = {"w": jax.random.normal(keys[-1], (h, 1)),
                       "b": jnp.zeros((1,))}
        return params

    def apply(self, params, obs) -> Tuple[Any, Any]:
        """obs [B, H*W*C] -> (logits [B, A], value [B]). jax-traceable."""
        import jax
        import jax.numpy as jnp

        x = obs.reshape((-1,) + self.obs_shape)
        for layer, s in zip(params["conv"], self.strides):
            x = jax.lax.conv_general_dilated(
                x, layer["w"], window_strides=(s, s), padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            x = jax.nn.relu(x + layer["b"])
        x = x.reshape((x.shape[0], -1))
        for layer in params["trunk"]:
            x = jnp.tanh(x @ layer["w"] + layer["b"])
        logits = x @ params["pi"]["w"] + params["pi"]["b"]
        value = (x @ params["v"]["w"] + params["v"]["b"])[..., 0]
        return logits, value

    def apply_np(self, params_np, obs: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray]:
        """Runner-side inference: the SAME pure function, jitted once on
        the host CPU (XLA's conv beats any numpy re-implementation)."""
        import jax

        if self._apply_cpu is None:
            cpu = jax.devices("cpu")[0]
            self._apply_cpu = jax.jit(self.apply, device=cpu)
        logits, value = self._apply_cpu(params_np, obs)
        return np.asarray(logits), np.asarray(value)


def build_pv_module(spec: dict):
    """Policy+value module from a spec dict: pixel specs (obs_shape) get
    the conv encoder, vector specs the MLP."""
    if spec.get("obs_shape"):
        return CNNModule(**spec)
    return MLPModule(**{k: v for k, v in spec.items()
                        if k != "obs_shape"})


def _init_mlp(keys, sizes, out_scale_last: float = 0.01):
    """He-init dense stack; last layer down-scaled (stable policy heads)."""
    import jax
    import jax.numpy as jnp

    layers = []
    for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
        scale = out_scale_last if i == len(sizes) - 2 else np.sqrt(2.0 / a)
        layers.append({"w": jax.random.normal(keys[i], (a, b)) * scale,
                       "b": jnp.zeros((b,))})
    return layers


def _mlp_np(layers, x, act=np.tanh):
    for layer in layers[:-1]:
        x = act(x @ layer["w"] + layer["b"])
    return x @ layers[-1]["w"] + layers[-1]["b"]


def _mlp_jax(layers, x, act="tanh"):
    """jax mirror of _mlp_np (act on hidden layers, linear last)."""
    import jax

    act_fn = {"tanh": jax.numpy.tanh, "relu": jax.nn.relu}[act]
    for layer in layers[:-1]:
        x = act_fn(x @ layer["w"] + layer["b"])
    return x @ layers[-1]["w"] + layers[-1]["b"]


class QMLPModule:
    """State-action value MLP for discrete actions (DQN family).

    apply(params, obs) -> Q [B, num_actions]. Reference analogue:
    rllib/algorithms/dqn/torch/dqn_torch_rl_module.py (the compute_q_values
    path); here a pure function over a pytree.
    """

    def __init__(self, obs_dim: int, num_actions: int,
                 hidden: Sequence[int] = (128, 128)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init_params(self, seed: int = 0) -> Dict[str, Any]:
        import jax

        sizes = (self.obs_dim,) + self.hidden + (self.num_actions,)
        keys = jax.random.split(jax.random.PRNGKey(seed), len(sizes) - 1)
        return {"q": _init_mlp(keys, sizes, out_scale_last=0.01)}

    def apply(self, params, obs):
        return _mlp_jax(params["q"], obs)

    def apply_np(self, params_np, obs: np.ndarray) -> np.ndarray:
        return _mlp_np(params_np["q"], obs)


LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


class SquashedGaussianModule:
    """Tanh-squashed Gaussian policy for continuous actions (SAC actor).

    apply(params, obs) -> (mu [B, D], log_std [B, D]); sampling + the tanh
    log-prob correction live in the learner (jax) and runner (numpy).
    """

    def __init__(self, obs_dim: int, action_dim: int,
                 action_low: float = -1.0, action_high: float = 1.0,
                 hidden: Sequence[int] = (128, 128)):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.action_low = float(action_low)
        self.action_high = float(action_high)
        self.hidden = tuple(hidden)

    @property
    def action_scale(self) -> float:
        return (self.action_high - self.action_low) / 2.0

    @property
    def action_center(self) -> float:
        return (self.action_high + self.action_low) / 2.0

    def init_params(self, seed: int = 0) -> Dict[str, Any]:
        import jax

        sizes = (self.obs_dim,) + self.hidden + (2 * self.action_dim,)
        keys = jax.random.split(jax.random.PRNGKey(seed), len(sizes) - 1)
        return {"pi": _init_mlp(keys, sizes, out_scale_last=0.01)}

    def apply(self, params, obs):
        import jax.numpy as jnp

        out = _mlp_jax(params["pi"], obs)
        mu, log_std = jnp.split(out, 2, axis=-1)
        return mu, jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)

    def apply_np(self, params_np, obs: np.ndarray):
        out = _mlp_np(params_np["pi"], obs)
        mu, log_std = np.split(out, 2, axis=-1)
        return mu, np.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)

    def sample_np(self, params_np, obs: np.ndarray, rng: np.random.Generator,
                  deterministic: bool = False) -> np.ndarray:
        """Environment-frame action (squashed + rescaled), runner-side."""
        mu, log_std = self.apply_np(params_np, obs)
        pre = mu if deterministic else (
            mu + np.exp(log_std) * rng.standard_normal(mu.shape))
        return np.tanh(pre) * self.action_scale + self.action_center


class TwinQModule:
    """Two independent Q(s, a) critics (SAC / TD3 style)."""

    def __init__(self, obs_dim: int, action_dim: int,
                 hidden: Sequence[int] = (128, 128)):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.hidden = tuple(hidden)

    def init_params(self, seed: int = 0) -> Dict[str, Any]:
        import jax

        sizes = (self.obs_dim + self.action_dim,) + self.hidden + (1,)
        k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
        keys1 = jax.random.split(k1, len(sizes) - 1)
        keys2 = jax.random.split(k2, len(sizes) - 1)
        return {"q1": _init_mlp(keys1, sizes, out_scale_last=1.0),
                "q2": _init_mlp(keys2, sizes, out_scale_last=1.0)}

    def apply(self, params, obs, action):
        import jax.numpy as jnp

        x0 = jnp.concatenate([obs, action], axis=-1)
        # relu (not tanh): Q targets can be large-magnitude (e.g.
        # Pendulum returns ~-1500) and tanh hidden layers saturate
        return tuple(_mlp_jax(params[name], x0, act="relu")[..., 0]
                     for name in ("q1", "q2"))


def to_numpy(params) -> Any:
    import jax

    return jax.tree_util.tree_map(lambda a: np.asarray(a), params)
