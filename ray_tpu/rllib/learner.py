"""PPO Learner: the whole epoch is ONE jitted program.

Reference analogue: rllib/core/learner/learner.py:116 +
ppo_torch_learner — there, each minibatch is a separate eager torch step;
here the permutation, minibatching, and every SGD step run inside a single
``lax.scan`` under jit, so a full PPO epoch set costs one dispatch (the
TPU-first shape: static batch sizes, no host round-trips mid-update).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np


class PPOLearner:
    def __init__(self, module, lr: float = 3e-4, clip: float = 0.2,
                 vf_coef: float = 0.5, ent_coef: float = 0.01,
                 num_epochs: int = 10, minibatch_size: int = 256,
                 max_grad_norm: float = 0.5, seed: int = 0):
        import jax
        import optax

        self.module = module
        self.params = module.init_params(seed)
        self.tx = optax.chain(
            optax.clip_by_global_norm(max_grad_norm),
            optax.adam(lr),
        )
        self.opt_state = self.tx.init(self.params)
        self._clip = clip
        self._vf_coef = vf_coef
        self._ent_coef = ent_coef
        self._num_epochs = num_epochs
        self._mb = minibatch_size
        self._update = jax.jit(self._update_impl, donate_argnums=(0, 1))
        self._rng = jax.random.PRNGKey(seed + 1)

    # ---- loss ---------------------------------------------------------------

    def _loss(self, params, batch):
        import jax
        import jax.numpy as jnp

        logits, value = self.module.apply(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None], axis=-1)[:, 0]
        ratio = jnp.exp(logp - batch["logp_old"])
        adv = batch["advantages"]
        pg = -jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - self._clip, 1 + self._clip) * adv).mean()
        vf = 0.5 * jnp.square(value - batch["returns"]).mean()
        ent = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        loss = pg + self._vf_coef * vf - self._ent_coef * ent
        return loss, {"pg_loss": pg, "vf_loss": vf, "entropy": ent}

    # ---- jitted epoch set ---------------------------------------------------

    def _update_impl(self, params, opt_state, batch, rng):
        import jax
        import jax.numpy as jnp

        n = batch["obs"].shape[0]
        mb = self._mb
        num_mb = n // mb
        grad_fn = jax.grad(self._loss, has_aux=True)

        def sgd_step(carry, idx):
            params, opt_state = carry
            minibatch = jax.tree_util.tree_map(lambda a: a[idx], batch)
            grads, aux = grad_fn(params, minibatch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(
                lambda p, u: p + u, params, updates)
            return (params, opt_state), aux

        def epoch(carry, key):
            perm = jax.random.permutation(key, n)[: num_mb * mb]
            idxs = perm.reshape(num_mb, mb)
            carry, aux = jax.lax.scan(sgd_step, carry, idxs)
            return carry, aux

        keys = jax.random.split(rng, self._num_epochs)
        (params, opt_state), aux = jax.lax.scan(
            epoch, (params, opt_state), keys)
        metrics = jax.tree_util.tree_map(lambda a: a[-1, -1], aux)
        return params, opt_state, metrics

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """One training round: num_epochs passes of minibatch SGD."""
        import jax
        import jax.numpy as jnp

        self._rng, key = jax.random.split(self._rng)
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, jb, key)
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        from ray_tpu.rllib.rl_module import to_numpy

        return to_numpy(self.params)
