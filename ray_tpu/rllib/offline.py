"""Offline RL: behavior cloning and conservative Q-learning over Datasets.

Reference: rllib/algorithms/bc/bc.py and rllib/algorithms/cql/cql.py —
there, offline data flows through offline_data readers into the learner;
here the input is a ``ray_tpu.data.Dataset`` (any datasource), iterated
with ``iter_batches`` and fed to a jitted update, so the streaming
executor (backpressure, prefetch) is the offline-data pipeline.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ray_tpu.rllib.rl_module import MLPModule, QMLPModule, to_numpy


class BCLearner:
    """Behavior cloning for discrete actions: maximize logp(a_data | s)."""

    def __init__(self, module: MLPModule, lr: float = 1e-3, seed: int = 0):
        import jax
        import optax

        self.module = module
        self.params = module.init_params(seed)
        self.tx = optax.adam(lr)
        self.opt_state = self.tx.init(self.params)
        self._update = jax.jit(self._update_impl, donate_argnums=(0, 1))

    def _loss(self, params, obs, actions):
        import jax
        import jax.numpy as jnp

        logits, _ = self.module.apply(params, obs)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, actions[:, None], axis=-1)[:, 0]
        return nll.mean()

    def _update_impl(self, params, opt_state, obs, actions):
        import jax

        loss, grads = jax.value_and_grad(self._loss)(params, obs, actions)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    def update(self, batch: Dict[str, np.ndarray]) -> float:
        import jax.numpy as jnp

        self.params, self.opt_state, loss = self._update(
            self.params, self.opt_state,
            jnp.asarray(batch["obs"], jnp.float32),
            jnp.asarray(batch["actions"], jnp.int32))
        return float(loss)

    def get_weights(self):
        return to_numpy(self.params)


class CQLLearner:
    """Discrete CQL(H): double-DQN TD loss + conservative regularizer
    ``alpha_cql * (logsumexp_a Q(s, a) - Q(s, a_data))`` (Kumar et al.
    2020), which penalizes Q on out-of-distribution actions.
    """

    def __init__(self, module: QMLPModule, lr: float = 1e-3,
                 gamma: float = 0.99, tau: float = 0.01,
                 alpha_cql: float = 1.0, seed: int = 0):
        import jax
        import optax

        self.module = module
        self.params = module.init_params(seed)
        import jax.numpy as jnp

        self.target_params = jax.tree_util.tree_map(jnp.array, self.params)
        self.tx = optax.adam(lr)
        self.opt_state = self.tx.init(self.params)
        self._gamma = gamma
        self._tau = tau
        self._alpha = alpha_cql
        self._update = jax.jit(self._update_impl, donate_argnums=(0, 1, 2))

    def _loss(self, params, target_params, mb):
        import jax
        import jax.numpy as jnp

        q = self.module.apply(params, mb["obs"])
        q_sa = jnp.take_along_axis(q, mb["actions"][:, None], axis=-1)[:, 0]
        a_next = jnp.argmax(self.module.apply(params, mb["next_obs"]),
                            axis=-1)
        q_next = jnp.take_along_axis(
            self.module.apply(target_params, mb["next_obs"]),
            a_next[:, None], axis=-1)[:, 0]
        target = jax.lax.stop_gradient(
            mb["rewards"] + self._gamma * (1.0 - mb["dones"]) * q_next)
        td_loss = jnp.square(q_sa - target).mean()
        conservative = (jax.nn.logsumexp(q, axis=-1) - q_sa).mean()
        return td_loss + self._alpha * conservative

    def _update_impl(self, params, target_params, opt_state, mb):
        import jax

        loss, grads = jax.value_and_grad(self._loss)(params, target_params,
                                                     mb)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        target_params = jax.tree_util.tree_map(
            lambda t, p: t + self._tau * (p - t), target_params, params)
        return params, target_params, opt_state, loss

    def update(self, batch: Dict[str, np.ndarray]) -> float:
        import jax.numpy as jnp

        mb = {
            "obs": jnp.asarray(batch["obs"], jnp.float32),
            "actions": jnp.asarray(batch["actions"], jnp.int32),
            "rewards": jnp.asarray(batch["rewards"], jnp.float32),
            "next_obs": jnp.asarray(batch["next_obs"], jnp.float32),
            "dones": jnp.asarray(batch["dones"], jnp.float32),
        }
        self.params, self.target_params, self.opt_state, loss = (
            self._update(self.params, self.target_params, self.opt_state,
                         mb))
        return float(loss)

    def get_weights(self):
        return to_numpy(self.params)


class MARWILLearner:
    """Monotonic Advantage Re-Weighted Imitation Learning (reference:
    rllib/algorithms/marwil/marwil.py — Wang et al. 2018). Cloning
    weighted by exponentiated advantage: the policy imitates the data's
    GOOD actions more than its bad ones, interpolating between pure BC
    (beta=0) and policy improvement. A value head regresses returns; the
    advantage for the weight is ``R - V(s)`` with a running-norm
    (reference: MARWIL's moving average of squared advantages)."""

    def __init__(self, module: MLPModule, lr: float = 1e-3,
                 beta: float = 1.0, vf_coef: float = 1.0, seed: int = 0):
        import jax
        import optax

        self.module = module
        self.params = module.init_params(seed)
        self.tx = optax.adam(lr)
        self.opt_state = self.tx.init(self.params)
        self._beta = beta
        self._vf_coef = vf_coef
        self._ma_adv_sq = 1.0  # running norm (host-side, like the ref)
        self._update = jax.jit(self._update_impl, donate_argnums=(0, 1))

    def _loss(self, params, obs, actions, returns, adv_norm):
        import jax
        import jax.numpy as jnp

        logits, values = self.module.apply(params, obs)
        logp = jax.nn.log_softmax(logits)
        logp_a = jnp.take_along_axis(logp, actions[:, None], axis=-1)[:, 0]
        adv = jax.lax.stop_gradient(returns - values)
        weight = jnp.exp(self._beta * jnp.clip(adv / adv_norm, -5.0, 5.0))
        pg_loss = -(jax.lax.stop_gradient(weight) * logp_a).mean()
        vf_loss = jnp.square(values - returns).mean()
        return (pg_loss + self._vf_coef * vf_loss,
                (jnp.square(adv).mean(),))

    def _update_impl(self, params, opt_state, obs, actions, returns,
                     adv_norm):
        import jax

        (loss, aux), grads = jax.value_and_grad(self._loss, has_aux=True)(
            params, obs, actions, returns, adv_norm)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params,
                                        updates)
        return params, opt_state, loss, aux[0]

    def update(self, batch: Dict[str, np.ndarray]) -> float:
        import jax.numpy as jnp

        adv_norm = max(self._ma_adv_sq, 1e-8) ** 0.5
        self.params, self.opt_state, loss, adv_sq = self._update(
            self.params, self.opt_state,
            jnp.asarray(batch["obs"], jnp.float32),
            jnp.asarray(batch["actions"], jnp.int32),
            jnp.asarray(batch["returns"], jnp.float32),
            jnp.asarray(adv_norm, jnp.float32))
        self._ma_adv_sq = (0.99 * self._ma_adv_sq
                           + 0.01 * float(adv_sq))
        return float(loss)

    def get_weights(self):
        return to_numpy(self.params)


def train_offline(learner, dataset, *, num_epochs: int = 1,
                  batch_size: int = 256, shuffle: bool = True) -> float:
    """Drive a BC/CQL learner over a Dataset; returns the last loss.

    With ``shuffle``, each epoch re-executes the pipeline with a full
    ``random_shuffle`` (new permutation per epoch).
    """
    loss = float("nan")
    for _ in range(num_epochs):
        ds = dataset.random_shuffle() if shuffle else dataset
        for batch in ds.iter_batches(batch_size=batch_size):
            if len(next(iter(batch.values()))) < 2:
                continue
            loss = learner.update(batch)
    return loss


def write_sample_batch_json(batches, path: str) -> int:
    """Persist sample batches as JSON-lines (reference:
    rllib/offline/json_writer.py — one JSON object per batch, array
    columns as lists). Returns the number of batches written."""
    import json

    n = 0
    with open(path, "w") as f:
        for batch in batches:
            obj = {k: np.asarray(v).tolist() for k, v in batch.items()}
            f.write(json.dumps(obj) + "\n")
            n += 1
    return n


def read_sample_batch_json(paths):
    """Load JSON-lines sample batches into a row-per-transition Dataset
    ready for ``train_offline`` (reference: rllib/offline/json_reader.py
    feeding the learner; here the Data streaming executor IS the
    offline pipeline)."""
    import json

    from ray_tpu import data as rdata

    ds = rdata.read_text(paths)

    def expand(batch):
        cols: Dict[str, list] = {}
        for line in np.asarray(batch["text"]).ravel().tolist():
            obj = json.loads(line)
            for k, v in obj.items():
                cols.setdefault(k, []).append(np.asarray(v))
        return {k: np.concatenate(v, axis=0) for k, v in cols.items()}

    return ds.map_batches(expand, batch_format="numpy")


def write_sample_batch_parquet(batches, path: str) -> int:
    """Persist sample batches as parquet, one row per TRANSITION with
    array columns as fixed-width lists (reference:
    rllib/offline/output_writer + the parquet path of offline_data; the
    columnar format is what large offline corpora actually ship as).
    ``path`` is a directory; returns the number of rows written."""
    import json
    import os

    import pyarrow as pa
    import pyarrow.parquet as pq

    os.makedirs(path, exist_ok=True)
    total = 0
    shapes: Dict[str, list] = {}
    for i, batch in enumerate(batches):
        cols = {}
        n = len(next(iter(batch.values())))
        for k, v in batch.items():
            arr = np.asarray(v)
            shp = list(arr.shape[1:])
            if shapes.setdefault(k, shp) != shp:
                raise ValueError(
                    f"column {k!r} has inconsistent trailing shapes "
                    f"across batches: {shapes[k]} vs {shp}")
            if arr.ndim == 1:
                cols[k] = pa.array(arr)
            else:
                # [n, d...] -> flat list column; the trailing shape goes
                # to the sidecar manifest so >2D observations (images)
                # round-trip exactly like the JSON format
                flat = arr.reshape(n, -1)
                cols[k] = pa.FixedSizeListArray.from_arrays(
                    pa.array(flat.ravel()), flat.shape[1])
        table = pa.table(cols)
        pq.write_table(table, os.path.join(path, f"batch-{i:06d}.parquet"))
        total += n
    with open(os.path.join(path, "_shapes.json"), "w") as f:
        json.dump(shapes, f)
    return total


def read_sample_batch_parquet(paths):
    """Load parquet sample batches into a row-per-transition Dataset for
    ``train_offline`` — nested list columns stack back to [n, d] float
    arrays; the streaming executor is the offline pipeline (reference:
    rllib/offline/json_reader.py's role, columnar)."""
    import json
    import os

    from ray_tpu import data as rdata

    shapes: Dict[str, list] = {}
    for root in ([paths] if isinstance(paths, str) else paths):
        m = os.path.join(root, "_shapes.json")
        if os.path.isdir(root) and os.path.exists(m):
            shapes.update(json.load(open(m)))
    ds = rdata.read_parquet(paths)

    def to_arrays(batch):
        out = {}
        for k, v in batch.items():
            arr = np.asarray(v)
            if arr.dtype == object:  # list column -> stacked array
                arr = np.stack([np.asarray(x) for x in arr.ravel()])
            shp = shapes.get(k)
            if shp and list(arr.shape[1:]) != shp:
                arr = arr.reshape((arr.shape[0], *shp))
            out[k] = arr
        return out

    return ds.map_batches(to_arrays, batch_format="numpy")
