"""Multi-agent RL: several policies learning in one environment.

Reference: rllib/env/multi_agent_env.py + the multi-agent paths of
rllib/algorithms/ppo — agent ids map to policy ids via a
``policy_mapping_fn``; each policy trains on the transitions of the
agents it controls (``policies_to_train`` freezes the rest).

The TPU-first shape is unchanged from single-agent PPO: each policy's
whole epoch set is ONE jitted program; the runner collects vectorized
dict-of-agent rollouts host-side and ships per-policy GAE batches.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import AlgorithmConfig, RunnerDriver
from ray_tpu.rllib.learner import PPOLearner
from ray_tpu.rllib.rl_module import MLPModule


class MultiAgentCoordination:
    """Vectorized 2-agent coordination game (a standard multi-agent
    testbed, cf. RLlib's two-step/RPS example envs): both agents pick one
    of K actions each step; both receive +1 when the actions match, 0
    otherwise. Observations are the one-hot previous joint action, so
    coordination ("always play action j") is learnable from history.
    Episodes truncate after ``episode_len`` steps.
    """

    agents = ("a0", "a1")
    num_actions = 3
    episode_len = 8

    def __init__(self, num_envs: int, seed: int = 0):
        self.n = num_envs
        self.obs_dim = 2 * self.num_actions
        self.rng = np.random.default_rng(seed)
        self.prev = np.zeros((num_envs, 2), np.int64)
        self.steps = np.zeros(num_envs, np.int64)
        self.reset()

    def _obs(self) -> Dict[str, np.ndarray]:
        eye = np.eye(self.num_actions, dtype=np.float32)
        joint = np.concatenate([eye[self.prev[:, 0]], eye[self.prev[:, 1]]],
                               axis=1)
        # each agent sees the same joint history
        return {a: joint.copy() for a in self.agents}

    def reset(self) -> Dict[str, np.ndarray]:
        self.prev = self.rng.integers(0, self.num_actions, size=(self.n, 2))
        self.steps[:] = 0
        return self._obs()

    def step(self, actions: Dict[str, np.ndarray]):
        a0 = np.asarray(actions["a0"])
        a1 = np.asarray(actions["a1"])
        match = (a0 == a1).astype(np.float32)
        self.prev = np.stack([a0, a1], axis=1)
        self.steps += 1
        truncated = self.steps >= self.episode_len
        terminated = np.zeros(self.n, bool)
        self.final_obs = self._obs()
        if truncated.any():
            idx = np.nonzero(truncated)[0]
            self.prev[idx] = self.rng.integers(
                0, self.num_actions, size=(len(idx), 2))
            self.steps[idx] = 0
        rew = {a: match.copy() for a in self.agents}
        return self._obs(), rew, terminated, truncated


MULTI_AGENT_ENVS = {"Coordination-v0": MultiAgentCoordination}


def make_multi_agent_env(name: str, num_envs: int, seed: int = 0):
    try:
        return MULTI_AGENT_ENVS[name](num_envs, seed=seed)
    except KeyError:
        raise ValueError(f"unknown multi-agent env {name!r}; registered: "
                         f"{sorted(MULTI_AGENT_ENVS)}") from None


from ray_tpu.rllib.env_runner import _EpisodeTracker


@ray_tpu.remote
class MultiAgentEnvRunner(_EpisodeTracker):
    """Collects joint rollouts; returns one GAE batch per POLICY (agent
    transitions are routed through policy_mapping_fn and concatenated)."""

    def __init__(self, env_name: str, num_envs: int, rollout_len: int,
                 module_spec: dict, policy_ids: List[str],
                 policy_mapping: Dict[str, str], gamma: float = 0.99,
                 lam: float = 0.95, seed: int = 0):
        self.env = make_multi_agent_env(env_name, num_envs, seed=seed)
        self.modules = {pid: MLPModule(**module_spec)
                        for pid in policy_ids}
        self.policy_mapping = policy_mapping
        self.rollout_len = rollout_len
        self.gamma = gamma
        self.lam = lam
        self.rng = np.random.default_rng(seed + 1)
        self.obs = self.env.reset()
        self._init_tracking()

    def sample(self, weights_by_policy: Dict[str, Any]
               ) -> Dict[str, Any]:
        from ray_tpu.rllib.env_runner import _logsumexp

        env = self.env
        T, N = self.rollout_len, env.n
        agents = env.agents
        buf = {a: {"obs": np.empty((T, N, env.obs_dim), np.float32),
                   "next_obs": np.empty((T, N, env.obs_dim), np.float32),
                   "actions": np.empty((T, N), np.int32),
                   "logp": np.empty((T, N), np.float32),
                   "value": np.empty((T, N), np.float32),
                   "reward": np.empty((T, N), np.float32)}
               for a in agents}
        term_buf = np.empty((T, N), bool)
        done_buf = np.empty((T, N), bool)

        obs = self.obs
        for t in range(T):
            actions = {}
            for a in agents:
                pid = self.policy_mapping[a]
                w = weights_by_policy[pid]
                logits, value = self.modules[pid].apply_np(w, obs[a])
                g = self.rng.gumbel(size=logits.shape)
                act = np.argmax(logits + g, axis=-1)
                logp = logits - _logsumexp(logits)
                buf[a]["obs"][t] = obs[a]
                buf[a]["actions"][t] = act
                buf[a]["logp"][t] = np.take_along_axis(
                    logp, act[:, None], axis=-1)[:, 0]
                buf[a]["value"][t] = value
                actions[a] = act
            nxt, rew, term, trunc = env.step(actions)
            done = term | trunc
            for a in agents:
                buf[a]["reward"][t] = rew[a]
                true_next = nxt[a].copy()
                if done.any():
                    true_next[done] = env.final_obs[a][done]
                buf[a]["next_obs"][t] = true_next
            term_buf[t], done_buf[t] = term, done
            # per-env mean-over-agents return tracking
            mean_rew = sum(rew[a] for a in agents) / len(agents)
            self._track_episodes(mean_rew, done)
            obs = nxt
        self.obs = obs

        # per-agent GAE, then group by policy
        per_policy: Dict[str, List[Dict[str, np.ndarray]]] = {}
        not_term = 1.0 - term_buf.astype(np.float32)
        not_done = 1.0 - done_buf.astype(np.float32)
        for a in agents:
            b = buf[a]
            pid = self.policy_mapping[a]
            # V(s'_true): values[t+1] for non-boundary steps (same weights,
            # same state); fresh forward only for boundary columns + last
            # row — mirrors the single-agent runner's optimization
            next_value = np.empty((T, N), np.float32)
            next_value[:-1] = b["value"][1:]
            fresh_t, fresh_i = np.nonzero(done_buf[:-1])
            fresh = ([b["next_obs"][fresh_t, fresh_i]] if len(fresh_t)
                     else [])
            fresh.append(b["next_obs"][T - 1])
            _, fresh_vals = self.modules[pid].apply_np(
                weights_by_policy[pid], np.concatenate(fresh, axis=0))
            if len(fresh_t):
                next_value[fresh_t, fresh_i] = fresh_vals[:len(fresh_t)]
            next_value[T - 1] = fresh_vals[len(fresh_t):]

            adv = np.zeros((T, N), np.float32)
            gae = np.zeros(N, np.float32)
            for t in reversed(range(T)):
                delta = (b["reward"][t]
                         + self.gamma * next_value[t] * not_term[t]
                         - b["value"][t])
                gae = delta + self.gamma * self.lam * not_done[t] * gae
                adv[t] = gae
            ret = adv + b["value"]
            batch = {
                "obs": b["obs"].reshape(T * N, -1),
                "actions": b["actions"].reshape(-1),
                "logp_old": b["logp"].reshape(-1),
                "advantages": adv.reshape(-1),
                "returns": ret.reshape(-1),
            }
            per_policy.setdefault(pid, []).append(batch)

        out = {
            pid: {k: np.concatenate([b[k] for b in batches])
                  for k in batches[0]}
            for pid, batches in per_policy.items()
        }
        out["episode_returns"] = self._drain_completed()
        out["num_env_steps"] = T * N
        return out


class MultiAgentPPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.env_name = "Coordination-v0"
        self.policies: List[str] = ["shared"]
        self.policy_mapping_fn: Callable[[str], str] = lambda aid: "shared"
        self.policies_to_train: Optional[List[str]] = None
        self.train_kwargs = {
            "clip": 0.2, "vf_coef": 0.5, "ent_coef": 0.01,
            "num_epochs": 6, "minibatch_size": 128, "lam": 0.95,
            "max_grad_norm": 0.5,
        }

    def multi_agent(self, *, policies: List[str],
                    policy_mapping_fn: Callable[[str], str],
                    policies_to_train: Optional[List[str]] = None
                    ) -> "MultiAgentPPOConfig":
        self.policies = list(policies)
        self.policy_mapping_fn = policy_mapping_fn
        self.policies_to_train = policies_to_train
        return self

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


class MultiAgentPPO(RunnerDriver):
    """PPO over a policy map: one PPOLearner per policy, runners route
    agent trajectories to their policies (reference: the multi-agent
    Algorithm path + MultiRLModule)."""

    def __init__(self, config: MultiAgentPPOConfig):
        probe = make_multi_agent_env(config.env_name, 1)
        self.config = config
        self.module_spec = {"obs_dim": probe.obs_dim,
                            "num_actions": probe.num_actions,
                            "hidden": config.module_hidden}
        mapping = {a: config.policy_mapping_fn(a) for a in probe.agents}
        unknown = set(mapping.values()) - set(config.policies)
        if unknown:
            raise ValueError(
                f"policy_mapping_fn produced unknown policies {unknown}")
        kw = dict(config.train_kwargs)
        kw.pop("lam", None)
        self.learners: Dict[str, PPOLearner] = {
            pid: PPOLearner(MLPModule(**self.module_spec), lr=config.lr,
                            seed=config.seed + i, **kw)
            for i, pid in enumerate(config.policies)
        }
        self.to_train = (set(config.policies_to_train)
                         if config.policies_to_train is not None
                         else set(config.policies))
        self.runners = [
            MultiAgentEnvRunner.remote(
                config.env_name, config.num_envs_per_runner,
                config.rollout_len, self.module_spec, config.policies,
                mapping, gamma=config.gamma,
                lam=config.train_kwargs.get("lam", 0.95),
                seed=config.seed + 1000 * i)
            for i in range(config.num_env_runners)
        ]
        self._init_driver()

    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        weights = {pid: ln.get_weights()
                   for pid, ln in self.learners.items()}
        w_ref = ray_tpu.put(weights)
        results = ray_tpu.get([r.sample.remote(w_ref)
                               for r in self.runners], timeout=300)
        metrics: Dict[str, float] = {}
        for res in results:
            self._record_returns(res)
            self.env_steps += res.pop("num_env_steps")
        for pid in self.to_train:
            batches = [res[pid] for res in results if pid in res]
            if not batches:
                continue
            batch = {k: np.concatenate([b[k] for b in batches])
                     for k in batches[0]}
            adv = batch["advantages"]
            batch["advantages"] = ((adv - adv.mean())
                                   / (adv.std() + 1e-8)).astype(np.float32)
            for k, v in self.learners[pid].update(batch).items():
                metrics[f"{pid}/{k}"] = v
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": self._mean_return(),
            "num_env_steps_sampled": self.env_steps,
            "time_this_iter_s": time.perf_counter() - t0,
            **metrics,
        }

    def evaluate(self, num_episodes: int = 64) -> float:
        """Mean per-env greedy joint return over one episode, locally."""
        env = make_multi_agent_env(self.config.env_name, num_episodes,
                                   seed=self.config.seed + 999)
        weights = {pid: ln.get_weights()
                   for pid, ln in self.learners.items()}
        mapping = {a: self.config.policy_mapping_fn(a) for a in env.agents}
        modules = {pid: MLPModule(**self.module_spec)
                   for pid in self.config.policies}
        obs = env.reset()
        total = np.zeros(num_episodes, np.float64)
        finished = np.zeros(num_episodes, bool)
        for _ in range(getattr(env, "episode_len", 1000) + 1):
            actions = {}
            for a in env.agents:
                pid = mapping[a]
                logits, _ = modules[pid].apply_np(weights[pid], obs[a])
                actions[a] = np.argmax(logits, axis=-1)
            obs, rew, term, trunc = env.step(actions)
            mean_rew = sum(rew[a] for a in env.agents) / len(env.agents)
            total += mean_rew * (~finished)
            finished |= term | trunc
            if finished.all():
                break
        return float(total.mean())
