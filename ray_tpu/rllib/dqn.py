"""DQN (double DQN + optional PER), JAX Learner path.

Reference: rllib/algorithms/dqn/dqn.py (training_step: sample -> replay ->
N update rounds -> target sync). TPU-first shape: each train iteration
samples U minibatches from replay at once and runs all U SGD steps +
polyak target updates inside ONE jitted ``lax.scan`` — a single dispatch
instead of U eager steps.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import AlgorithmConfig, RunnerDriver
from ray_tpu.rllib.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer
from ray_tpu.rllib.rl_module import QMLPModule, to_numpy


class DQNLearner:
    def __init__(self, module: QMLPModule, lr: float = 1e-3,
                 gamma: float = 0.99, tau: float = 0.01,
                 max_grad_norm: float = 10.0, seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        self.module = module
        self.params = module.init_params(seed)
        # materialize a distinct copy (donation would alias otherwise)
        self.target_params = jax.tree_util.tree_map(jnp.array, self.params)
        self.tx = optax.chain(optax.clip_by_global_norm(max_grad_norm),
                              optax.adam(lr))
        self.opt_state = self.tx.init(self.params)
        self._gamma = gamma
        self._tau = tau
        self._update = jax.jit(self._update_impl, donate_argnums=(0, 1, 2))

    def _loss(self, params, target_params, mb):
        import jax
        import jax.numpy as jnp

        q = self.module.apply(params, mb["obs"])
        q_sa = jnp.take_along_axis(q, mb["actions"][:, None], axis=-1)[:, 0]
        # double DQN: online net picks a', target net evaluates it
        q_next_online = self.module.apply(params, mb["next_obs"])
        a_next = jnp.argmax(q_next_online, axis=-1)
        q_next_target = self.module.apply(target_params, mb["next_obs"])
        q_next = jnp.take_along_axis(q_next_target, a_next[:, None],
                                     axis=-1)[:, 0]
        target = jax.lax.stop_gradient(
            mb["rewards"] + self._gamma * (1.0 - mb["dones"]) * q_next)
        td = q_sa - target
        w = mb.get("weights", jnp.ones_like(td))
        loss = (w * _huber(td)).mean()
        return loss, td

    def _update_impl(self, params, target_params, opt_state, batches):
        import jax

        def step(carry, mb):
            params, target_params, opt_state = carry
            (loss, td), grads = jax.value_and_grad(
                self._loss, has_aux=True)(params, target_params, mb)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(lambda p, u: p + u, params,
                                            updates)
            target_params = jax.tree_util.tree_map(
                lambda t, p: t + self._tau * (p - t), target_params, params)
            return (params, target_params, opt_state), (loss, td)

        (params, target_params, opt_state), (losses, tds) = jax.lax.scan(
            step, (params, target_params, opt_state), batches)
        return params, target_params, opt_state, losses.mean(), tds

    def update_many(self, batches: Dict[str, np.ndarray]):
        """Run U stacked minibatches ([U, B, ...]) in one jitted scan.

        Returns (mean_loss, td_errors [U, B]) — td_errors feed PER
        priority updates.
        """
        import jax.numpy as jnp

        jb = {k: jnp.asarray(v) for k, v in batches.items()
              if k != "_indices"}
        (self.params, self.target_params, self.opt_state, loss,
         tds) = self._update(self.params, self.target_params,
                             self.opt_state, jb)
        return float(loss), np.asarray(tds)

    def get_weights(self):
        return to_numpy(self.params)


def _huber(x, delta: float = 1.0):
    import jax.numpy as jnp

    a = jnp.abs(x)
    return jnp.where(a <= delta, 0.5 * x * x, delta * (a - 0.5 * delta))


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.num_env_runners = 2
        self.num_envs_per_runner = 8
        self.rollout_len = 32           # steps per runner per iteration
        self.module_hidden = (128, 128)
        self.train_kwargs = {
            "buffer_size": 50_000,
            "learning_starts": 1_000,
            "batch_size": 64,
            "updates_per_iter": 16,
            "tau": 0.01,
            "epsilon_initial": 1.0,
            "epsilon_final": 0.05,
            "epsilon_decay_steps": 10_000,
            "prioritized_replay": False,
            "max_grad_norm": 10.0,
        }

    def build(self) -> "DQN":
        return DQN(self)


class DQN(RunnerDriver):
    def __init__(self, config: DQNConfig):
        from ray_tpu.rllib.env_runner import OffPolicyRunner
        from ray_tpu.rllib.envs import make_env

        self.config = config
        kw = config.train_kwargs
        probe = make_env(config.env_name, 1)
        self.module_spec = {"obs_dim": probe.obs_dim,
                            "num_actions": probe.num_actions,
                            "hidden": config.module_hidden}
        self.learner = DQNLearner(QMLPModule(**self.module_spec),
                                  lr=config.lr, gamma=config.gamma,
                                  tau=kw["tau"],
                                  max_grad_norm=kw["max_grad_norm"],
                                  seed=config.seed)
        if kw["prioritized_replay"]:
            self.buffer = PrioritizedReplayBuffer(kw["buffer_size"],
                                                  seed=config.seed)
        else:
            self.buffer = ReplayBuffer(kw["buffer_size"], seed=config.seed)
        self.runners = [
            OffPolicyRunner.remote(config.env_name,
                                   config.num_envs_per_runner,
                                   self.module_spec, kind="dqn",
                                   seed=config.seed + 1000 * i)
            for i in range(config.num_env_runners)
        ]
        self._init_driver()

    def _epsilon(self) -> float:
        kw = self.config.train_kwargs
        frac = min(1.0, self.env_steps / kw["epsilon_decay_steps"])
        return kw["epsilon_initial"] + frac * (
            kw["epsilon_final"] - kw["epsilon_initial"])

    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        kw = self.config.train_kwargs
        weights = self.learner.get_weights()
        w_ref = ray_tpu.put(weights)
        eps = self._epsilon()
        batches = ray_tpu.get(
            [r.sample_transitions.remote(w_ref, self.config.rollout_len,
                                         epsilon=eps)
             for r in self.runners], timeout=300)
        for b in batches:
            self._record_returns(b)
            self.env_steps += len(b["rewards"])
            self.buffer.add_batch(b)

        loss = float("nan")
        if len(self.buffer) >= kw["learning_starts"]:
            stacked = self.buffer.sample_many(kw["updates_per_iter"],
                                              kw["batch_size"])
            indices = stacked.pop("_indices", None)
            loss, tds = self.learner.update_many(stacked)
            if indices is not None:
                self.buffer.update_priorities(indices, tds)
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": self._mean_return(),
            "num_env_steps_sampled": self.env_steps,
            "epsilon": eps,
            "loss": loss,
            "time_this_iter_s": time.perf_counter() - t0,
        }
