"""APPO: asynchronous PPO — the IMPALA architecture with a clipped
surrogate loss against a periodically-updated target network.

Reference: rllib/algorithms/appo/appo.py:277 and the APPO learner
(appo_learner / appo_tf_policy): V-trace advantages are computed with
the TARGET ("old") policy's outputs, the PPO ratio is corrected by a
clipped behavior/target importance ratio, and the target network copies
the live weights every ``target_update_freq`` learner updates. The whole
loss (V-trace scan + clipped surrogate + SGD step) runs as one jitted
program, like the IMPALA learner it extends.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ray_tpu.rllib.impala import IMPALA, ImpalaLearner, IMPALAConfig


class AppoLearner(ImpalaLearner):
    def __init__(self, module, clip_param: float = 0.4,
                 target_update_freq: int = 8, **kw):
        import jax

        super().__init__(module, **kw)
        self._clip = clip_param
        self._target_update_freq = target_update_freq
        self._updates = 0
        self.target_params = jax.tree_util.tree_map(
            lambda p: p.copy(), self.params)
        # re-jit with the target params threaded through
        self._update = jax.jit(self._appo_update_impl,
                               donate_argnums=(0, 1))

    def _loss(self, params, target_params, batch):
        import jax
        import jax.numpy as jnp

        T, N = batch["rewards"].shape
        obs_flat = batch["obs"].reshape(T * N, -1)
        next_flat = batch["next_obs"].reshape(T * N, -1)

        logits, values = self.module.apply(params, obs_flat)
        logits = logits.reshape(T, N, -1)
        values = values.reshape(T, N)

        tgt_logits, tgt_values = self.module.apply(target_params, obs_flat)
        _, tgt_next_values = self.module.apply(target_params, next_flat)
        tgt_logits = jax.lax.stop_gradient(tgt_logits.reshape(T, N, -1))
        tgt_values = jax.lax.stop_gradient(tgt_values.reshape(T, N))
        tgt_next_values = jax.lax.stop_gradient(
            tgt_next_values.reshape(T, N))

        a = batch["actions"][..., None]
        logp_all = jax.nn.log_softmax(logits)
        cur_logp = jnp.take_along_axis(logp_all, a, axis=-1)[..., 0]
        b_logp_all = jax.nn.log_softmax(batch["behavior_logits"])
        behavior_logp = jnp.take_along_axis(b_logp_all, a, axis=-1)[..., 0]
        t_logp_all = jax.nn.log_softmax(tgt_logits)
        tgt_logp = jnp.take_along_axis(t_logp_all, a, axis=-1)[..., 0]

        disc_boot = self._gamma * (1.0 - batch["terminateds"])
        cont = 1.0 - batch["dones"]

        # V-trace against the TARGET policy (reference: APPO computes
        # vtrace with the old_policy's outputs so targets stay stable
        # across the async lag)
        vs, pg_adv = self._vtrace(tgt_logp, behavior_logp, tgt_values,
                                  tgt_next_values, batch["rewards"],
                                  disc_boot, cont)

        # clipped-surrogate with the behavior->target importance
        # correction (reference: appo_tf_policy is_ratio clip to [0, 2])
        is_ratio = jnp.clip(jnp.exp(behavior_logp - tgt_logp), 0.0, 2.0)
        ratio = is_ratio * jnp.exp(cur_logp - behavior_logp)
        surr = jnp.minimum(
            pg_adv * ratio,
            pg_adv * jnp.clip(ratio, 1.0 - self._clip, 1.0 + self._clip))
        pg_loss = -surr.mean()
        vf_loss = 0.5 * jnp.square(vs - values).mean()
        ent = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        loss = pg_loss + self._vf_coef * vf_loss - self._ent_coef * ent
        return loss, {"pg_loss": pg_loss, "vf_loss": vf_loss,
                      "entropy": ent}

    def _appo_update_impl(self, params, opt_state, target_params, batch):
        import jax

        grads, aux = jax.grad(self._loss, has_aux=True)(
            params, target_params, batch)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params,
                                        updates)
        return params, opt_state, aux

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        jb["dones"] = jb["dones"].astype(jnp.float32)
        jb["terminateds"] = jb["terminateds"].astype(jnp.float32)
        self.params, self.opt_state, aux = self._update(
            self.params, self.opt_state, self.target_params, jb)
        self._updates += 1
        if self._updates % self._target_update_freq == 0:
            self.target_params = jax.tree_util.tree_map(
                lambda p: p.copy(), self.params)
        return {k: float(v) for k, v in aux.items()}


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.train_kwargs = {
            "vf_coef": 0.5, "ent_coef": 0.01, "rho_bar": 1.0,
            "c_bar": 1.0, "max_grad_norm": 40.0,
            "clip_param": 0.4, "target_update_freq": 8,
            "batches_per_iter": 8,
        }

    def build(self) -> "APPO":
        return APPO(self)


class APPO(IMPALA):
    """IMPALA runner gang + APPO learner (reference: appo.py:277 — APPO
    subclasses Impala the same way)."""

    LEARNER_CLS = AppoLearner
