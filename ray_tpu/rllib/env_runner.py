"""EnvRunner: an actor collecting vectorized rollouts.

Reference: rllib/env/env_runner.py:22 / single_agent_env_runner. The gang
of runners samples in parallel (one actor each); weights are broadcast as
numpy pytrees each round. GAE is computed runner-side so the learner batch
arrives ready.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

import ray_tpu
from ray_tpu.rllib.envs import make_env
from ray_tpu.rllib.rl_module import MLPModule


@ray_tpu.remote
class EnvRunner:
    def __init__(self, env_name: str, num_envs: int, rollout_len: int,
                 module_spec: dict, gamma: float = 0.99, lam: float = 0.95,
                 seed: int = 0):
        self.env = make_env(env_name, num_envs, seed=seed)
        self.module = MLPModule(**module_spec)
        self.rollout_len = rollout_len
        self.gamma = gamma
        self.lam = lam
        self.rng = np.random.default_rng(seed + 1)
        self.obs = self.env.reset()
        # episode-return tracking (completed episodes since last sample)
        self._ep_ret = np.zeros(self.env.n, np.float64)
        self._completed: list = []

    def sample(self, weights) -> Dict[str, np.ndarray]:
        """Collect rollout_len vectorized steps; returns a flat GAE batch
        plus episode stats."""
        T, N = self.rollout_len, self.env.n
        obs_buf = np.empty((T, N, self.env.obs_dim), np.float32)
        act_buf = np.empty((T, N), np.int32)
        logp_buf = np.empty((T, N), np.float32)
        val_buf = np.empty((T + 1, N), np.float32)
        rew_buf = np.empty((T, N), np.float32)
        done_buf = np.empty((T, N), bool)

        obs = self.obs
        for t in range(T):
            logits, value = self.module.apply_np(weights, obs)
            # sample from the categorical (gumbel trick, vectorized)
            g = self.rng.gumbel(size=logits.shape)
            actions = np.argmax(logits + g, axis=-1)
            logp = logits - _logsumexp(logits)
            logp_t = np.take_along_axis(
                logp, actions[:, None], axis=-1)[:, 0]
            nxt, rew, done = self.env.step(actions)
            obs_buf[t], act_buf[t] = obs, actions
            logp_buf[t], val_buf[t] = logp_t, value
            rew_buf[t], done_buf[t] = rew, done
            self._ep_ret += rew
            if done.any():
                for i in np.nonzero(done)[0]:
                    self._completed.append(self._ep_ret[i])
                    self._ep_ret[i] = 0.0
            obs = nxt
        self.obs = obs
        _, last_value = self.module.apply_np(weights, obs)
        val_buf[T] = last_value

        # GAE(lambda)
        adv = np.zeros((T, N), np.float32)
        gae = np.zeros(N, np.float32)
        for t in reversed(range(T)):
            nonterminal = 1.0 - done_buf[t].astype(np.float32)
            delta = (rew_buf[t] + self.gamma * val_buf[t + 1] * nonterminal
                     - val_buf[t])
            gae = delta + self.gamma * self.lam * nonterminal * gae
            adv[t] = gae
        ret = adv + val_buf[:T]

        completed, self._completed = self._completed, []
        return {
            "obs": obs_buf.reshape(T * N, -1),
            "actions": act_buf.reshape(-1).astype(np.int32),
            "logp_old": logp_buf.reshape(-1),
            "advantages": adv.reshape(-1),
            "returns": ret.reshape(-1),
            "episode_returns": np.asarray(completed, np.float64),
        }

    def evaluate(self, weights, num_episodes: int = 8) -> float:
        """Mean greedy-policy episode return."""
        env = make_env(type(self.env).__name__ and "CartPole-v1",
                       num_episodes, seed=int(self.rng.integers(1 << 30)))
        obs = env.reset()
        total = np.zeros(num_episodes, np.float64)
        finished = np.zeros(num_episodes, bool)
        for _ in range(env.max_steps + 1):
            logits, _ = self.module.apply_np(weights, obs)
            obs, rew, done = env.step(np.argmax(logits, axis=-1))
            total += rew * (~finished)
            finished |= done
            if finished.all():
                break
        return float(total.mean())


def _logsumexp(x: np.ndarray) -> np.ndarray:
    m = x.max(axis=-1, keepdims=True)
    return m + np.log(np.exp(x - m).sum(axis=-1, keepdims=True))
