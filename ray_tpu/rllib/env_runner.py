"""EnvRunner: an actor collecting vectorized rollouts.

Reference: rllib/env/env_runner.py:22 / single_agent_env_runner. The gang
of runners samples in parallel (one actor each); weights are broadcast as
numpy pytrees each round. GAE is computed runner-side so the learner batch
arrives ready.

Termination vs truncation: envs report both (gymnasium split). Collected
batches carry ``next_obs`` holding the TRUE successor state (the env's
``final_obs`` at episode boundaries, never the auto-reset obs) plus a
``terminateds`` mask, so targets bootstrap through time-limit truncations
(r + gamma*V(s')) instead of treating them as value-0 terminals; GAE /
V-trace propagation still stops at every episode boundary.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

import ray_tpu
from ray_tpu.rllib.envs import make_env
from ray_tpu.rllib.rl_module import build_pv_module


class _EpisodeTracker:
    """Shared episode-return bookkeeping across runner kinds."""

    def _init_tracking(self):
        self._ep_ret = np.zeros(self.env.n, np.float64)
        self._completed: list = []

    def _track_episodes(self, rew: np.ndarray, done: np.ndarray) -> None:
        self._ep_ret += rew
        if done.any():
            for i in np.nonzero(done)[0]:
                self._completed.append(self._ep_ret[i])
                self._ep_ret[i] = 0.0

    def _drain_completed(self) -> np.ndarray:
        completed, self._completed = self._completed, []
        return np.asarray(completed, np.float64)


def _true_next_obs(env, nxt: np.ndarray, done: np.ndarray) -> np.ndarray:
    """The successor obs for targets: final_obs where the episode ended
    (auto-reset replaced it in ``nxt``), nxt elsewhere."""
    if not done.any():
        return nxt
    out = nxt.copy()
    out[done] = env.final_obs[done]
    return out


@ray_tpu.remote
class EnvRunner(_EpisodeTracker):
    def __init__(self, env_name: str, num_envs: int, rollout_len: int,
                 module_spec: dict, gamma: float = 0.99, lam: float = 0.95,
                 seed: int = 0):
        self.env_name = env_name
        self.env = make_env(env_name, num_envs, seed=seed)
        self.module = build_pv_module(module_spec)
        self.rollout_len = rollout_len
        self.gamma = gamma
        self.lam = lam
        self.rng = np.random.default_rng(seed + 1)
        self.obs = self.env.reset()
        self._init_tracking()

    def sample(self, weights) -> Dict[str, np.ndarray]:
        """Collect rollout_len vectorized steps; returns a flat GAE batch
        plus episode stats."""
        T, N = self.rollout_len, self.env.n
        obs_buf = np.empty((T, N, self.env.obs_dim), np.float32)
        next_buf = np.empty((T, N, self.env.obs_dim), np.float32)
        act_buf = np.empty((T, N), np.int32)
        logp_buf = np.empty((T, N), np.float32)
        val_buf = np.empty((T, N), np.float32)
        rew_buf = np.empty((T, N), np.float32)
        term_buf = np.empty((T, N), bool)
        done_buf = np.empty((T, N), bool)

        obs = self.obs
        for t in range(T):
            logits, value = self.module.apply_np(weights, obs)
            # sample from the categorical (gumbel trick, vectorized)
            g = self.rng.gumbel(size=logits.shape)
            actions = np.argmax(logits + g, axis=-1)
            logp = logits - _logsumexp(logits)
            logp_t = np.take_along_axis(
                logp, actions[:, None], axis=-1)[:, 0]
            nxt, rew, term, trunc = self.env.step(actions)
            done = term | trunc
            obs_buf[t], act_buf[t] = obs, actions
            next_buf[t] = _true_next_obs(self.env, nxt, done)
            logp_buf[t], val_buf[t] = logp_t, value
            rew_buf[t], term_buf[t], done_buf[t] = rew, term, done
            self._track_episodes(rew, done)
            obs = nxt
        self.obs = obs
        # V(s'_true) per step: for non-boundary steps that's val_buf[t+1]
        # (same weights, same state — no recompute); fresh evaluation only
        # for boundary columns (final_obs) and the last row
        next_val = np.empty((T, N), np.float32)
        next_val[:-1] = val_buf[1:]
        fresh_t, fresh_i = np.nonzero(done_buf[:-1])
        fresh_obs = [next_buf[fresh_t, fresh_i]] if len(fresh_t) else []
        fresh_obs.append(next_buf[T - 1])
        _, fresh_vals = self.module.apply_np(
            weights, np.concatenate(fresh_obs, axis=0))
        if len(fresh_t):
            next_val[fresh_t, fresh_i] = fresh_vals[:len(fresh_t)]
        next_val[T - 1] = fresh_vals[len(fresh_t):]

        # GAE(lambda): bootstrap masked only by TERMINATION; the gae
        # accumulation stops at any episode boundary
        adv = np.zeros((T, N), np.float32)
        gae = np.zeros(N, np.float32)
        for t in reversed(range(T)):
            not_term = 1.0 - term_buf[t].astype(np.float32)
            not_done = 1.0 - done_buf[t].astype(np.float32)
            delta = (rew_buf[t] + self.gamma * next_val[t] * not_term
                     - val_buf[t])
            gae = delta + self.gamma * self.lam * not_done * gae
            adv[t] = gae
        ret = adv + val_buf

        return {
            "obs": obs_buf.reshape(T * N, -1),
            "actions": act_buf.reshape(-1).astype(np.int32),
            "logp_old": logp_buf.reshape(-1),
            "advantages": adv.reshape(-1),
            "returns": ret.reshape(-1),
            "episode_returns": self._drain_completed(),
        }

    def sample_sequences(self, weights) -> Dict[str, np.ndarray]:
        """Time-major rollout for off-policy-corrected learners (IMPALA).

        Returns [T, N, ...] arrays with BEHAVIOR logits (the learner
        recomputes target logits and applies V-trace; reference:
        rllib/algorithms/impala/impala.py). ``next_obs`` carries true
        successors so the learner can bootstrap every step, including
        through truncations.
        """
        T, N = self.rollout_len, self.env.n
        obs_buf = np.empty((T, N, self.env.obs_dim), np.float32)
        next_buf = np.empty((T, N, self.env.obs_dim), np.float32)
        act_buf = np.empty((T, N), np.int32)
        logits_buf = np.empty((T, N, self.env.num_actions), np.float32)
        rew_buf = np.empty((T, N), np.float32)
        term_buf = np.empty((T, N), bool)
        done_buf = np.empty((T, N), bool)

        obs = self.obs
        for t in range(T):
            logits, _ = self.module.apply_np(weights, obs)
            g = self.rng.gumbel(size=logits.shape)
            actions = np.argmax(logits + g, axis=-1)
            nxt, rew, term, trunc = self.env.step(actions)
            done = term | trunc
            obs_buf[t], act_buf[t] = obs, actions
            next_buf[t] = _true_next_obs(self.env, nxt, done)
            logits_buf[t], rew_buf[t] = logits, rew
            term_buf[t], done_buf[t] = term, done
            self._track_episodes(rew, done)
            obs = nxt
        self.obs = obs
        return {
            "obs": obs_buf,
            "next_obs": next_buf,
            "actions": act_buf,
            "behavior_logits": logits_buf,
            "rewards": rew_buf,
            "terminateds": term_buf,
            "dones": done_buf,
            "episode_returns": self._drain_completed(),
        }

    def evaluate(self, weights, num_episodes: int = 8) -> float:
        """Mean greedy-policy episode return."""
        env = make_env(self.env_name, num_episodes,
                       seed=int(self.rng.integers(1 << 30)))
        obs = env.reset()
        total = np.zeros(num_episodes, np.float64)
        finished = np.zeros(num_episodes, bool)
        for _ in range(env.max_steps + 1):
            logits, _ = self.module.apply_np(weights, obs)
            obs, rew, term, trunc = env.step(np.argmax(logits, axis=-1))
            total += rew * (~finished)
            finished |= term | trunc
            if finished.all():
                break
        return float(total.mean())


@ray_tpu.remote
class OffPolicyRunner(_EpisodeTracker):
    """Transition-collecting actor for replay-based algorithms (DQN/SAC).

    Reference: rllib/env/single_agent_env_runner.py in the off-policy
    algorithms' sample loop. Keeps env state across calls; the policy is
    epsilon-greedy over a Q module (discrete) or a squashed Gaussian
    (continuous), selected by ``kind``. Stored transitions are
    (s, a, r, s'_true, terminated): time-limit truncations keep their
    bootstrap.
    """

    def __init__(self, env_name: str, num_envs: int, module_spec: dict,
                 kind: str = "dqn", seed: int = 0):
        from ray_tpu.rllib.rl_module import (QMLPModule,
                                             SquashedGaussianModule)

        self.env_name = env_name
        self.env = make_env(env_name, num_envs, seed=seed)
        if kind == "dqn":
            self.module = QMLPModule(**module_spec)
        elif kind == "sac":
            self.module = SquashedGaussianModule(**module_spec)
        else:
            raise ValueError(f"unknown runner kind {kind!r}")
        self.kind = kind
        self.rng = np.random.default_rng(seed + 1)
        self.obs = self.env.reset()
        self._init_tracking()

    def _act(self, weights, obs, epsilon: float) -> np.ndarray:
        if self.kind == "dqn":
            q = self.module.apply_np(weights, obs)
            greedy = np.argmax(q, axis=-1)
            explore = self.rng.random(len(obs)) < epsilon
            random_a = self.rng.integers(0, self.env.num_actions,
                                         size=len(obs))
            return np.where(explore, random_a, greedy).astype(np.int32)
        return self.module.sample_np(weights, obs, self.rng).astype(
            np.float32)

    def sample_transitions(self, weights, num_steps: int,
                           epsilon: float = 0.0) -> Dict[str, np.ndarray]:
        """Collect num_steps vectorized steps of (s, a, r, s', term)."""
        N = self.env.n
        cols = {
            "obs": np.empty((num_steps, N, self.env.obs_dim), np.float32),
            "rewards": np.empty((num_steps, N), np.float32),
            "next_obs": np.empty((num_steps, N, self.env.obs_dim),
                                 np.float32),
            "dones": np.empty((num_steps, N), np.float32),
        }
        actions = []
        obs = self.obs
        for t in range(num_steps):
            a = self._act(weights, obs, epsilon)
            nxt, rew, term, trunc = self.env.step(a)
            done = term | trunc
            cols["obs"][t] = obs
            actions.append(a)
            cols["rewards"][t] = rew
            cols["next_obs"][t] = _true_next_obs(self.env, nxt, done)
            # the replay "done" masks the bootstrap => termination only
            cols["dones"][t] = term.astype(np.float32)
            self._track_episodes(rew, done)
            obs = nxt
        self.obs = obs
        act = np.stack(actions)
        out = {k: v.reshape((num_steps * N,) + v.shape[2:])
               for k, v in cols.items()}
        out["actions"] = act.reshape((num_steps * N,) + act.shape[2:])
        out["episode_returns"] = self._drain_completed()
        return out

    def evaluate(self, weights, num_episodes: int = 8) -> float:
        """Mean deterministic-policy episode return."""
        env = make_env(self.env_name, num_episodes,
                       seed=int(self.rng.integers(1 << 30)))
        obs = env.reset()
        total = np.zeros(num_episodes, np.float64)
        finished = np.zeros(num_episodes, bool)
        for _ in range(env.max_steps + 1):
            if self.kind == "dqn":
                a = np.argmax(self.module.apply_np(weights, obs), axis=-1)
            else:
                a = self.module.sample_np(weights, obs, self.rng,
                                          deterministic=True)
            obs, rew, term, trunc = env.step(a)
            total += rew * (~finished)
            finished |= term | trunc
            if finished.all():
                break
        return float(total.mean())


def _logsumexp(x: np.ndarray) -> np.ndarray:
    m = x.max(axis=-1, keepdims=True)
    return m + np.log(np.exp(x - m).sum(axis=-1, keepdims=True))
