"""IMPALA: asynchronous actor-learner with V-trace off-policy correction.

Reference: rllib/algorithms/impala/impala.py (async sample + learner
queue). Here: each EnvRunner always has one sample() in flight; the
driver waits for ANY runner's time-major batch, updates the learner with
it (V-trace corrects the policy lag), and resubmits that runner with the
newest weights. The whole V-trace computation + SGD step is one jitted
program (reversed ``lax.scan`` for the v_s recursion — no host loop).
V-trace follows Espeholt et al. 2018, eqs. (1)-(4).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import AlgorithmConfig, RunnerDriver
from ray_tpu.rllib.rl_module import MLPModule, build_pv_module, to_numpy


class ImpalaLearner:
    def __init__(self, module: MLPModule, lr: float = 6e-4,
                 gamma: float = 0.99, vf_coef: float = 0.5,
                 ent_coef: float = 0.01, rho_bar: float = 1.0,
                 c_bar: float = 1.0, max_grad_norm: float = 40.0,
                 seed: int = 0):
        import jax
        import optax

        self.module = module
        self.params = module.init_params(seed)
        self.tx = optax.chain(optax.clip_by_global_norm(max_grad_norm),
                              optax.adam(lr))
        self.opt_state = self.tx.init(self.params)
        self._gamma = gamma
        self._vf_coef = vf_coef
        self._ent_coef = ent_coef
        self._rho_bar = rho_bar
        self._c_bar = c_bar
        self._update = jax.jit(self._update_impl, donate_argnums=(0, 1))

    # ---- V-trace target computation (inside jit) ----------------------------

    def _vtrace(self, target_logp, behavior_logp, values, next_values,
                rewards, disc_boot, cont):
        """v_s and the pg advantage for [T, N] time-major inputs.

        ``next_values`` are V(s'_true) per step (the env's final obs at
        episode boundaries), ``disc_boot = gamma*(1-terminated)`` masks
        the bootstrap only at real terminations, and ``cont = 1-done``
        stops the v_s recursion at every episode boundary (so time-limit
        truncations bootstrap but don't leak across episodes).
        """
        import jax
        import jax.numpy as jnp

        rho = jnp.exp(target_logp - behavior_logp)
        rho_c = jnp.minimum(self._rho_bar, rho)
        c = jnp.minimum(self._c_bar, rho)
        deltas = rho_c * (rewards + disc_boot * next_values - values)

        def back(acc, xs):
            delta_t, cont_t, c_t = xs
            acc = delta_t + self._gamma * cont_t * c_t * acc
            return acc, acc

        _, vs_minus_v = jax.lax.scan(
            back, jnp.zeros_like(values[0]),
            (deltas, cont, c), reverse=True)
        vs = vs_minus_v + values
        # within a trajectory the next target is vs[t+1]; at a boundary it
        # is the (terminal-masked) bootstrap value itself
        vs_shift = jnp.concatenate([vs[1:], next_values[-1:]], axis=0)
        vs_next = cont * vs_shift + (1.0 - cont) * next_values
        pg_adv = rho_c * (rewards + disc_boot * vs_next - values)
        return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)

    def _loss(self, params, batch):
        import jax
        import jax.numpy as jnp

        T, N = batch["rewards"].shape
        obs_flat = batch["obs"].reshape(T * N, -1)
        logits, values = self.module.apply(params, obs_flat)
        logits = logits.reshape(T, N, -1)
        values = values.reshape(T, N)
        _, next_values = self.module.apply(
            params, batch["next_obs"].reshape(T * N, -1))
        next_values = jax.lax.stop_gradient(next_values.reshape(T, N))
        logp_all = jax.nn.log_softmax(logits)
        b_logp_all = jax.nn.log_softmax(batch["behavior_logits"])
        a = batch["actions"][..., None]
        target_logp = jnp.take_along_axis(logp_all, a, axis=-1)[..., 0]
        behavior_logp = jnp.take_along_axis(b_logp_all, a, axis=-1)[..., 0]
        disc_boot = self._gamma * (1.0 - batch["terminateds"])
        cont = 1.0 - batch["dones"]

        vs, pg_adv = self._vtrace(target_logp, behavior_logp, values,
                                  next_values, batch["rewards"],
                                  disc_boot, cont)
        pg_loss = -(target_logp * pg_adv).mean()
        vf_loss = 0.5 * jnp.square(vs - values).mean()
        ent = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        loss = pg_loss + self._vf_coef * vf_loss - self._ent_coef * ent
        return loss, {"pg_loss": pg_loss, "vf_loss": vf_loss,
                      "entropy": ent}

    def _update_impl(self, params, opt_state, batch):
        import jax

        grads, aux = jax.grad(self._loss, has_aux=True)(params, batch)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, opt_state, aux

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax.numpy as jnp

        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        jb["dones"] = jb["dones"].astype(jnp.float32)
        jb["terminateds"] = jb["terminateds"].astype(jnp.float32)
        self.params, self.opt_state, aux = self._update(
            self.params, self.opt_state, jb)
        return {k: float(v) for k, v in aux.items()}

    def get_weights(self):
        return to_numpy(self.params)


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 6e-4
        self.num_env_runners = 2
        self.num_envs_per_runner = 8
        self.rollout_len = 40
        self.train_kwargs = {
            "vf_coef": 0.5, "ent_coef": 0.01, "rho_bar": 1.0,
            "c_bar": 1.0, "max_grad_norm": 40.0,
            "batches_per_iter": 8,
        }

    def build(self) -> "IMPALA":
        return IMPALA(self)


class IMPALA(RunnerDriver):
    """Async driver: one in-flight rollout per runner, learner consumes
    batches in completion order (the IMPALA architecture)."""

    LEARNER_CLS = ImpalaLearner

    def __init__(self, config: IMPALAConfig):
        from ray_tpu.rllib.env_runner import EnvRunner
        from ray_tpu.rllib.envs import make_env

        self.config = config
        kw = dict(config.train_kwargs)
        self._batches_per_iter = kw.pop("batches_per_iter")
        probe = make_env(config.env_name, 1)
        self.module_spec = {"obs_dim": probe.obs_dim,
                            "num_actions": probe.num_actions,
                            "hidden": config.module_hidden}
        if getattr(probe, "obs_shape", None):
            self.module_spec["obs_shape"] = tuple(probe.obs_shape)
        self.learner = self.LEARNER_CLS(build_pv_module(self.module_spec),
                                        lr=config.lr, gamma=config.gamma,
                                        seed=config.seed, **kw)
        self.runners = [
            EnvRunner.remote(config.env_name, config.num_envs_per_runner,
                             config.rollout_len, self.module_spec,
                             seed=config.seed + 1000 * i)
            for i in range(config.num_env_runners)
        ]
        self._inflight: Dict[Any, Any] = {}   # ref -> runner
        self._init_driver()

    def _submit(self, runner) -> None:
        w_ref = ray_tpu.put(self.learner.get_weights())
        ref = runner.sample_sequences.remote(w_ref)
        self._inflight[ref] = runner

    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        for r in self.runners:
            if r not in self._inflight.values():
                self._submit(r)
        accum: Dict[str, List[float]] = {}
        for _ in range(self._batches_per_iter):
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=300)
            if not ready:
                raise TimeoutError(
                    "no EnvRunner rollout completed within 300s "
                    f"({len(self._inflight)} in flight)")
            ref = ready[0]
            runner = self._inflight.pop(ref)
            batch = ray_tpu.get(ref)
            self._submit(runner)   # immediately refill with fresh weights
            self._record_returns(batch)
            self.env_steps += batch["rewards"].size
            for k, v in self.learner.update(batch).items():
                accum.setdefault(k, []).append(v)
        metrics = {k: float(np.mean(v)) for k, v in accum.items()}
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": self._mean_return(),
            "num_env_steps_sampled": self.env_steps,
            "time_this_iter_s": time.perf_counter() - t0,
            **metrics,
        }

    def _eval_runner(self):
        # prefer a runner with no sample in flight
        busy = set(self._inflight.values())
        return next((r for r in self.runners if r not in busy),
                    self.runners[0])
