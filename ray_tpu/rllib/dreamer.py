"""DreamerV3: model-based RL — learn a world model, act in imagination.

Reference: rllib/algorithms/dreamerv3/ (Hafner et al. 2023,
arXiv:2301.04104). This is a compact TPU-first implementation of the
algorithm's core: an RSSM world model (GRU deterministic state +
categorical stochastic latents with unimix), symlog observation/KL
losses with free bits and KL balancing, twohot symlog reward and critic
heads, imagination rollouts from replayed posterior states, λ-returns
over predicted continues, percentile-EMA return normalization for the
REINFORCE actor. Both updates (world model, actor-critic) are single
jitted programs; the recurrent policy steps through one small jitted
act function during collection.

Deliberate simplifications vs the paper at this scale (documented, not
hidden): vector observations only (MLP encoder/decoder — the CNN path
lives in rl_module.CNNModule and can slot in), no slow-critic EMA
regularizer, and collection runs in-process because the policy is
recurrent (the learner dominates compute; the env is a vectorized
host loop).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithm import AlgorithmConfig
from ray_tpu.rllib.envs import make_env


def symlog(x):
    import jax.numpy as jnp

    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    import jax.numpy as jnp

    return jnp.sign(x) * jnp.expm1(jnp.abs(x))


class TwoHot:
    """Twohot encoding over symlog-spaced bins (the paper's robust
    regression head for rewards and values)."""

    def __init__(self, low=-15.0, high=15.0, n=41):
        import jax.numpy as jnp

        self.bins = jnp.linspace(low, high, n)
        self.n = n

    def encode(self, y):
        """y [...] real -> [... , n] twohot weights of symlog(y)."""
        import jax.numpy as jnp

        y = symlog(y)
        y = jnp.clip(y, self.bins[0], self.bins[-1])
        idx = jnp.clip(jnp.searchsorted(self.bins, y, side="right") - 1,
                       0, self.n - 2)  # left bin of the bracket
        left = self.bins[idx]
        right = self.bins[idx + 1]
        w_right = jnp.clip((y - left) / (right - left), 0.0, 1.0)
        one = jnp.eye(self.n)
        return (one[idx] * (1.0 - w_right)[..., None]
                + one[idx + 1] * w_right[..., None])

    def decode(self, logits):
        """[..., n] logits -> [...] real expectation in symexp space."""
        import jax

        probs = jax.nn.softmax(logits, axis=-1)
        return symexp((probs * self.bins).sum(-1))


def _linear(key, din, dout, scale=1.0):
    import jax
    import jax.numpy as jnp

    w = jax.random.truncated_normal(key, -2, 2, (din, dout)) \
        * scale / np.sqrt(din)
    return {"w": w.astype(jnp.float32), "b": jnp.zeros((dout,))}


def _apply_linear(p, x):
    import jax.numpy as jnp

    return jnp.dot(x, p["w"]) + p["b"]


def _norm_silu(x):
    """LayerNorm + SiLU — the paper's block activation."""
    import jax
    import jax.numpy as jnp

    mean = x.mean(-1, keepdims=True)
    var = ((x - mean) ** 2).mean(-1, keepdims=True)
    return jax.nn.silu((x - mean) * jax.lax.rsqrt(var + 1e-5))


def _mlp(params, x):
    for p in params:
        x = _norm_silu(_apply_linear(p, x))
    return x


class DreamerV3Learner:
    """World model + actor-critic, each updated by one jitted program."""

    def __init__(self, obs_dim: int, num_actions: int, *, deter=128,
                 stoch_vars=8, stoch_classes=8, units=128, lr=4e-4,
                 ac_lr=1e-4, gamma=0.99, lam=0.95, horizon=10,
                 entropy=1e-3, unimix=0.01, free_bits=1.0,
                 imag_starts=64, seed=0):
        import jax
        import jax.numpy as jnp
        import optax

        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.deter = deter
        self.V, self.K = stoch_vars, stoch_classes
        self.z_dim = stoch_vars * stoch_classes
        self.units = units
        self.gamma, self.lam = gamma, lam
        self.horizon = horizon
        self.entropy = entropy
        self.unimix = unimix
        self.free_bits = free_bits
        self.imag_starts = imag_starts
        self.twohot = TwoHot()

        keys = iter(jax.random.split(jax.random.PRNGKey(seed), 24))
        U, D, Z, A = units, deter, self.z_dim, num_actions
        nb = self.twohot.n
        self.wm_params = {
            "enc": [_linear(next(keys), obs_dim, U),
                    _linear(next(keys), U, U)],
            "in": _linear(next(keys), Z + A, U),     # GRU input embed
            "gru": _linear(next(keys), U + D, 3 * D),
            "prior": [_linear(next(keys), D, U)],
            "prior_out": _linear(next(keys), U, Z),
            "post": [_linear(next(keys), D + U, U)],
            "post_out": _linear(next(keys), U, Z),
            "dec": [_linear(next(keys), D + Z, U),
                    _linear(next(keys), U, U)],
            "dec_out": _linear(next(keys), U, obs_dim),
            "rew": [_linear(next(keys), D + Z, U)],
            "rew_out": _linear(next(keys), U, nb, scale=0.0),
            "cont": [_linear(next(keys), D + Z, U)],
            "cont_out": _linear(next(keys), U, 1),
        }
        self.ac_params = {
            "actor": [_linear(next(keys), D + Z, U),
                      _linear(next(keys), U, U)],
            "actor_out": _linear(next(keys), U, A, scale=0.01),
            "critic": [_linear(next(keys), D + Z, U),
                       _linear(next(keys), U, U)],
            "critic_out": _linear(next(keys), U, nb, scale=0.0),
        }
        self.wm_tx = optax.chain(optax.clip_by_global_norm(100.0),
                                 optax.adam(lr))
        self.ac_tx = optax.chain(optax.clip_by_global_norm(100.0),
                                 optax.adam(ac_lr))
        self.wm_opt = self.wm_tx.init(self.wm_params)
        self.ac_opt = self.ac_tx.init(self.ac_params)
        # percentile EMA for return normalization (paper eq. 9)
        self.ret_lo = jnp.asarray(0.0)
        self.ret_hi = jnp.asarray(0.0)
        self._update = jax.jit(self._update_impl, donate_argnums=(0, 1))
        self._act = jax.jit(self._act_impl)

    # ---- RSSM pieces -----------------------------------------------------

    def _uni_logits(self, logits):
        """Unimix: 1% uniform mixed into the categorical (paper §B)."""
        import jax
        import jax.numpy as jnp

        logits = logits.reshape(logits.shape[:-1] + (self.V, self.K))
        probs = jax.nn.softmax(logits, -1)
        probs = (1 - self.unimix) * probs + self.unimix / self.K
        return jnp.log(probs)

    def _sample_z(self, logits, key):
        """Straight-through one-hot sample from V independent
        categoricals; returns flat [., V*K]."""
        import jax
        import jax.numpy as jnp

        idx = jax.random.categorical(key, logits, axis=-1)
        hot = jax.nn.one_hot(idx, self.K)
        probs = jax.nn.softmax(logits, -1)
        hot = probs + jax.lax.stop_gradient(hot - probs)
        return hot.reshape(hot.shape[:-2] + (self.z_dim,))

    def _gru(self, wm, h, x):
        import jax
        import jax.numpy as jnp

        x = _norm_silu(_apply_linear(wm["in"], x))
        gates = _apply_linear(wm["gru"], jnp.concatenate([x, h], -1))
        reset, cand, update = jnp.split(gates, 3, -1)
        reset = jax.nn.sigmoid(reset)
        cand = jnp.tanh(reset * cand)
        update = jax.nn.sigmoid(update - 1.0)
        return update * cand + (1 - update) * h

    def _prior(self, wm, h):
        return self._uni_logits(_apply_linear(wm["prior_out"],
                                              _mlp(wm["prior"], h)))

    def _post(self, wm, h, emb):
        import jax.numpy as jnp

        x = _mlp(wm["post"], jnp.concatenate([h, emb], -1))
        return self._uni_logits(_apply_linear(wm["post_out"], x))

    def _wm_step(self, wm, h, z, a_onehot, emb, is_first, key):
        """One posterior RSSM step with episode-boundary reset."""
        import jax.numpy as jnp

        mask = (1.0 - is_first)[..., None]
        h = h * mask
        z = z * mask
        a_onehot = a_onehot * mask
        h = self._gru(wm, h, jnp.concatenate([z, a_onehot], -1))
        post_logits = self._post(wm, h, emb)
        z_new = self._sample_z(post_logits, key)
        return h, z_new, post_logits

    # ---- world-model update ---------------------------------------------

    def _kl(self, lhs, rhs):
        """KL(cat(lhs) || cat(rhs)) summed over latent vars."""
        import jax
        import jax.numpy as jnp

        lp = jax.nn.log_softmax(lhs, -1)
        rp = jax.nn.log_softmax(rhs, -1)
        return (jnp.exp(lp) * (lp - rp)).sum(-1).sum(-1)

    def _wm_loss(self, wm, batch, key):
        import jax
        import jax.numpy as jnp

        obs = batch["obs"]            # [B, L, obs_dim]
        acts = batch["actions"]       # [B, L] int32 (action TAKEN at t)
        rews = batch["rewards"]       # [B, L]
        cont = 1.0 - batch["dones"]   # [B, L]
        first = batch["is_first"]     # [B, L]
        B, L = obs.shape[:2]
        emb = _mlp(wm["enc"], symlog(obs))
        a_prev = jnp.concatenate(
            [jnp.zeros((B, 1, self.num_actions)),
             jax.nn.one_hot(acts[:, :-1], self.num_actions)], axis=1)

        def step(carry, inp):
            h, z, k = carry
            emb_t, a_t, first_t = inp
            k, sub = jax.random.split(k)
            h, z, post_logits = self._wm_step(wm, h, z, a_t, emb_t,
                                              first_t, sub)
            prior_logits = self._prior(wm, h)
            return (h, z, k), (h, z, post_logits, prior_logits)

        h0 = jnp.zeros((B, self.deter))
        z0 = jnp.zeros((B, self.z_dim))
        (_, _, _), (hs, zs, post_l, prior_l) = jax.lax.scan(
            step, (h0, z0, key),
            (emb.transpose(1, 0, 2), a_prev.transpose(1, 0, 2),
             first.transpose(1, 0)))
        hs = hs.transpose(1, 0, 2)            # [B, L, D]
        zs = zs.transpose(1, 0, 2)
        post_l = post_l.transpose(1, 0, 2, 3)
        prior_l = prior_l.transpose(1, 0, 2, 3)

        feat = jnp.concatenate([hs, zs], -1)
        recon = _apply_linear(wm["dec_out"], _mlp(wm["dec"], feat))
        rew_logits = _apply_linear(wm["rew_out"], _mlp(wm["rew"], feat))
        cont_logit = _apply_linear(wm["cont_out"],
                                   _mlp(wm["cont"], feat))[..., 0]

        recon_loss = ((recon - symlog(obs)) ** 2).sum(-1)
        rew_target = self.twohot.encode(rews)
        rew_loss = -(rew_target
                     * jax.nn.log_softmax(rew_logits, -1)).sum(-1)
        cont_loss = (jnp.maximum(cont_logit, 0) - cont_logit * cont
                     + jnp.log1p(jnp.exp(-jnp.abs(cont_logit))))
        # KL balancing (paper eq. 5): dyn pushes the prior toward the
        # posterior, rep regularizes the posterior; both free-bits clipped
        dyn = self._kl(jax.lax.stop_gradient(post_l), prior_l)
        rep = self._kl(post_l, jax.lax.stop_gradient(prior_l))
        kl = (0.5 * jnp.maximum(dyn, self.free_bits)
              + 0.1 * jnp.maximum(rep, self.free_bits))
        loss = (recon_loss + rew_loss + cont_loss + kl).mean()
        return loss, (hs, zs)

    # ---- actor-critic update --------------------------------------------

    def _imagine(self, wm, ac, h, z, key):
        """Roll the prior forward ``horizon`` steps with actor actions.
        World-model params are constants here (REINFORCE needs no
        gradient through the dynamics)."""
        import jax
        import jax.numpy as jnp

        def step(carry, _):
            h, z, k = carry
            feat = jnp.concatenate([h, z], -1)
            logits = _apply_linear(ac["actor_out"],
                                   _mlp(ac["actor"], feat))
            k, k1, k2 = jax.random.split(k, 3)
            a = jax.random.categorical(k1, logits, axis=-1)
            a_hot = jax.nn.one_hot(a, self.num_actions)
            h2 = self._gru(wm, h, jnp.concatenate([z, a_hot], -1))
            z2 = self._sample_z(self._prior(wm, h2), k2)
            return (h2, z2, k), (feat, a)

        (hH, zH, _), (feats, acts) = jax.lax.scan(
            step, (h, z, key), None, length=self.horizon)
        last_feat = jnp.concatenate([hH, zH], -1)
        return feats, acts, last_feat  # feats [H, N, F], acts [H, N]

    def _ac_loss(self, ac, wm, states, key, ret_lo, ret_hi):
        import jax
        import jax.numpy as jnp

        h, z = states
        feats, acts, last_feat = self._imagine(
            wm, ac, h, z, key)
        all_feats = jnp.concatenate([feats, last_feat[None]], 0)
        # predictions along the imagined trajectory (constants for the
        # actor's REINFORCE gradient)
        sg = jax.lax.stop_gradient
        # pre-action-state convention, matching EXACTLY how the heads
        # are trained on auto-reset real data: rew(feat_t) ~ reward of
        # the transition taken FROM t, cont(feat_t) ~ that transition
        # survives. (The paper's arrival convention needs terminal
        # observations, which auto-reset vector envs swallow.)
        rew_logits = _apply_linear(wm["rew_out"],
                                   _mlp(wm["rew"], all_feats[:-1]))
        rewards = self.twohot.decode(rew_logits)          # [H, N]
        cont = jax.nn.sigmoid(_apply_linear(
            wm["cont_out"], _mlp(wm["cont"], all_feats[:-1]))[..., 0])
        v_logits = _apply_linear(ac["critic_out"],
                                 _mlp(ac["critic"], all_feats))
        values = self.twohot.decode(v_logits)             # [H+1, N]

        disc = self.gamma * cont
        # λ-returns, backward
        def back(acc, inp):
            r, d, v_next = inp
            ret = r + d * ((1 - self.lam) * v_next + self.lam * acc)
            return ret, ret

        _, rets = jax.lax.scan(
            back, values[-1],
            (rewards[::-1], disc[::-1], values[1:][::-1]))
        rets = rets[::-1]                                  # [H, N]
        rets = sg(rets)

        # trajectory weights: don't learn past predicted terminations
        weights = sg(jnp.concatenate(
            [jnp.ones_like(disc[:1]), jnp.cumprod(disc[:-1], 0)], 0))

        # percentile-EMA return normalization (paper: scale by
        # max(1, per95-per5))
        lo = jnp.percentile(rets, 5.0)
        hi = jnp.percentile(rets, 95.0)
        new_lo = 0.99 * ret_lo + 0.01 * lo
        new_hi = 0.99 * ret_hi + 0.01 * hi
        scale = jnp.maximum(1.0, new_hi - new_lo)

        actor_logits = _apply_linear(ac["actor_out"],
                                     _mlp(ac["actor"], sg(feats)))
        logp = jax.nn.log_softmax(actor_logits, -1)
        lp_a = jnp.take_along_axis(logp, acts[..., None], -1)[..., 0]
        adv = sg((rets - values[:-1]) / scale)
        ent = -(jnp.exp(logp) * logp).sum(-1)
        actor_loss = -(weights * (lp_a * adv + self.entropy * ent)).mean()

        target = self.twohot.encode(rets)
        critic_ce = -(target * jax.nn.log_softmax(
            v_logits[:-1], -1)).sum(-1)
        critic_loss = (weights * critic_ce).mean()
        return actor_loss + critic_loss, (new_lo, new_hi,
                                          rets.mean(), ent.mean())

    # ---- combined jitted update -----------------------------------------

    def _update_impl(self, wm_params, ac_params, wm_opt, ac_opt, batch,
                     key, ret_lo, ret_hi):
        import jax

        k1, k2 = jax.random.split(key)
        (wm_loss, (hs, zs)), wm_grads = jax.value_and_grad(
            self._wm_loss, has_aux=True)(wm_params, batch, k1)
        upd, wm_opt = self.wm_tx.update(wm_grads, wm_opt, wm_params)
        import optax

        wm_params = optax.apply_updates(wm_params, upd)

        # imagination starts: a random subsample of the batch's
        # posterior states (capping the AC program's width — the paper
        # uses every state, which at B*L starts dominates update cost)
        sg = jax.lax.stop_gradient
        h = sg(hs).reshape(-1, self.deter)
        z = sg(zs).reshape(-1, self.z_dim)
        if self.imag_starts and self.imag_starts < h.shape[0]:
            k2, ksub = jax.random.split(k2)
            pick = jax.random.choice(ksub, h.shape[0],
                                     (self.imag_starts,), replace=False)
            h, z = h[pick], z[pick]
        (ac_loss, (ret_lo, ret_hi, ret_mean, ent)), ac_grads = \
            jax.value_and_grad(self._ac_loss, has_aux=True)(
                ac_params, wm_params, (h, z), k2, ret_lo, ret_hi)
        upd, ac_opt = self.ac_tx.update(ac_grads, ac_opt, ac_params)
        ac_params = optax.apply_updates(ac_params, upd)
        return (wm_params, ac_params, wm_opt, ac_opt, ret_lo, ret_hi,
                {"wm_loss": wm_loss, "ac_loss": ac_loss,
                 "imag_return": ret_mean, "entropy": ent})

    def update(self, batch: Dict[str, np.ndarray], key) -> Dict[str, Any]:
        import jax.numpy as jnp

        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        (self.wm_params, self.ac_params, self.wm_opt, self.ac_opt,
         self.ret_lo, self.ret_hi, metrics) = self._update(
            self.wm_params, self.ac_params, self.wm_opt, self.ac_opt,
            batch, key, self.ret_lo, self.ret_hi)
        return {k: float(v) for k, v in metrics.items()}

    # ---- acting ----------------------------------------------------------

    def _act_impl(self, wm, ac, h, z, a_prev, obs, is_first, key,
                  greedy):
        import jax
        import jax.numpy as jnp

        emb = _mlp(wm["enc"], symlog(obs))
        a_hot = jax.nn.one_hot(a_prev, self.num_actions)
        k1, k2 = jax.random.split(key)
        h, z, _ = self._wm_step(wm, h, z, a_hot, emb, is_first, k1)
        logits = _apply_linear(ac["actor_out"], _mlp(
            ac["actor"], jnp.concatenate([h, z], -1)))
        a = jnp.where(greedy, jnp.argmax(logits, -1),
                      jax.random.categorical(k2, logits, -1))
        return h, z, a.astype(jnp.int32)

    def act(self, state, obs, is_first, key, greedy=False):
        h, z, a_prev = state
        h, z, a = self._act(self.wm_params, self.ac_params, h, z,
                            a_prev, obs, is_first, key, greedy)
        return (h, z, a), np.asarray(a)

    def init_state(self, n: int):
        import jax.numpy as jnp

        return (jnp.zeros((n, self.deter)), jnp.zeros((n, self.z_dim)),
                jnp.zeros((n,), jnp.int32))


class _SeqReplay:
    """Per-env contiguous streams; samples length-L windows (is_first
    flags let the RSSM reset across episode boundaries inside a
    window)."""

    def __init__(self, num_envs: int, obs_dim: int, capacity: int = 4096):
        self.cap = capacity
        self.n = num_envs
        self.obs = np.zeros((num_envs, capacity, obs_dim), np.float32)
        self.act = np.zeros((num_envs, capacity), np.int32)
        self.rew = np.zeros((num_envs, capacity), np.float32)
        self.done = np.zeros((num_envs, capacity), np.float32)
        self.first = np.zeros((num_envs, capacity), np.float32)
        self.ptr = 0
        self.full = False

    def add(self, obs, act, rew, done, first):
        i = self.ptr % self.cap
        self.obs[:, i] = obs
        self.act[:, i] = act
        self.rew[:, i] = rew
        self.done[:, i] = done
        self.first[:, i] = first
        self.ptr += 1
        if self.ptr >= self.cap:
            self.full = True

    def __len__(self):
        return min(self.ptr, self.cap)

    def sample(self, rng, batch: int, length: int) -> Dict[str, np.ndarray]:
        size = len(self)
        assert size >= length
        envs = rng.integers(0, self.n, batch)
        # windows must not straddle the ring's write head
        if self.full:
            # inclusive bound: offset size-length is the newest valid
            # non-straddling window, and excluding it degenerates to an
            # empty range when capacity == length
            offs = rng.integers(0, size - length + 1, batch)
            starts = (self.ptr + offs) % self.cap
        else:
            starts = rng.integers(0, size - length + 1, batch)
        idx = (starts[:, None] + np.arange(length)[None]) % self.cap
        out = {"obs": self.obs[envs[:, None], idx],
               "actions": self.act[envs[:, None], idx],
               "rewards": self.rew[envs[:, None], idx],
               "dones": self.done[envs[:, None], idx],
               "is_first": self.first[envs[:, None], idx]}
        # the window's first element always resets the RSSM state (we
        # don't know the state before the window)
        out["is_first"][:, 0] = 1.0
        return out


class DreamerV3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 4e-4
        self.num_envs_per_runner = 8

    def build(self) -> "DreamerV3":
        return DreamerV3(self)


class DreamerV3:
    """Algorithm driver: collect with the recurrent policy, train the
    world model + imagination actor-critic (train() = one iteration of
    ``steps_per_iter`` env batches with one update each)."""

    def __init__(self, config: DreamerV3Config):
        import jax

        kw = config.train_kwargs
        self.env = make_env(config.env_name, config.num_envs_per_runner,
                            seed=config.seed)
        self.learner = DreamerV3Learner(
            self.env.obs_dim, self.env.num_actions,
            lr=config.lr, ac_lr=kw.get("ac_lr", 1e-4),
            gamma=config.gamma, horizon=kw.get("horizon", 10),
            entropy=kw.get("entropy", 1e-3),
            lam=kw.get("lam", 0.95), unimix=kw.get("unimix", 0.01),
            free_bits=kw.get("free_bits", 1.0),
            deter=kw.get("deter", 128), units=kw.get("units", 128),
            stoch_vars=kw.get("stoch_vars", 8),
            stoch_classes=kw.get("stoch_classes", 8),
            imag_starts=kw.get("imag_starts", 64),
            seed=config.seed)
        self.replay = _SeqReplay(config.num_envs_per_runner,
                                 self.env.obs_dim,
                                 capacity=kw.get("replay_capacity", 4096))
        self.batch_size = kw.get("batch_size", 8)
        self.seq_len = kw.get("seq_len", 16)
        self.learning_starts = kw.get("learning_starts", 128)
        self.steps_per_iter = kw.get("steps_per_iter", 64)
        self.updates_per_step = kw.get("updates_per_step", 1)
        self.update_every = kw.get("update_every", 1)  # env steps/update
        self._since_update = 0
        self.rng = np.random.default_rng(config.seed)
        self._key = jax.random.PRNGKey(config.seed)
        self._obs = self.env.reset()
        self._state = self.learner.init_state(self.env.n)
        self._first = np.ones(self.env.n, np.float32)
        self.env_steps = 0
        self.iteration = 0
        self._ep_ret = np.zeros(self.env.n)
        self._recent: list = []

    def _next_key(self):
        import jax

        self._key, k = jax.random.split(self._key)
        return k

    def train(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        metrics: Dict[str, Any] = {}
        for _ in range(self.steps_per_iter):
            state, acts = self.learner.act(
                self._state, jnp.asarray(self._obs),
                jnp.asarray(self._first), self._next_key())
            obs2, rews, terminated, truncated = self.env.step(acts)
            reset = terminated | truncated
            # the continue head's target is 1-terminated ONLY: a time
            # limit is not death, and the following is_first already
            # resets the RSSM across the auto-reset boundary
            self.replay.add(self._obs, acts, rews,
                            terminated.astype(np.float32), self._first)
            self._ep_ret += rews
            for i in np.nonzero(reset)[0]:
                self._recent.append(self._ep_ret[i])
                self._ep_ret[i] = 0.0
            self._first = reset.astype(np.float32)
            self._obs = obs2
            self._state = state
            self.env_steps += self.env.n
            self._since_update += 1
            if (len(self.replay) * self.env.n >= self.learning_starts
                    and len(self.replay) >= self.seq_len
                    and self._since_update >= self.update_every):
                self._since_update = 0
                for _ in range(self.updates_per_step):
                    batch = self.replay.sample(self.rng, self.batch_size,
                                               self.seq_len)
                    metrics = self.learner.update(batch, self._next_key())
        self.iteration += 1
        self._recent = self._recent[-100:]
        out = {"iteration": self.iteration, "env_steps": self.env_steps,
               "episode_return_mean": (float(np.mean(self._recent))
                                       if self._recent else 0.0)}
        out.update(metrics)
        return out

    def evaluate(self, num_episodes: int = 8) -> float:
        import jax.numpy as jnp

        # evaluate on a fresh copy of the training env class
        env = type(self.env)(num_episodes, seed=1234)
        obs = env.reset()
        state = self.learner.init_state(num_episodes)
        first = np.ones(num_episodes, np.float32)
        rets = np.zeros(num_episodes)
        alive = np.ones(num_episodes, bool)
        for _ in range(env.max_steps):
            state, acts = self.learner.act(
                state, jnp.asarray(obs), jnp.asarray(first),
                self._next_key(), greedy=True)
            obs, rews, terminated, truncated = env.step(acts)
            done = terminated | truncated
            rets += rews * alive
            first = done.astype(np.float32)
            alive &= ~done
            if not alive.any():
                break
        return float(rets.mean())

    def stop(self):
        pass
