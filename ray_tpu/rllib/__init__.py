"""RLlib-lite: JAX-native reinforcement learning on the cluster runtime.

Capability analogue of the reference's RLlib new API stack
(rllib/algorithms/algorithm.py:227, rllib/core/learner/learner.py:116,
rllib/env/env_runner.py:22), re-designed TPU-first: the RLModule is a pure
function over a jax pytree, the Learner's update is ONE jitted program
(minibatch loop via lax.scan — no per-minibatch dispatch), and EnvRunners
are actors collecting vectorized numpy rollouts in parallel.

Algorithm families: PPO (on-policy, clipped), IMPALA (async actor-learner
with V-trace), DQN (double DQN + optional prioritized replay), SAC
(continuous control), DreamerV3 (model-based: RSSM world model +
imagination actor-critic), and offline BC/CQL/MARWIL over
``ray_tpu.data`` Datasets.
"""

from ray_tpu.rllib.algorithm import AlgorithmConfig  # noqa: F401
from ray_tpu.rllib.dqn import DQN, DQNConfig  # noqa: F401
from ray_tpu.rllib.dreamer import DreamerV3, DreamerV3Config  # noqa: F401
from ray_tpu.rllib.appo import APPO, APPOConfig  # noqa: F401
from ray_tpu.rllib.policy_server import PolicyClient, PolicyServerInput  # noqa: F401
from ray_tpu.rllib.catalog import Box, Catalog, Discrete  # noqa: F401
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig  # noqa: F401
from ray_tpu.rllib.multi_agent import (MultiAgentPPO,  # noqa: F401
                                       MultiAgentPPOConfig)
from ray_tpu.rllib.offline import (BCLearner, CQLLearner, MARWILLearner,  # noqa: F401
                                   train_offline)
from ray_tpu.rllib.ppo import PPO, PPOConfig  # noqa: F401
from ray_tpu.rllib.replay_buffer import (PrioritizedReplayBuffer,  # noqa: F401
                                         ReplayBuffer)
from ray_tpu.rllib.rl_module import (MLPModule, QMLPModule,  # noqa: F401
                                     SquashedGaussianModule, TwinQModule)
from ray_tpu.rllib.sac import SAC, SACConfig  # noqa: F401
