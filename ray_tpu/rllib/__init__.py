"""RLlib-lite: JAX-native reinforcement learning on the cluster runtime.

Capability analogue of the reference's RLlib new API stack
(rllib/algorithms/algorithm.py:227, rllib/core/learner/learner.py:116,
rllib/env/env_runner.py:22), re-designed TPU-first: the RLModule is a pure
function over a jax pytree, the Learner's update is ONE jitted program
(minibatch loop via lax.scan — no per-minibatch dispatch), and EnvRunners
are actors collecting vectorized numpy rollouts in parallel.
"""

from ray_tpu.rllib.algorithm import AlgorithmConfig  # noqa: F401
from ray_tpu.rllib.ppo import PPO, PPOConfig  # noqa: F401
from ray_tpu.rllib.rl_module import MLPModule  # noqa: F401
