"""Algorithm base: config + train-iteration driver over the runner gang.

Reference: rllib/algorithms/algorithm.py:227 (Algorithm.train) and
algorithm_config.py. The driver loop each iteration: broadcast weights ->
parallel sample() on the EnvRunner gang -> learner.update -> metrics.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu


class AlgorithmConfig:
    """Fluent config (subset of the reference's AlgorithmConfig)."""

    def __init__(self):
        self.env_name = "CartPole-v1"
        self.num_env_runners = 2
        self.num_envs_per_runner = 8
        self.rollout_len = 128
        self.gamma = 0.99
        self.lr = 3e-4
        self.train_kwargs: Dict[str, Any] = {}
        self.module_hidden = (64, 64)
        self.seed = 0

    def environment(self, env: str) -> "AlgorithmConfig":
        self.env_name = env
        return self

    def env_runners(self, num_env_runners: int = 2,
                    num_envs_per_env_runner: int = 8,
                    rollout_fragment_length: int = 128
                    ) -> "AlgorithmConfig":
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_env_runner
        self.rollout_len = rollout_fragment_length
        return self

    def training(self, lr: float = None, gamma: float = None,
                 **kwargs) -> "AlgorithmConfig":
        if lr is not None:
            self.lr = lr
        if gamma is not None:
            self.gamma = gamma
        self.train_kwargs.update(kwargs)
        return self

    def rl_module(self, hidden=(64, 64)) -> "AlgorithmConfig":
        self.module_hidden = tuple(hidden)
        return self

    def debugging(self, seed: int = 0) -> "AlgorithmConfig":
        self.seed = seed
        return self


class RunnerDriver:
    """Shared driver plumbing: a learner + a runner gang + episode-return
    bookkeeping. All algorithm drivers (PPO/IMPALA/DQN/SAC) extend this."""

    learner = None
    runners: List[Any] = []

    def _init_driver(self):
        self.iteration = 0
        self.env_steps = 0
        self._recent_returns: List[float] = []

    def _record_returns(self, batch: Dict[str, np.ndarray]) -> None:
        """Consume the episode_returns column of a runner batch."""
        self._recent_returns.extend(batch.pop("episode_returns").tolist())

    def _mean_return(self) -> float:
        self._recent_returns = self._recent_returns[-100:]
        return (float(np.mean(self._recent_returns))
                if self._recent_returns else 0.0)

    def evaluate(self, num_episodes: int = 8) -> float:
        return float(ray_tpu.get(
            self._eval_runner().evaluate.remote(
                self.learner.get_weights(), num_episodes), timeout=120))

    def _eval_runner(self):
        return self.runners[0]

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass


class Algorithm(RunnerDriver):
    """Drives a learner + an EnvRunner gang. Subclasses build the learner."""

    def __init__(self, config: AlgorithmConfig):
        from ray_tpu.rllib.env_runner import EnvRunner
        from ray_tpu.rllib.envs import make_env

        self.config = config
        probe = make_env(config.env_name, 1)
        self.module_spec = {"obs_dim": probe.obs_dim,
                            "num_actions": probe.num_actions,
                            "hidden": config.module_hidden}
        # pixel envs advertise obs_shape: the module factory then builds
        # the conv encoder instead of the MLP (reference: catalog picks
        # the CNN encoder from the obs space, encoder.py:107)
        if getattr(probe, "obs_shape", None):
            self.module_spec["obs_shape"] = tuple(probe.obs_shape)
        self.learner = self._build_learner()
        self.runners = [
            EnvRunner.remote(config.env_name, config.num_envs_per_runner,
                             config.rollout_len, self.module_spec,
                             gamma=config.gamma,
                             lam=config.train_kwargs.get("lam", 0.95),
                             seed=config.seed + 1000 * i)
            for i in range(config.num_env_runners)
        ]
        self._init_driver()

    def _build_learner(self):
        raise NotImplementedError

    def train(self) -> Dict[str, Any]:
        """One training iteration (reference: Algorithm.train())."""
        t0 = time.perf_counter()
        weights = self.learner.get_weights()
        w_ref = ray_tpu.put(weights)
        batches = ray_tpu.get(
            [r.sample.remote(w_ref) for r in self.runners], timeout=300)
        for b in batches:
            self._record_returns(b)
        batch = {
            k: np.concatenate([b[k] for b in batches]) for k in batches[0]
        }
        # advantage normalization (standard PPO practice)
        adv = batch["advantages"]
        batch["advantages"] = ((adv - adv.mean())
                               / (adv.std() + 1e-8)).astype(np.float32)
        metrics = self.learner.update(batch)
        self.iteration += 1
        self.env_steps += batch["obs"].shape[0]
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": self._mean_return(),
            "num_env_steps_sampled": self.env_steps,
            "time_this_iter_s": time.perf_counter() - t0,
            **metrics,
        }
