"""Datasources: pluggable readers/writers producing ReadTasks.

Reference: python/ray/data/read_api.py + python/ray/data/_internal/datasource/
(parquet, csv, json, numpy, range, binary, text datasources). A Datasource
plans itself into independent ``ReadTask``s — serializable thunks the
streaming executor runs as remote tasks, each yielding blocks.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Iterable, List, Optional

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata


class ReadTask:
    """A serializable unit of read work (reference:
    python/ray/data/datasource/datasource.py ReadTask)."""

    def __init__(self, read_fn: Callable[[], Iterable[Block]],
                 metadata: BlockMetadata):
        self._read_fn = read_fn
        self.metadata = metadata  # estimate; actual metadata computed on read

    def __call__(self) -> Iterable[Block]:
        return self._read_fn()


class Datasource:
    """Base class for custom datasources (reference:
    python/ray/data/datasource/datasource.py Datasource)."""

    def get_name(self) -> str:
        return type(self).__name__.replace("Datasource", "")

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError


# ---- built-in sources ------------------------------------------------------

class RangeDatasource(Datasource):
    def __init__(self, n: int, use_tensor: bool = False,
                 tensor_shape: tuple = (1,)):
        self._n = n
        self._use_tensor = use_tensor
        self._tensor_shape = tensor_shape

    def estimate_inmemory_data_size(self):
        return self._n * 8 * int(np.prod(self._tensor_shape))

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        n, k = self._n, max(1, parallelism)
        use_tensor, shape = self._use_tensor, self._tensor_shape
        for i in range(k):
            start = n * i // k
            end = n * (i + 1) // k
            if end <= start:
                continue

            def read(start=start, end=end):
                ids = np.arange(start, end, dtype=np.int64)
                if use_tensor:
                    data = np.broadcast_to(
                        ids.reshape((-1,) + (1,) * len(shape)),
                        (end - start,) + shape).copy()
                    yield BlockAccessor.batch_to_block({"data": data})
                else:
                    yield BlockAccessor.batch_to_block({"id": ids})

            meta = BlockMetadata(num_rows=end - start,
                                 size_bytes=(end - start) * 8)
            tasks.append(ReadTask(read, meta))
        return tasks


class ItemsDatasource(Datasource):
    def __init__(self, items: List[Any]):
        self._items = list(items)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        n, k = len(self._items), max(1, parallelism)
        for i in range(k):
            chunk = self._items[n * i // k:n * (i + 1) // k]
            if not chunk:
                continue

            def read(chunk=chunk):
                yield BlockAccessor.rows_to_block(chunk)

            tasks.append(ReadTask(read, BlockMetadata(len(chunk), 0)))
        return tasks


def _expand_paths(paths, suffixes: Optional[List[str]] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _, files in os.walk(p):
                for f in sorted(files):
                    if f.startswith((".", "_")):
                        continue
                    if suffixes and not any(f.endswith(s) for s in suffixes):
                        continue
                    out.append(os.path.join(root, f))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"No input files found for {paths!r}")
    return out


class FileDatasource(Datasource):
    """Shared logic for file-based sources: split files across read tasks."""

    suffixes: Optional[List[str]] = None
    # decoded-size multiplier for read-parallelism inference (reference:
    # ParquetDatasource's encoding-ratio estimate — on-disk parquet/
    # compressed formats expand in memory)
    size_multiplier: float = 1.0

    def __init__(self, paths):
        self._paths = _expand_paths(paths, self.suffixes)

    def estimate_inmemory_data_size(self):
        try:
            return int(sum(os.path.getsize(p) for p in self._paths)
                       * self.size_multiplier)
        except OSError:
            return None

    def read_file(self, path: str) -> Iterable[Block]:
        raise NotImplementedError

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        groups = np.array_split(np.asarray(self._paths, dtype=object),
                                max(1, min(parallelism, len(self._paths))))
        for grp in groups:
            paths = [str(p) for p in grp]
            if not paths:
                continue

            def read(paths=paths, self=self):
                for p in paths:
                    yield from self.read_file(p)

            size = sum(os.path.getsize(p) for p in paths
                       if os.path.exists(p))
            tasks.append(ReadTask(read, BlockMetadata(
                num_rows=0, size_bytes=size, input_files=paths)))
        return tasks


class ParquetDatasource(FileDatasource):
    size_multiplier = 5.0  # columnar compression expands in memory

    suffixes = [".parquet"]
    supports_column_pruning = True
    supports_predicate_pushdown = True

    def __init__(self, paths, columns: Optional[List[str]] = None):
        super().__init__(paths)
        self._columns = columns
        self._filter = None  # pyarrow.dataset expression
        self._expr = None    # framework Expr (row-group stat pruning)

    def with_columns(self, columns: List[str]) -> "ParquetDatasource":
        """Pruned clone (projection pushdown target)."""
        import copy

        out = copy.copy(self)
        out._columns = list(columns)
        return out

    def with_filter(self, pa_expr, expr=None) -> "ParquetDatasource":
        """Filtered clone (predicate pushdown target); multiple pushed
        filters AND together. ``expr`` is the framework Expr used for
        row-group statistics pruning (the pyarrow expression alone is
        opaque to interval analysis)."""
        import copy

        out = copy.copy(self)
        out._filter = (pa_expr if out._filter is None
                       else out._filter & pa_expr)
        if expr is not None:
            out._expr = (expr if getattr(out, "_expr", None) is None
                         else out._expr & expr)
        return out

    def read_file(self, path: str):
        import pyarrow.parquet as pq

        pf = pq.ParquetFile(path)
        if self._filter is None:
            for batch in pf.iter_batches(columns=self._columns):
                yield pa.Table.from_batches([batch])
            return
        # explicit row-group statistics pruning: a group whose min/max
        # bounds PROVE the predicate empty is never read off disk
        # (reference: fragment-metadata pruning in
        # _internal/datasource/parquet_datasource.py); survivors filter
        # vectorized per batch before the block materializes
        expr = getattr(self, "_expr", None)
        n_groups = pf.metadata.num_row_groups
        kept = list(range(n_groups))
        if expr is not None:
            from ray_tpu.data.expr import row_group_may_match

            kept = []
            for i in range(n_groups):
                rg = pf.metadata.row_group(i)
                stats = {}
                for j in range(rg.num_columns):
                    c = rg.column(j)
                    if (c.statistics is not None
                            and c.statistics.has_min_max):
                        stats[c.path_in_schema] = (c.statistics.min,
                                                   c.statistics.max)
                if row_group_may_match(expr, stats):
                    kept.append(i)
        # observability (tests + stats debugging; one process-local scan)
        self.last_scan_row_groups = (n_groups, len(kept))
        if not kept:
            return
        # the residual filter may reference columns the projection
        # pruned (the scanner-based predicate needs them only
        # transiently): read the union, filter, then re-project
        read_cols = self._columns
        if read_cols is not None:
            if expr is not None:
                read_cols = sorted(set(read_cols) | set(expr.columns()))
            else:
                read_cols = None  # unknown filter columns: read all
        for batch in pf.iter_batches(row_groups=kept, columns=read_cols):
            t = pa.Table.from_batches([batch]).filter(self._filter)
            if self._columns is not None and t.column_names != self._columns:
                t = t.select(self._columns)
            if t.num_rows:
                yield t


class _ScannedTextDatasource(FileDatasource):
    """Shared base for row-oriented text formats (CSV/JSON) with
    EARLY-SKIP predicate pushdown: there are no statistics to prune on,
    but a pushed filter applies per record batch inside the scanner —
    non-matching rows are dropped before any block materializes or
    crosses the object store (reference: the planner pushes filters
    only into parquet; this extends the same rule to text scans)."""

    format: str = ""
    supports_predicate_pushdown = True

    def __init__(self, paths):
        super().__init__(paths)
        self._filter = None

    def with_filter(self, pa_expr, expr=None):
        import copy

        out = copy.copy(self)
        out._filter = (pa_expr if out._filter is None
                       else out._filter & pa_expr)
        return out

    def _read_table(self, path: str):
        raise NotImplementedError

    def read_file(self, path: str):
        if self._filter is None:
            yield self._read_table(path)
            return
        import pyarrow.dataset as pads

        scan = pads.dataset(path, format=self.format)
        for batch in scan.to_batches(filter=self._filter):
            if batch.num_rows:
                yield pa.Table.from_batches([batch])


class CSVDatasource(_ScannedTextDatasource):
    suffixes = [".csv"]
    format = "csv"

    def _read_table(self, path: str):
        import pyarrow.csv as pacsv
        return pacsv.read_csv(path)


class JSONDatasource(_ScannedTextDatasource):
    suffixes = [".json", ".jsonl"]
    format = "json"

    def _read_table(self, path: str):
        import pyarrow.json as pajson
        return pajson.read_json(path)


class NumpyDatasource(FileDatasource):
    suffixes = [".npy"]

    def read_file(self, path: str):
        arr = np.load(path)
        yield BlockAccessor.batch_to_block({"data": arr})


class TextDatasource(FileDatasource):
    def read_file(self, path: str):
        with open(path, "r", errors="replace") as f:
            lines = [ln.rstrip("\n") for ln in f]
        yield pa.table({"text": pa.array(lines)})


class BinaryDatasource(FileDatasource):
    def read_file(self, path: str):
        with open(path, "rb") as f:
            data = f.read()
        yield pa.table({"bytes": pa.array([data], type=pa.binary()),
                        "path": pa.array([path])})


class TFRecordsDatasource(FileDatasource):
    """Minimal TFRecord reader (uncompressed): parses the framing format
    (length/crc framing per the TFRecord spec) and yields raw example
    bytes; decoding protos is left to a downstream map (torch/tf-free)."""

    suffixes = [".tfrecords", ".tfrecord"]

    def read_file(self, path: str):
        import struct
        records = []
        with open(path, "rb") as f:
            while True:
                header = f.read(8)
                if len(header) < 8:
                    break
                (length,) = struct.unpack("<Q", header)
                f.read(4)  # length crc
                records.append(f.read(length))
                f.read(4)  # data crc
        yield pa.table({"bytes": pa.array(records, type=pa.binary())})


class WebDatasetDatasource(FileDatasource):
    """WebDataset-style tar shards (reference:
    _internal/datasource/webdataset_datasource.py): each sample is the
    group of tar members sharing a basename; extensions become columns
    holding raw bytes (decoding is a downstream map)."""

    suffixes = [".tar"]

    def read_file(self, path: str):
        import tarfile

        samples: dict = {}
        order: List[str] = []
        with tarfile.open(path) as tf:
            for member in tf:
                if not member.isfile():
                    continue
                # webdataset convention: split at the first dot of the LAST
                # path component (dotted directories stay in the key)
                dirname, _, fname = member.name.rpartition("/")
                stem, _, ext = fname.partition(".")
                base = f"{dirname}/{stem}" if dirname else stem
                data = tf.extractfile(member).read()
                if base not in samples:
                    samples[base] = {"__key__": base}
                    order.append(base)
                samples[base][ext or "bin"] = data
        if not order:
            return
        cols = sorted({k for s in samples.values() for k in s})
        table = {}
        for c in cols:
            vals = [samples[b].get(c) for b in order]
            if c == "__key__":
                table[c] = pa.array(vals, type=pa.string())
            else:
                table[c] = pa.array(vals, type=pa.binary())
        yield pa.table(table)


class SQLDatasource(Datasource):
    """Rows from a DBAPI connection factory (reference:
    _internal/datasource/sql_datasource.py; works out of the box with
    stdlib sqlite3)."""

    def __init__(self, sql: str, connection_factory):
        self._sql = sql
        self._factory = connection_factory

    def estimate_inmemory_data_size(self):
        return None

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        sql, factory = self._sql, self._factory

        def read():
            conn = factory()
            try:
                cur = conn.cursor()
                cur.execute(sql)
                names = [d[0] for d in cur.description]
                # page the cursor so huge result sets stream as bounded
                # blocks instead of one fetchall() materialization
                while True:
                    rows = cur.fetchmany(10_000)
                    if not rows:
                        break
                    cols = {n: pa.array([r[i] for r in rows])
                            for i, n in enumerate(names)}
                    yield pa.table(cols)
            finally:
                conn.close()

        return [ReadTask(read, BlockMetadata(num_rows=0, size_bytes=0,
                                             input_files=[]))]


class ImageDatasource(FileDatasource):
    """Decoded images as tensor columns (reference:
    _internal/datasource/image_datasource.py). Columns: ``image`` (HWC
    uint8 tensor) + ``path``. ``size=(H, W)`` resizes on read so blocks
    have a uniform tensor shape; ``mode`` forces a PIL color mode."""

    suffixes = [".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp"]

    def __init__(self, paths, size: Optional[tuple] = None,
                 mode: Optional[str] = None):
        super().__init__(paths)
        self._size = tuple(size) if size else None
        self._mode = mode

    def read_file(self, path: str):
        from PIL import Image

        img = Image.open(path)
        if self._mode:
            img = img.convert(self._mode)
        if self._size:
            # PIL takes (W, H); the API takes (H, W) like the reference
            img = img.resize((self._size[1], self._size[0]))
        arr = np.asarray(img)
        yield BlockAccessor.batch_to_block(
            {"image": arr[None, ...], "path": np.asarray([path])})


# ---- Avro object container files (pure-python, no fastavro) ---------------

class _AvroReader:
    """Minimal Avro OCF decoder per the 1.11 spec: null/deflate codecs;
    null, boolean, int, long, float, double, bytes, string, record, enum,
    array, map, union, and fixed types."""

    def __init__(self, buf: bytes):
        self._b = buf
        self._i = 0

    def _read(self, n: int) -> bytes:
        out = self._b[self._i:self._i + n]
        if len(out) < n:
            raise EOFError("truncated avro data")
        self._i += n
        return out

    def long(self) -> int:
        shift, acc = 0, 0
        while True:
            byte = self._b[self._i]
            self._i += 1
            acc |= (byte & 0x7F) << shift
            if not byte & 0x80:
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)  # zigzag

    def bytes_(self) -> bytes:
        return self._read(self.long())

    def string(self) -> str:
        return self.bytes_().decode("utf-8")

    def value(self, schema, named) -> Any:
        import struct

        if isinstance(schema, str) and schema in named:
            schema = named[schema]
        if isinstance(schema, list):   # union
            return self.value(schema[self.long()], named)
        t = schema["type"] if isinstance(schema, dict) else schema
        if t == "null":
            return None
        if t == "boolean":
            return self._read(1)[0] == 1
        if t in ("int", "long"):
            return self.long()
        if t == "float":
            return struct.unpack("<f", self._read(4))[0]
        if t == "double":
            return struct.unpack("<d", self._read(8))[0]
        if t == "bytes":
            return self.bytes_()
        if t == "string":
            return self.string()
        if t == "record":
            named[schema["name"]] = schema
            return {f["name"]: self.value(f["type"], named)
                    for f in schema["fields"]}
        if t == "enum":
            named[schema["name"]] = schema
            return schema["symbols"][self.long()]
        if t == "fixed":
            named[schema["name"]] = schema
            return self._read(schema["size"])
        if t == "array":
            out = []
            while True:
                n = self.long()
                if n == 0:
                    break
                if n < 0:
                    n = -n
                    self.long()  # skip byte-size hint
                out.extend(self.value(schema["items"], named)
                           for _ in range(n))
            return out
        if t == "map":
            out = {}
            while True:
                n = self.long()
                if n == 0:
                    break
                if n < 0:
                    n = -n
                    self.long()
                for _ in range(n):
                    k = self.string()  # key MUST decode before the value
                    out[k] = self.value(schema["values"], named)
            return out
        raise ValueError(f"unsupported avro type {t!r}")


def read_avro_rows(path: str) -> List[dict]:
    """Decode one Avro OCF into plain Python rows (shared by
    AvroDatasource and the Iceberg manifest reader, whose nested
    manifest-entry records should not round-trip through Arrow)."""
    import json
    import zlib

    with open(path, "rb") as f:
        data = f.read()
    r = _AvroReader(data)
    if r._read(4) != b"Obj\x01":
        raise ValueError(f"{path} is not an avro container file")
    meta = {}
    while True:
        n = r.long()
        if n == 0:
            break
        if n < 0:
            n = -n
            r.long()
        for _ in range(n):
            k = r.string()  # key MUST decode before the value
            meta[k] = r.bytes_()
    schema = json.loads(meta["avro.schema"])
    codec = meta.get("avro.codec", b"null").decode()
    sync = r._read(16)

    rows: List[dict] = []
    while r._i < len(r._b):
        count = r.long()
        size = r.long()
        payload = r._read(size)
        if codec == "deflate":
            payload = zlib.decompress(payload, -15)
        elif codec != "null":
            raise ValueError(f"unsupported avro codec {codec!r}")
        block = _AvroReader(payload)
        named: dict = {}
        for _ in range(count):
            v = block.value(schema, named)
            rows.append(v if isinstance(v, dict) else {"value": v})
        if r._read(16) != sync:
            raise ValueError(f"{path}: bad sync marker (corrupt file)")
    return rows


class AvroDatasource(FileDatasource):
    """Avro object container files (reference:
    _internal/datasource/avro_datasource.py uses fastavro; this image has
    none, so the container + binary encoding are decoded directly)."""

    suffixes = [".avro"]

    def read_file(self, path: str):
        rows = read_avro_rows(path)
        if rows:
            yield BlockAccessor.rows_to_block(rows)


# ---- external-framework converters ----------------------------------------

class TorchDatasource(Datasource):
    """Map-style ``torch.utils.data.Dataset`` split by index ranges
    (reference: read_api.from_torch)."""

    def __init__(self, torch_dataset):
        self._ds = torch_dataset

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = len(self._ds)
        ds = self._ds
        tasks = []
        k = max(1, min(parallelism, n))
        for i in range(k):
            start, end = n * i // k, n * (i + 1) // k
            if end <= start:
                continue

            def read(start=start, end=end):
                rows = []
                for j in range(start, end):
                    item = ds[j]
                    if isinstance(item, dict):
                        item = {k2: _to_numpy_value(v)
                                for k2, v in item.items()}
                    elif isinstance(item, (tuple, list)):
                        # e.g. TensorDataset yields (x, y): one column per
                        # element (a tuple cell has no arrow type)
                        item = {f"item_{idx}": _to_numpy_value(v)
                                for idx, v in enumerate(item)}
                    else:
                        item = {"item": _to_numpy_value(item)}
                    rows.append(item)
                yield BlockAccessor.rows_to_block(rows)

            tasks.append(ReadTask(read, BlockMetadata(end - start, 0)))
        return tasks


def _to_numpy_value(v):
    try:
        import torch
        if isinstance(v, torch.Tensor):
            return v.detach().cpu().numpy()
    except ImportError:
        pass
    if isinstance(v, (list, tuple)):
        return type(v)(_to_numpy_value(x) for x in v)
    return v


def huggingface_to_blocks(hf_dataset, parallelism: int) -> List[Block]:
    """An HF ``datasets.Dataset`` is arrow-backed: slice its table into
    blocks zero-copy (reference: read_api.from_huggingface)."""
    # select/shuffle/filter keep the full backing table plus an indices
    # mapping — materialize it or we'd read the unfiltered rows
    if getattr(hf_dataset, "_indices", None) is not None:
        hf_dataset = hf_dataset.flatten_indices()
    table = hf_dataset.data.table if hasattr(hf_dataset, "data") else None
    if table is None:
        raise TypeError(
            "from_huggingface expects a materialized datasets.Dataset "
            f"(got {type(hf_dataset).__name__}); for IterableDataset, "
            "materialize first or use from_items")
    table = table.combine_chunks()
    n = table.num_rows
    k = max(1, min(parallelism if parallelism > 0 else 8, max(n, 1)))
    return [table.slice(n * i // k, n * (i + 1) // k - n * i // k)
            for i in range(k) if n * (i + 1) // k > n * i // k]


def _require_bigquery():
    """Actionable gated-import error, consistent with make_gated_reader."""
    try:
        from google.cloud import bigquery  # noqa: F401
    except ImportError:
        raise ImportError(
            "read_bigquery/write_bigquery require the optional dependency "
            "'google-cloud-bigquery', which is not installed in this "
            "environment. Install it, or export the table to parquet/csv "
            "and use read_parquet/read_csv.") from None


class BigQueryDatasource(Datasource):
    """BigQuery tables/queries via the google-cloud-bigquery client
    (reference: _internal/datasource/bigquery_datasource.py). A table
    read is split into row ranges across read tasks; a query runs once
    and is sliced."""

    def __init__(self, project_id: str, dataset: Optional[str] = None,
                 query: Optional[str] = None):
        if (dataset is None) == (query is None):
            raise ValueError(
                "read_bigquery: pass exactly one of dataset='ds.table' "
                "or query='SELECT ...'")
        self._project = project_id
        self._dataset = dataset
        self._query = query

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        _require_bigquery()
        project, dataset, query = self._project, self._dataset, self._query

        if query is not None:
            def read_query():
                from google.cloud import bigquery

                client = bigquery.Client(project=project)
                table = client.query(query).to_arrow()
                if table.num_rows:
                    yield table

            return [ReadTask(read_query, BlockMetadata(0, 0))]

        from google.cloud import bigquery

        client = bigquery.Client(project=project)
        bq_table = client.get_table(dataset)
        n = bq_table.num_rows
        k = max(1, min(parallelism if parallelism > 0 else 8, max(n, 1)))
        tasks = []
        for i in range(k):
            start, end = n * i // k, n * (i + 1) // k
            if end <= start:
                continue

            def read(start=start, end=end):
                from google.cloud import bigquery as bq

                rows = bq.Client(project=project).list_rows(
                    dataset, start_index=start, max_results=end - start)
                table = rows.to_arrow()
                if table.num_rows:
                    yield table

            tasks.append(ReadTask(read, BlockMetadata(end - start, 0)))
        return tasks


def write_bigquery_block(block: Block, project_id: str, dataset: str
                         ) -> int:
    """Append one arrow block to a BigQuery table via a load job."""
    import io

    import pyarrow.parquet as pq
    _require_bigquery()
    from google.cloud import bigquery

    client = bigquery.Client(project=project_id)
    buf = io.BytesIO()
    pq.write_table(block, buf)
    buf.seek(0)
    job = client.load_table_from_file(
        buf, dataset,
        job_config=bigquery.LoadJobConfig(
            source_format=bigquery.SourceFormat.PARQUET))
    job.result()
    return block.num_rows


# ---- gated cloud datasources (backing libraries not in this image) ---------

_CLOUD_SOURCES = {
    "read_lance": "lance",
    "read_mongo": "pymongo",
    "read_databricks_tables": "databricks.sql",
    "read_clickhouse": "clickhouse_connect",
    "read_snowflake": "snowflake.connector",
}


def make_gated_reader(api_name: str, module: str):
    def _reader(*args, **kwargs):
        import importlib
        try:
            importlib.import_module(module)
        except ImportError:
            raise ImportError(
                f"{api_name} requires the optional dependency {module!r}, "
                "which is not installed in this environment. Install it, "
                "or load via the generic paths: read_parquet/read_sql/"
                "Datasource plugins cover these formats' export paths."
            ) from None
        raise NotImplementedError(
            f"{api_name}: {module!r} is present but this connector is not "
            "implemented yet; use a Datasource plugin (data/datasource.py)")
    _reader.__name__ = api_name
    return _reader


# ---- writers ---------------------------------------------------------------

def write_block(block: Block, path: str, file_format: str, index: int,
                **kwargs) -> str:
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{index:06d}.{file_format}")
    if file_format == "parquet":
        import pyarrow.parquet as pq
        pq.write_table(block, out, **kwargs)
    elif file_format == "csv":
        import pyarrow.csv as pacsv
        pacsv.write_csv(block, out)
    elif file_format == "json":
        import json
        rows = list(BlockAccessor(block).iter_rows())
        with open(out, "w") as f:
            for r in rows:
                f.write(json.dumps(_json_safe(r)) + "\n")
    elif file_format == "npy":
        data = BlockAccessor(block).to_numpy()
        if len(data) == 1:
            np.save(out, next(iter(data.values())))
        else:
            np.savez(out, **data)
    else:
        raise ValueError(f"Unknown file format {file_format!r}")
    return out


def _json_safe(v):
    if isinstance(v, dict):
        return {k: _json_safe(x) for k, x in v.items()}
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, bytes):
        return v.decode("utf-8", errors="replace")
    return v


_DELTA_PARTITION_TYPES = {
    "string": "string", "integer": "int32", "long": "int64",
    "short": "int16", "byte": "int8", "double": "float64",
    "float": "float32", "boolean": "bool", "date": "date32",
}


def _delta_partition_array(delta_type: str, val: Optional[str], n: int):
    """Materialize one partition column: n copies of the add action's
    string-serialized partition value, converted to the schema type."""
    import pyarrow as pa

    pa_name = _DELTA_PARTITION_TYPES.get(delta_type)
    if pa_name is None:
        raise ValueError(
            f"unsupported Delta partition column type {delta_type!r} "
            f"(supported: {sorted(_DELTA_PARTITION_TYPES)})")
    typ = getattr(pa, pa_name)()
    if val is None:
        return pa.nulls(n, typ)
    v: Any = val
    if delta_type == "boolean":
        v = val == "true"
    elif delta_type == "date":
        import datetime

        v = datetime.date.fromisoformat(val)
    elif delta_type in ("integer", "long", "short", "byte"):
        v = int(val)
    elif delta_type in ("float", "double"):
        v = float(val)
    return pa.array([v] * n, type=typ)


def _delta_map(v) -> dict:
    """Normalize a Delta action's map field: JSON commits decode as
    dicts, but parquet checkpoints store map<string,string> which
    to_pydict yields as a list of (key, value) tuples."""
    if not v:
        return {}
    if isinstance(v, dict):
        return v
    return dict(v)


class DeltaDatasource(Datasource):
    """Delta Lake table reader, dependency-free (reference:
    _internal/datasource/delta_sharing_datasource.py fills this role via
    the deltalake lib; the table format itself is open: a parquet data
    set plus a JSON transaction log). Reconstructs the CURRENT snapshot:
    parquet checkpoint (if any) + JSON commits after it, applying
    add/remove actions in order. Partition columns (stored only in the
    add actions' partitionValues, not in the data files) are
    materialized back into each block with their schema types. Time
    travel / deletion vectors / column mapping are out of scope and
    refuse loudly."""

    def __init__(self, table_path: str,
                 columns: Optional[List[str]] = None):
        if "://" in table_path and not table_path.startswith("file://"):
            raise ValueError(
                f"read_delta reads local filesystems (got "
                f"{table_path!r}); mount or sync the table locally")
        if table_path.startswith("file://"):
            table_path = table_path[len("file://"):]
        self._root = table_path.rstrip("/")
        self._columns = columns
        # path -> partitionValues; plus the latest metaData's partition
        # schema {col: delta type}
        self._files, self._part_schema = self._live_files()

    def get_name(self):
        return "Delta"

    # -- log replay -------------------------------------------------------
    def _log_dir(self):
        return os.path.join(self._root, "_delta_log")

    def _find_checkpoint(self, log: str):
        """Latest COMPLETE checkpoint by listing the log dir (the
        _last_checkpoint hint is best-effort per the protocol — it can be
        missing or stale while checkpoint files exist, and trusting it
        alone silently drops every file the checkpoint compacted)."""
        import re

        single = re.compile(r"^(\d{20})\.checkpoint\.parquet$")
        multi = re.compile(
            r"^(\d{20})\.checkpoint\.(\d{10})\.(\d{10})\.parquet$")
        # keyed by (version, declared part count) so a complete 2-part
        # checkpoint is never mixed with / shadowed by an abandoned
        # 3-part attempt at the same version
        found: Dict[tuple, Dict[int, str]] = {}
        for name in os.listdir(log):
            m = single.match(name)
            if m:
                found.setdefault((int(m.group(1)), 1), {})[1] = name
                continue
            m = multi.match(name)
            if m:
                key = (int(m.group(1)), int(m.group(3)))
                found.setdefault(key, {})[int(m.group(2))] = name
        for v, total in sorted(found, reverse=True):
            parts = found[(v, total)]
            if len(parts) == total:
                return v, [os.path.join(log, parts[i + 1])
                           for i in range(total)]
        return -1, []

    def _live_files(self):
        import json

        log = self._log_dir()
        if not os.path.isdir(log):
            raise FileNotFoundError(
                f"{self._root} is not a Delta table (no _delta_log)")
        ckpt_version, ckpt_parts = self._find_checkpoint(log)
        live: Dict[str, Dict[str, Optional[str]]] = {}
        meta_holder: Dict[str, Any] = {}

        def check_metadata(md):
            if not md:
                return
            conf = _delta_map(md.get("configuration"))
            if conf.get("delta.columnMapping.mode", "none") != "none":
                raise ValueError(
                    "unsupported Delta feature: column mapping")
            meta_holder["meta"] = md

        def check_protocol(proto):
            if proto and int(proto.get("minReaderVersion") or 1) > 1:
                feats = proto.get("readerFeatures") or []
                raise ValueError(
                    f"unsupported Delta protocol: minReaderVersion="
                    f"{proto.get('minReaderVersion')} "
                    f"(readerFeatures={feats}) — this reader implements "
                    f"version 1 (plain parquet + log)")

        def apply_add(a):
            if a.get("deletionVector"):
                raise ValueError(
                    "unsupported Delta feature: deletion vectors")
            live[a["path"]] = _delta_map(a.get("partitionValues"))

        for part in ckpt_parts:
            import pyarrow.parquet as pq

            # project to the action columns consumed — checkpoints also
            # carry stats/txn/remove for every live file, and reading
            # those just to discard them stalls the driver on big tables
            names = pq.read_schema(part).names
            want = [c for c in ("add", "metaData", "protocol")
                    if c in names]
            cols = pq.read_table(part, columns=want).to_pydict()
            # metaData/protocol actions usually live IN the checkpoint
            # once one exists — gate there too, not just in JSON commits
            for md in cols.get("metaData") or []:
                check_metadata(md)
            for proto in cols.get("protocol") or []:
                check_protocol(proto)
            for add in cols.get("add") or []:
                if add and add.get("path"):
                    apply_add(add)
        commits = sorted(
            f for f in os.listdir(log)
            if f.endswith(".json") and f[:20].isdigit()
            and int(f[:20]) > ckpt_version)
        for name in commits:
            with open(os.path.join(log, name)) as f:
                for line in f:
                    if not line.strip():
                        continue
                    action = json.loads(line)
                    if "add" in action:
                        apply_add(action["add"])
                    elif "remove" in action:
                        live.pop(action["remove"]["path"], None)
                    elif "metaData" in action:
                        check_metadata(action["metaData"])
                    elif "protocol" in action:
                        check_protocol(action["protocol"])
        from urllib.parse import unquote

        part_schema = self._partition_schema(meta_holder.get("meta"), live)
        return ([(os.path.join(self._root, unquote(p)), pv)
                 for p, pv in live.items()], part_schema)

    @staticmethod
    def _partition_schema(meta, live) -> Dict[str, str]:
        """{partition column: delta type} from the latest metaData."""
        import json

        pcols = (meta or {}).get("partitionColumns") or []
        if not pcols:
            if any(pv for _, pv in live.items()):
                raise ValueError(
                    "Delta table has partitionValues but no metaData "
                    "action with partitionColumns was found in the log")
            return {}
        schema = json.loads(meta["schemaString"])
        types = {f["name"]: f["type"] for f in schema.get("fields", [])}
        out = {}
        for c in pcols:
            t = types.get(c)
            if not isinstance(t, str):
                raise ValueError(
                    f"unsupported Delta partition column {c!r}: type "
                    f"{t!r} is not a primitive")
            if t not in _DELTA_PARTITION_TYPES:
                raise ValueError(
                    f"unsupported Delta partition column type {t!r} "
                    f"for column {c!r}")
            out[c] = t
        return out

    # -- datasource surface ----------------------------------------------
    def estimate_inmemory_data_size(self):
        return _parquet_size_estimate([p for p, _ in self._files])

    def get_read_tasks(self, parallelism: int) -> List["ReadTask"]:
        groups = [self._files[i::parallelism] for i in range(parallelism)]
        groups = [g for g in groups if g]
        out = []
        for g in groups:
            def read(items=tuple(g), cols=self._columns,
                     pschema=self._part_schema):
                import pyarrow.parquet as pq

                for p, pvals in items:
                    file_cols = (None if cols is None else
                                 [c for c in cols if c not in pschema])
                    want_parts = [c for c in pschema
                                  if cols is None or c in cols]
                    if cols is not None and not file_cols and want_parts:
                        # partition-only projection: no parquet columns
                        # needed, just the row count
                        import pyarrow as pa

                        n = pq.ParquetFile(p).metadata.num_rows
                        tbl = pa.table({c: _delta_partition_array(
                            pschema[c], pvals.get(c), n)
                            for c in want_parts})
                        yield tbl
                        continue
                    # partitioning=None: the delta log's partitionValues
                    # are the source of truth — pyarrow would otherwise
                    # hive-infer day=... path segments as string columns,
                    # shadowing the schema-typed materialization below
                    tbl = pq.read_table(p, columns=file_cols,
                                        partitioning=None)
                    for c in want_parts:
                        # writers MAY also store partition columns in the
                        # data files; don't append a duplicate then
                        if c in tbl.column_names:
                            continue
                        tbl = tbl.append_column(c, _delta_partition_array(
                            pschema[c], pvals.get(c), tbl.num_rows))
                    yield tbl
            out.append(ReadTask(read, BlockMetadata(
                num_rows=None, size_bytes=None, schema=None,
                input_files=[p for p, _ in g])))
        return out


_CRC32C_FAST = None
_CRC32C_PROBED = False


def _parquet_fan_out(files: List[tuple], columns, parallelism: int
                     ) -> List["ReadTask"]:
    """Round-robin a known file list into parquet ReadTasks (shared by
    the table-format readers whose snapshots resolve to plain parquet
    file sets). ``files`` entries are (path, size_bytes, num_rows)
    tuples — table-format manifests carry exact per-file stats, so use
    them in block metadata instead of None/re-statting."""
    groups = [files[i::parallelism] for i in range(parallelism)]
    groups = [g for g in groups if g]
    out = []
    for g in groups:
        def read(paths=tuple(p for p, _, _ in g), cols=columns):
            import pyarrow.parquet as pq

            for p in paths:
                yield pq.read_table(p, columns=cols)
        sizes = [s for _, s, _ in g]
        rows = [r for _, _, r in g]
        out.append(ReadTask(read, BlockMetadata(
            num_rows=sum(rows) if all(r is not None for r in rows)
            else None,
            size_bytes=sum(sizes) if all(s is not None for s in sizes)
            else None,
            schema=None, input_files=[p for p, _, _ in g])))
    return out


def _parquet_size_estimate(files: List[str],
                           sizes: Optional[List[Optional[int]]] = None
                           ) -> Optional[int]:
    """On-disk bytes * decode ratio; exact manifest sizes when the
    caller has them, getsize syscalls otherwise."""
    try:
        total = sum(s if (sizes and sizes[i] is not None)
                    else os.path.getsize(files[i])
                    for i, s in enumerate(sizes or [None] * len(files)))
        return int(total * 5.0)
    except OSError:
        return None


def _iceberg_local_path(uri: str, root: str) -> str:
    """Resolve a location recorded in Iceberg metadata to a local path.
    Writers record full URIs at write time; strip file:// and fall back
    to joining relative paths under the table root."""
    if uri.startswith("file://"):
        uri = uri[len("file://"):]
    if "://" in uri:
        raise ValueError(
            f"read_iceberg reads local filesystems (metadata references "
            f"{uri!r}); mount or sync the table locally")
    if os.path.isabs(uri):
        return uri
    return os.path.join(root, uri)


class IcebergDatasource(Datasource):
    """Apache Iceberg table reader, dependency-free (reference:
    _internal/datasource/iceberg_datasource.py delegates to pyiceberg,
    which isn't in this image; the format itself is open: JSON table
    metadata + Avro manifest lists/manifests + parquet data files, all
    decoded with the in-tree readers). Reconstructs a snapshot: current
    metadata file -> snapshot -> manifest list (Avro) -> manifests
    (Avro) -> live parquet data files (entry status != DELETED).
    ``snapshot_id`` time-travels to any retained snapshot. Row-level
    deletes (v2 position/equality delete files) and non-parquet data
    files are out of scope and refuse loudly."""

    def __init__(self, table_path: str,
                 columns: Optional[List[str]] = None,
                 snapshot_id: Optional[int] = None):
        if "://" in table_path and not table_path.startswith("file://"):
            raise ValueError(
                f"read_iceberg reads local filesystem tables (got "
                f"{table_path!r}); mount or sync the table locally, or "
                "export to parquet and use read_parquet")
        if table_path.startswith("file://"):
            table_path = table_path[len("file://"):]
        self._root = table_path.rstrip("/")
        self._columns = columns
        self._files = self._live_files(snapshot_id)

    def get_name(self):
        return "Iceberg"

    # -- metadata resolution ----------------------------------------------

    def _current_metadata(self) -> str:
        """Latest metadata JSON: trust metadata/version-hint.text when it
        resolves, else pick the highest version among *.metadata.json
        (covers both v<N>.metadata.json and <N>-<uuid>.metadata.json
        naming)."""
        import re

        md = os.path.join(self._root, "metadata")
        if not os.path.isdir(md):
            raise FileNotFoundError(
                f"{self._root} is not an Iceberg table (no metadata/ dir)")
        hint = os.path.join(md, "version-hint.text")
        if os.path.exists(hint):
            v = open(hint).read().strip()
            for name in (f"v{v}.metadata.json", f"{v}.metadata.json"):
                p = os.path.join(md, name)
                if os.path.exists(p):
                    return p
        best, best_v = None, -1
        pat = re.compile(r"^v?(\d+)")
        for name in os.listdir(md):
            if not name.endswith(".metadata.json"):
                continue
            m = pat.match(name)
            v = int(m.group(1)) if m else 0
            if v > best_v:
                best, best_v = name, v
        if best is None:
            raise FileNotFoundError(
                f"{md} contains no *.metadata.json files")
        return os.path.join(md, best)

    def _live_files(self, snapshot_id: Optional[int]) -> List[tuple]:
        """Returns (local path, size_bytes, record_count) per live data
        file, stats straight from the manifest entries."""
        import json

        meta = json.load(open(self._current_metadata()))
        fv = int(meta.get("format-version") or 1)
        if fv > 2:
            raise ValueError(
                f"unsupported Iceberg format-version {fv} (this reader "
                "implements v1/v2)")
        snapshots = meta.get("snapshots") or []
        if snapshot_id is None:
            snapshot_id = meta.get("current-snapshot-id")
        if snapshot_id is None or snapshot_id == -1 or not snapshots:
            return []  # empty table: no snapshot yet
        snap = next((s for s in snapshots
                     if s.get("snapshot-id") == snapshot_id), None)
        if snap is None:
            raise ValueError(
                f"snapshot {snapshot_id} not found in "
                f"{sorted(s.get('snapshot-id') for s in snapshots)}")

        manifests: List[str] = []
        live: List[tuple] = []  # (path, size_bytes, record_count)
        if snap.get("manifest-list"):
            for e in read_avro_rows(
                    _iceberg_local_path(snap["manifest-list"], self._root)):
                # v2 manifest lists mark delete manifests via content=1
                if int(e.get("content") or 0) != 0:
                    raise ValueError(
                        "unsupported Iceberg feature: row-level delete "
                        "manifests (merge-on-read v2 tables); compact/"
                        "rewrite the table to copy-on-write first")
                manifests.append(e["manifest_path"])
        else:
            # v1 inline manifest listing
            manifests = list(snap.get("manifests") or [])

        for mpath in manifests:
            for entry in read_avro_rows(
                    _iceberg_local_path(mpath, self._root)):
                if int(entry.get("status") or 0) == 2:  # DELETED
                    continue
                df = entry.get("data_file") or {}
                if int(df.get("content") or 0) != 0:
                    raise ValueError(
                        "unsupported Iceberg feature: delete files "
                        "(position/equality deletes)")
                fmt = (df.get("file_format") or "PARQUET").upper()
                if fmt != "PARQUET":
                    raise ValueError(
                        f"unsupported Iceberg data file format {fmt!r} "
                        "(parquet only)")
                live.append(
                    (_iceberg_local_path(df["file_path"], self._root),
                     df.get("file_size_in_bytes"),
                     df.get("record_count")))
        return live

    # -- datasource surface ----------------------------------------------

    def estimate_inmemory_data_size(self):
        return _parquet_size_estimate([p for p, _, _ in self._files],
                                      [s for _, s, _ in self._files])

    def get_read_tasks(self, parallelism: int) -> List["ReadTask"]:
        return _parquet_fan_out(self._files, self._columns, parallelism)


def _crc32c_fast():
    """Best importable C implementation of CRC-32C, probed once: the
    crc32c or google-crc32c extensions if installed."""
    global _CRC32C_FAST, _CRC32C_PROBED
    if _CRC32C_PROBED:
        return _CRC32C_FAST
    _CRC32C_PROBED = True
    try:
        import crc32c as _c

        _CRC32C_FAST = _c.crc32c
        return _CRC32C_FAST
    except (ImportError, AttributeError):
        pass
    try:
        import google_crc32c as _g

        _CRC32C_FAST = _g.value
    except ImportError:
        _CRC32C_FAST = None
    return _CRC32C_FAST


def _crc32c(data: bytes) -> int:
    """CRC-32C (Castagnoli) — the TFRecord framing checksum. Uses a C
    extension when one is importable; the pure-python table loop is the
    dependency-free fallback (~MB/s — fine for tests and small writes,
    install crc32c for bulk exports)."""
    fast = _crc32c_fast()
    if fast is not None:
        return fast(data)
    table = _crc32c_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


_CRC32C_TABLE: Optional[List[int]] = None


def _crc32c_table():
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        poly = 0x82F63B78
        tbl = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            tbl.append(c)
        _CRC32C_TABLE = tbl
    return _CRC32C_TABLE


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF)


def write_tfrecords_file(records, path: str) -> int:
    """Write raw byte records in TFRecord framing WITH valid masked
    CRC-32C checksums (interoperable with TensorFlow readers; the
    in-repo reader skips checksum verification). Returns record count."""
    import struct

    n = 0
    with open(path, "wb") as f:
        for rec in records:
            rec = bytes(rec)
            header = struct.pack("<Q", len(rec))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(rec)
            f.write(struct.pack("<I", _masked_crc(rec)))
            n += 1
    return n


class HuggingFaceDatasource(FileDatasource):
    """Distributed reader for the HF ``datasets`` LOCAL on-disk format
    (``Dataset.save_to_disk``: arrow shard files + state.json listing
    them; DatasetDict adds one subdirectory per split). Shards split
    across read tasks, so a big saved dataset streams without the
    driver materializing it — unlike ``from_huggingface``, which
    converts an in-memory Dataset (reference:
    _internal/datasource/huggingface_datasource.py; no network or hub
    client needed for this path)."""

    suffixes = [".arrow"]

    def __init__(self, path, split: Optional[str] = None):
        import json

        path = os.path.abspath(os.fspath(path))
        if os.path.isdir(path):
            if os.path.exists(os.path.join(path, "dataset_dict.json")):
                splits = sorted(
                    d for d in os.listdir(path)
                    if os.path.exists(os.path.join(path, d, "state.json")))
                if split is None:
                    raise ValueError(
                        f"{path} holds a DatasetDict with splits "
                        f"{splits}; pass split=...")
                if split not in splits:
                    raise ValueError(
                        f"split {split!r} not in {splits} at {path}")
                path = os.path.join(path, split)
            state = os.path.join(path, "state.json")
            if os.path.exists(state):
                with open(state) as f:
                    files = [os.path.join(path, e["filename"])
                             for e in json.load(f)["_data_files"]]
                self._paths = files
                return
        super().__init__(path)

    def read_file(self, path: str):
        import pyarrow.ipc as ipc

        # save_to_disk shards are Arrow STREAMING format; memory-map so
        # a shard larger than the block target still reads lazily
        with pa.memory_map(path) as source:
            try:
                reader = ipc.open_stream(source)
            except pa.ArrowInvalid:
                reader = ipc.open_file(source)  # the random-access variant
            for batch in reader:
                if batch.num_rows:
                    yield pa.Table.from_batches([batch])
