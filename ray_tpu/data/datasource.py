"""Datasources: pluggable readers/writers producing ReadTasks.

Reference: python/ray/data/read_api.py + python/ray/data/_internal/datasource/
(parquet, csv, json, numpy, range, binary, text datasources). A Datasource
plans itself into independent ``ReadTask``s — serializable thunks the
streaming executor runs as remote tasks, each yielding blocks.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Iterable, List, Optional

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata


class ReadTask:
    """A serializable unit of read work (reference:
    python/ray/data/datasource/datasource.py ReadTask)."""

    def __init__(self, read_fn: Callable[[], Iterable[Block]],
                 metadata: BlockMetadata):
        self._read_fn = read_fn
        self.metadata = metadata  # estimate; actual metadata computed on read

    def __call__(self) -> Iterable[Block]:
        return self._read_fn()


class Datasource:
    """Base class for custom datasources (reference:
    python/ray/data/datasource/datasource.py Datasource)."""

    def get_name(self) -> str:
        return type(self).__name__.replace("Datasource", "")

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError


# ---- built-in sources ------------------------------------------------------

class RangeDatasource(Datasource):
    def __init__(self, n: int, use_tensor: bool = False,
                 tensor_shape: tuple = (1,)):
        self._n = n
        self._use_tensor = use_tensor
        self._tensor_shape = tensor_shape

    def estimate_inmemory_data_size(self):
        return self._n * 8 * int(np.prod(self._tensor_shape))

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        n, k = self._n, max(1, parallelism)
        use_tensor, shape = self._use_tensor, self._tensor_shape
        for i in range(k):
            start = n * i // k
            end = n * (i + 1) // k
            if end <= start:
                continue

            def read(start=start, end=end):
                ids = np.arange(start, end, dtype=np.int64)
                if use_tensor:
                    data = np.broadcast_to(
                        ids.reshape((-1,) + (1,) * len(shape)),
                        (end - start,) + shape).copy()
                    yield BlockAccessor.batch_to_block({"data": data})
                else:
                    yield BlockAccessor.batch_to_block({"id": ids})

            meta = BlockMetadata(num_rows=end - start,
                                 size_bytes=(end - start) * 8)
            tasks.append(ReadTask(read, meta))
        return tasks


class ItemsDatasource(Datasource):
    def __init__(self, items: List[Any]):
        self._items = list(items)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        n, k = len(self._items), max(1, parallelism)
        for i in range(k):
            chunk = self._items[n * i // k:n * (i + 1) // k]
            if not chunk:
                continue

            def read(chunk=chunk):
                yield BlockAccessor.rows_to_block(chunk)

            tasks.append(ReadTask(read, BlockMetadata(len(chunk), 0)))
        return tasks


def _expand_paths(paths, suffixes: Optional[List[str]] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _, files in os.walk(p):
                for f in sorted(files):
                    if f.startswith((".", "_")):
                        continue
                    if suffixes and not any(f.endswith(s) for s in suffixes):
                        continue
                    out.append(os.path.join(root, f))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"No input files found for {paths!r}")
    return out


class FileDatasource(Datasource):
    """Shared logic for file-based sources: split files across read tasks."""

    suffixes: Optional[List[str]] = None

    def __init__(self, paths):
        self._paths = _expand_paths(paths, self.suffixes)

    def estimate_inmemory_data_size(self):
        try:
            return sum(os.path.getsize(p) for p in self._paths)
        except OSError:
            return None

    def read_file(self, path: str) -> Iterable[Block]:
        raise NotImplementedError

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        groups = np.array_split(np.asarray(self._paths, dtype=object),
                                max(1, min(parallelism, len(self._paths))))
        for grp in groups:
            paths = [str(p) for p in grp]
            if not paths:
                continue

            def read(paths=paths, self=self):
                for p in paths:
                    yield from self.read_file(p)

            size = sum(os.path.getsize(p) for p in paths
                       if os.path.exists(p))
            tasks.append(ReadTask(read, BlockMetadata(
                num_rows=0, size_bytes=size, input_files=paths)))
        return tasks


class ParquetDatasource(FileDatasource):
    suffixes = [".parquet"]

    def __init__(self, paths, columns: Optional[List[str]] = None):
        super().__init__(paths)
        self._columns = columns

    def read_file(self, path: str):
        import pyarrow.parquet as pq
        pf = pq.ParquetFile(path)
        for batch in pf.iter_batches(columns=self._columns):
            yield pa.Table.from_batches([batch])


class CSVDatasource(FileDatasource):
    suffixes = [".csv"]

    def read_file(self, path: str):
        import pyarrow.csv as pacsv
        yield pacsv.read_csv(path)


class JSONDatasource(FileDatasource):
    suffixes = [".json", ".jsonl"]

    def read_file(self, path: str):
        import pyarrow.json as pajson
        yield pajson.read_json(path)


class NumpyDatasource(FileDatasource):
    suffixes = [".npy"]

    def read_file(self, path: str):
        arr = np.load(path)
        yield BlockAccessor.batch_to_block({"data": arr})


class TextDatasource(FileDatasource):
    def read_file(self, path: str):
        with open(path, "r", errors="replace") as f:
            lines = [ln.rstrip("\n") for ln in f]
        yield pa.table({"text": pa.array(lines)})


class BinaryDatasource(FileDatasource):
    def read_file(self, path: str):
        with open(path, "rb") as f:
            data = f.read()
        yield pa.table({"bytes": pa.array([data], type=pa.binary()),
                        "path": pa.array([path])})


class TFRecordsDatasource(FileDatasource):
    """Minimal TFRecord reader (uncompressed): parses the framing format
    (length/crc framing per the TFRecord spec) and yields raw example
    bytes; decoding protos is left to a downstream map (torch/tf-free)."""

    suffixes = [".tfrecords", ".tfrecord"]

    def read_file(self, path: str):
        import struct
        records = []
        with open(path, "rb") as f:
            while True:
                header = f.read(8)
                if len(header) < 8:
                    break
                (length,) = struct.unpack("<Q", header)
                f.read(4)  # length crc
                records.append(f.read(length))
                f.read(4)  # data crc
        yield pa.table({"bytes": pa.array(records, type=pa.binary())})


class WebDatasetDatasource(FileDatasource):
    """WebDataset-style tar shards (reference:
    _internal/datasource/webdataset_datasource.py): each sample is the
    group of tar members sharing a basename; extensions become columns
    holding raw bytes (decoding is a downstream map)."""

    suffixes = [".tar"]

    def read_file(self, path: str):
        import tarfile

        samples: dict = {}
        order: List[str] = []
        with tarfile.open(path) as tf:
            for member in tf:
                if not member.isfile():
                    continue
                # webdataset convention: split at the first dot of the LAST
                # path component (dotted directories stay in the key)
                dirname, _, fname = member.name.rpartition("/")
                stem, _, ext = fname.partition(".")
                base = f"{dirname}/{stem}" if dirname else stem
                data = tf.extractfile(member).read()
                if base not in samples:
                    samples[base] = {"__key__": base}
                    order.append(base)
                samples[base][ext or "bin"] = data
        if not order:
            return
        cols = sorted({k for s in samples.values() for k in s})
        table = {}
        for c in cols:
            vals = [samples[b].get(c) for b in order]
            if c == "__key__":
                table[c] = pa.array(vals, type=pa.string())
            else:
                table[c] = pa.array(vals, type=pa.binary())
        yield pa.table(table)


class SQLDatasource(Datasource):
    """Rows from a DBAPI connection factory (reference:
    _internal/datasource/sql_datasource.py; works out of the box with
    stdlib sqlite3)."""

    def __init__(self, sql: str, connection_factory):
        self._sql = sql
        self._factory = connection_factory

    def estimate_inmemory_data_size(self):
        return None

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        sql, factory = self._sql, self._factory

        def read():
            conn = factory()
            try:
                cur = conn.cursor()
                cur.execute(sql)
                names = [d[0] for d in cur.description]
                # page the cursor so huge result sets stream as bounded
                # blocks instead of one fetchall() materialization
                while True:
                    rows = cur.fetchmany(10_000)
                    if not rows:
                        break
                    cols = {n: pa.array([r[i] for r in rows])
                            for i, n in enumerate(names)}
                    yield pa.table(cols)
            finally:
                conn.close()

        return [ReadTask(read, BlockMetadata(num_rows=0, size_bytes=0,
                                             input_files=[]))]


# ---- writers ---------------------------------------------------------------

def write_block(block: Block, path: str, file_format: str, index: int,
                **kwargs) -> str:
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{index:06d}.{file_format}")
    if file_format == "parquet":
        import pyarrow.parquet as pq
        pq.write_table(block, out, **kwargs)
    elif file_format == "csv":
        import pyarrow.csv as pacsv
        pacsv.write_csv(block, out)
    elif file_format == "json":
        import json
        rows = list(BlockAccessor(block).iter_rows())
        with open(out, "w") as f:
            for r in rows:
                f.write(json.dumps(_json_safe(r)) + "\n")
    elif file_format == "npy":
        data = BlockAccessor(block).to_numpy()
        if len(data) == 1:
            np.save(out, next(iter(data.values())))
        else:
            np.savez(out, **data)
    else:
        raise ValueError(f"Unknown file format {file_format!r}")
    return out


def _json_safe(v):
    if isinstance(v, dict):
        return {k: _json_safe(x) for k, x in v.items()}
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, bytes):
        return v.decode("utf-8", errors="replace")
    return v
