"""Column expressions: declarative row logic the optimizer can read.

Reference: the Ray Data expression surface (ray.data.expressions —
``col``/``lit`` combining into vectorized predicates and projections).
A lambda is opaque; an ``Expr`` exposes exactly which columns it
touches (``columns()``), so plans built from expressions feed the
projection-pushdown rule (optimizer.py: ProjectionPushdown) and file
readers prune columns at the source.

Usage::

    from ray_tpu.data.expr import col, lit

    ds.filter(expr=(col("age") >= 18) & (col("country") == "DE"))
    ds.with_column("usd", col("cents") / 100.0)
    ds.select_columns(["usd"])   # parquet read prunes to {cents}
"""

from __future__ import annotations

import operator
from typing import Any, Callable, Dict, FrozenSet

import numpy as np


class Expr:
    """A vectorized expression over one batch (dict of numpy columns)."""

    def eval(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        raise NotImplementedError

    def columns(self) -> FrozenSet[str]:
        raise NotImplementedError

    # -- operator sugar ---------------------------------------------------
    def _bin(self, op, other, symbol: str, reflected: bool = False):
        other = other if isinstance(other, Expr) else Literal(other)
        return (BinaryOp(op, other, self, symbol) if reflected
                else BinaryOp(op, self, other, symbol))

    def __add__(self, o):
        return self._bin(operator.add, o, "+")

    def __radd__(self, o):
        return self._bin(operator.add, o, "+", True)

    def __sub__(self, o):
        return self._bin(operator.sub, o, "-")

    def __rsub__(self, o):
        return self._bin(operator.sub, o, "-", True)

    def __mul__(self, o):
        return self._bin(operator.mul, o, "*")

    def __rmul__(self, o):
        return self._bin(operator.mul, o, "*", True)

    def __truediv__(self, o):
        return self._bin(operator.truediv, o, "/")

    def __rtruediv__(self, o):
        return self._bin(operator.truediv, o, "/", True)

    def __floordiv__(self, o):
        return self._bin(operator.floordiv, o, "//")

    def __mod__(self, o):
        return self._bin(operator.mod, o, "%")

    def __pow__(self, o):
        return self._bin(operator.pow, o, "**")

    def __eq__(self, o):  # type: ignore[override]
        return self._bin(operator.eq, o, "==")

    def __ne__(self, o):  # type: ignore[override]
        return self._bin(operator.ne, o, "!=")

    def __lt__(self, o):
        return self._bin(operator.lt, o, "<")

    def __le__(self, o):
        return self._bin(operator.le, o, "<=")

    def __gt__(self, o):
        return self._bin(operator.gt, o, ">")

    def __ge__(self, o):
        return self._bin(operator.ge, o, ">=")

    def __and__(self, o):
        return self._bin(np.logical_and, o, "&")

    def __or__(self, o):
        return self._bin(np.logical_or, o, "|")

    def __invert__(self):
        return UnaryOp(np.logical_not, self, "~")

    def __neg__(self):
        return UnaryOp(operator.neg, self, "-")

    def __abs__(self):
        return UnaryOp(np.abs, self, "abs")

    def abs(self):
        return UnaryOp(np.abs, self, "abs")

    def is_null(self):
        u = UnaryOp(lambda a: np.asarray(
            [v is None or (isinstance(v, float) and np.isnan(v))
             for v in np.asarray(a).ravel().tolist()])
            if np.asarray(a).dtype == object else np.isnan(a),
            self, "is_null")
        u.kind = "is_null"
        return u

    def isin(self, values):
        vals = tuple(values)
        u = UnaryOp(lambda a: np.isin(a, np.asarray(vals)),
                    self, f"isin{vals!r}")
        u.kind = "isin"
        u.values = vals
        return u

    def cast(self, dtype):
        u = UnaryOp(lambda a, _d=np.dtype(dtype): a.astype(_d),
                    self, f"cast[{dtype}]")
        u.kind = "cast"
        u.np_dtype = np.dtype(dtype)
        return u

    # hashability: __eq__ builds an Expr, so default hashing breaks;
    # identity hash keeps Exprs usable in dicts/sets
    def __hash__(self):
        return id(self)

    def __bool__(self):
        raise TypeError(
            "an Expr has no truth value — use & | ~ for boolean logic "
            "(Python's `and`/`or` cannot be overloaded)")


class Column(Expr):
    def __init__(self, name: str):
        self.name = name

    def eval(self, batch):
        return np.asarray(batch[self.name])

    def columns(self):
        return frozenset({self.name})

    def __repr__(self):
        return f"col({self.name!r})"


class Literal(Expr):
    def __init__(self, value: Any):
        self.value = value

    def eval(self, batch):
        return self.value

    def columns(self):
        return frozenset()

    def __repr__(self):
        return f"lit({self.value!r})"


class BinaryOp(Expr):
    def __init__(self, op: Callable, left: Expr, right: Expr,
                 symbol: str):
        self.op = op
        self.left = left
        self.right = right
        self.symbol = symbol

    def eval(self, batch):
        return self.op(self.left.eval(batch), self.right.eval(batch))

    def columns(self):
        return self.left.columns() | self.right.columns()

    def __repr__(self):
        return f"({self.left!r} {self.symbol} {self.right!r})"


class UnaryOp(Expr):
    def __init__(self, op: Callable, operand: Expr, symbol: str):
        self.op = op
        self.operand = operand
        self.symbol = symbol

    def eval(self, batch):
        return self.op(self.operand.eval(batch))

    def columns(self):
        return self.operand.columns()

    def __repr__(self):
        return f"{self.symbol}({self.operand!r})"


def col(name: str) -> Column:
    """Reference a column (reference: ray.data.expressions.col)."""
    return Column(name)


def lit(value: Any) -> Literal:
    """A constant (reference: ray.data.expressions.lit)."""
    return Literal(value)


# -- pyarrow conversion (predicate pushdown into file scans) -----------------

# Pushdown converts only expressions whose pyarrow semantics match the
# numpy eval path ROW-FOR-ROW, including on NULLs. Nulls surface as NaN
# in numpy, so a comparison yields False (row dropped) where pyarrow
# yields null (row dropped) — equivalent for ==, <, <=, >, >=. NOT
# equivalent, and therefore excluded:
#  - "!=": NaN != x is True (kept) but null != x is null (dropped)
#  - "~":  negation turns dropped-on-both into kept-vs-dropped
#  - "/":  pyarrow divides integers integrally; numpy truediv floats
# "&"/"|" are faithful under Kleene logic ONLY over boolean-producing
# operands (null AND/OR propagation lands on the same kept/dropped
# outcome as numpy's False); over non-boolean operands numpy coerces
# truthiness while pyarrow's and_kleene has no integer kernel at all.
_PA_BINOPS = frozenset({"+", "-", "*", "==", "<", "<=", ">", ">=",
                        "&", "|"})
_BOOL_BINOPS = frozenset({"==", "<", "<=", ">", ">=", "&", "|"})


def _is_boolean(expr: Expr) -> bool:
    """Does this expression produce a boolean column (comparison/isin/
    is_null or a combination of them)?"""
    if isinstance(expr, BinaryOp):
        return expr.symbol in _BOOL_BINOPS
    if isinstance(expr, UnaryOp):
        return getattr(expr, "kind", expr.symbol) in ("isin", "is_null")
    return False


def to_pyarrow(expr: Expr):
    """Convert an Expr to a ``pyarrow.dataset`` filter expression, or
    return None when any sub-expression has no faithful pyarrow
    equivalent (the caller then keeps the in-memory filter)."""
    import operator as op

    import pyarrow as pa
    import pyarrow.compute as pc

    if isinstance(expr, Column):
        return pc.field(expr.name)
    if isinstance(expr, Literal):
        try:
            return pc.scalar(expr.value)
        except (pa.ArrowInvalid, pa.ArrowNotImplementedError, TypeError):
            return None
    if isinstance(expr, BinaryOp):
        if expr.symbol not in _PA_BINOPS:
            return None
        if expr.symbol in ("&", "|") and not (
                _is_boolean(expr.left) and _is_boolean(expr.right)):
            return None
        left = to_pyarrow(expr.left)
        right = to_pyarrow(expr.right)
        if left is None or right is None:
            return None
        fns = {"+": op.add, "-": op.sub, "*": op.mul, "==": op.eq,
               "<": op.lt, "<=": op.le, ">": op.gt, ">=": op.ge,
               "&": op.and_, "|": op.or_}
        return fns[expr.symbol](left, right)
    if isinstance(expr, UnaryOp):
        inner = to_pyarrow(expr.operand)
        if inner is None:
            return None
        kind = getattr(expr, "kind", expr.symbol)
        if kind == "is_null":
            # the numpy eval path treats NaN as null; match it
            return inner.is_null(nan_is_null=True)
        if kind == "isin":
            return inner.isin(list(expr.values))
        # cast is NOT pushed: pyarrow's safe cast raises on float
        # truncation/NaN where numpy astype silently truncates — a
        # pushed cast(float->int) filter would crash the scan (or
        # diverge) instead of matching the in-memory mask
        return None
    return None


# -- row-group statistics pruning (parquet predicate pushdown) ---------------

def _interval_eval(expr: Expr, stats) -> "bool | None":
    """Tri-state evaluation of a boolean expr against row-group
    statistics {column: (min, max)}: True = every non-null row matches,
    False = NO row can match, None = unknown. Conservative by
    construction — anything unmodellable is None (keep the group).
    Null semantics: every supported operator drops nulls (the reason
    "!="/"~" are never pushed down, see _PA_BINOPS), so min/max bounds
    over the non-null values are sufficient to prove emptiness."""

    def col_lit(b: BinaryOp):
        if isinstance(b.left, Column) and isinstance(b.right, Literal):
            return b.left.name, b.right.value, False
        if isinstance(b.right, Column) and isinstance(b.left, Literal):
            return b.right.name, b.left.value, True
        return None

    if isinstance(expr, BinaryOp):
        if expr.symbol == "&":
            a = _interval_eval(expr.left, stats)
            b = _interval_eval(expr.right, stats)
            if a is False or b is False:
                return False
            if a is True and b is True:
                return True
            return None
        if expr.symbol == "|":
            a = _interval_eval(expr.left, stats)
            b = _interval_eval(expr.right, stats)
            if a is True or b is True:
                return True
            if a is False and b is False:
                return False
            return None
        if expr.symbol in ("==", "<", "<=", ">", ">="):
            cl = col_lit(expr)
            if cl is None:
                return None
            name, v, flipped = cl
            if name not in stats:
                return None
            mn, mx = stats[name]
            sym = expr.symbol
            if flipped:  # lit OP col  ->  col OP' lit
                sym = {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
                       "==": "=="}[sym]
            try:
                if sym == "==":
                    if v < mn or v > mx:
                        return False
                    if mn == mx == v:
                        return True
                elif sym == "<":
                    if mn >= v:
                        return False
                    if mx < v:
                        return True
                elif sym == "<=":
                    if mn > v:
                        return False
                    if mx <= v:
                        return True
                elif sym == ">":
                    if mx <= v:
                        return False
                    if mn > v:
                        return True
                elif sym == ">=":
                    if mx < v:
                        return False
                    if mn >= v:
                        return True
            except TypeError:
                return None  # incomparable types: keep the group
            return None
    if isinstance(expr, UnaryOp):
        kind = getattr(expr, "kind", expr.symbol)
        if kind == "isin" and isinstance(expr.operand, Column):
            name = expr.operand.name
            if name not in stats:
                return None
            mn, mx = stats[name]
            try:
                if all(v < mn or v > mx for v in expr.values):
                    return False
            except TypeError:
                return None
            return None
    return None


def row_group_may_match(expr: Expr, stats) -> bool:
    """False ONLY when the statistics PROVE the predicate matches no row
    of the group — the parquet scan then skips the group entirely."""
    return _interval_eval(expr, stats) is not False
