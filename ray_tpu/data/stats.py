"""Per-dataset execution statistics.

Reference: python/ray/data/_internal/stats.py — per-operator wall time,
task counts, and rows, surfaced via Dataset.stats().
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List


@dataclass
class OpStats:
    name: str
    tasks_finished: int = 0
    rows: int = 0


@dataclass
class DatasetStats:
    ops: List[OpStats] = field(default_factory=list)
    wall_time_s: float = 0.0

    def add_op(self, name: str) -> OpStats:
        s = OpStats(name)
        self.ops.append(s)
        return s

    def summary(self) -> str:
        lines = [f"Dataset execution: {self.wall_time_s:.3f}s"]
        for s in self.ops:
            lines.append(
                f"  {s.name}: {s.tasks_finished} tasks, {s.rows} rows")
        return "\n".join(lines)
