"""Per-dataset execution statistics.

Reference: python/ray/data/_internal/stats.py — per-operator wall/cpu
time, rows and bytes in/out, peak block size, task counts, and
backpressure wait, surfaced via ``Dataset.stats()`` as a formatted
summary. Task-side numbers ride each block's ``BlockMetadata.exec_stats``
(measured inside the remote task); executor-side numbers (queueing,
backpressure) are accumulated by the scheduling loop.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GB"


@dataclass
class OpStats:
    name: str
    tasks_launched: int = 0
    tasks_finished: int = 0
    rows_in: int = 0
    rows_out: int = 0
    bytes_in: int = 0
    bytes_out: int = 0
    task_wall_s: float = 0.0      # summed in-task execution wall time
    task_cpu_s: float = 0.0       # summed in-task process_time
    sched_wall_s: float = 0.0     # launch -> completion (incl. queueing)
    peak_block_bytes: int = 0
    backpressure_s: float = 0.0   # time gated by downstream pressure
    # Pipeline-relative timeline (seconds since execution start). With
    # streaming map returns a downstream op's started_s precedes its
    # upstream's finished_s — blocks flow before the producing task ends.
    started_s: Optional[float] = None       # first task launched / output
    first_output_s: Optional[float] = None  # first rows emitted
    finished_s: Optional[float] = None      # operator fully done

    # kept for pre-existing callers
    @property
    def rows(self) -> int:
        return self.rows_out

    @rows.setter
    def rows(self, v: int):
        self.rows_out = v

    def lines(self) -> List[str]:
        out = [f"  {self.name}:"]
        out.append(
            f"    tasks: {self.tasks_finished} finished"
            + (f" / {self.tasks_launched} launched"
               if self.tasks_launched else ""))
        out.append(
            f"    rows: {self.rows_in} in -> {self.rows_out} out"
            f"  ({_fmt_bytes(self.bytes_in)} -> "
            f"{_fmt_bytes(self.bytes_out)})")
        if self.task_wall_s or self.task_cpu_s:
            out.append(
                f"    time: {self.task_wall_s:.3f}s wall, "
                f"{self.task_cpu_s:.3f}s cpu in tasks; "
                f"{self.sched_wall_s:.3f}s launch-to-done")
        if self.peak_block_bytes:
            out.append(
                f"    peak block: {_fmt_bytes(self.peak_block_bytes)}")
        if self.started_s is not None:
            seg = f"    timeline: start +{self.started_s:.3f}s"
            if self.first_output_s is not None:
                seg += f", first output +{self.first_output_s:.3f}s"
            if self.finished_s is not None:
                seg += f", done +{self.finished_s:.3f}s"
            out.append(seg)
        if self.backpressure_s > 0.0005:
            out.append(
                f"    backpressured: {self.backpressure_s:.3f}s")
        return out


@dataclass
class DatasetStats:
    ops: List[OpStats] = field(default_factory=list)
    wall_time_s: float = 0.0

    def add_op(self, name: str) -> OpStats:
        s = OpStats(name)
        self.ops.append(s)
        return s

    def bottleneck(self) -> str:
        """Name of the operator with the most in-task wall time (ties:
        launch-to-done time) — the first place to look when a pipeline
        is slow."""
        if not self.ops:
            return ""
        return max(self.ops, key=lambda s: (s.task_wall_s,
                                            s.sched_wall_s)).name

    def summary(self) -> str:
        lines = [f"Dataset execution: {self.wall_time_s:.3f}s wall"]
        for s in self.ops:
            lines.extend(s.lines())
        bn = self.bottleneck()
        if bn:
            lines.append(f"  bottleneck: {bn}")
        return "\n".join(lines)
