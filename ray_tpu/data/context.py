"""DataContext: per-dataset execution configuration.

Reference: python/ray/data/context.py (DataContext) — a process-wide
singleton of execution knobs, snapshotted per-dataset at creation time.
"""

from __future__ import annotations

import copy
import threading
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class DataContext:
    """Execution knobs for ray_tpu.data pipelines.

    TPU-first defaults: blocks sized so a handful of them fit in host RAM
    while batches stream into HBM; numpy is the default batch format since
    it feeds ``jax.device_put`` zero-copy.
    """

    # Target size of a block produced by reads/shuffles, in bytes.
    target_max_block_size: int = 128 * 1024 * 1024
    # Minimum rows per block before reads further subdivide files.
    target_min_block_size: int = 1 * 1024 * 1024
    # Max blocks buffered in an operator's output queue before backpressure.
    max_op_output_queue_blocks: int = 16
    # Cap on concurrently running tasks per map operator (None = executor
    # derives it from the worker pool size).
    max_tasks_in_flight_per_op: Optional[int] = None
    # Default batch format for iter_batches / map_batches.
    batch_format: str = "numpy"
    # Default parallelism for reads when not specified (-1 = auto).
    read_parallelism: int = -1
    # Whether the optimizer fuses compatible map operators.
    optimizer_enabled: bool = True
    # Preserve input order of blocks through execution.
    preserve_order: bool = True
    # Number of batches prefetched by iterators (double-buffering into HBM).
    prefetch_batches: int = 2
    # Raise instead of warn when a map UDF returns an unknown type.
    strict_mode: bool = True
    # Run read/map tasks as num_returns="streaming" generators so each
    # output block is sealed and routed downstream as it is produced
    # (downstream operators start before the producing task finishes).
    streaming_map_returns: bool = True
    # Extra metadata attached by tests.
    extras: dict = field(default_factory=dict)

    _current: "DataContext" = None  # class-level, set below
    _lock = threading.Lock()

    @staticmethod
    def get_current() -> "DataContext":
        with DataContext._lock:
            if DataContext._current is None:
                DataContext._current = DataContext()
            return DataContext._current

    def copy(self) -> "DataContext":
        c = copy.copy(self)
        c.extras = dict(self.extras)
        return c
