"""Dataset: the lazy, streaming, distributed dataset facade.

Reference: python/ray/data/dataset.py (Dataset :139) + read_api.py. Builds
a logical plan per transform; execution is deferred to consumption
(iter_batches/take/write_*) and runs on the streaming executor over the
ray_tpu task runtime, blocks living in the shared-memory object store.
"""

from __future__ import annotations

import builtins
import math
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.data import logical as L
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.context import DataContext
from ray_tpu.data.datasource import (
    BinaryDatasource,
    CSVDatasource,
    Datasource,
    ItemsDatasource,
    JSONDatasource,
    NumpyDatasource,
    ParquetDatasource,
    RangeDatasource,
    TextDatasource,
    TFRecordsDatasource,
)
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.logical import ActorPoolStrategy, TaskPoolStrategy
from ray_tpu.data.physical import RefBundle
from ray_tpu.data.planner import Planner
from ray_tpu.data.streaming_executor import StreamingExecutor


class Dataset:
    def __init__(self, logical_op: L.LogicalOperator,
                 context: Optional[DataContext] = None):
        self._logical_op = logical_op
        self._context = context or DataContext.get_current().copy()
        self._last_stats: Optional[str] = None

    # ---- transforms (lazy) ----

    def _map(self, name: str, kind: str, fn, *, compute=None,
             batch_size=None, batch_format=None, fn_args=(), fn_kwargs=None,
             num_chips=0, fn_constructor_args=()) -> "Dataset":
        node = L.AbstractMap(
            name, self._logical_op, kind, fn, fn_args, fn_kwargs,
            batch_size=batch_size, batch_format=batch_format,
            compute=compute, num_chips=num_chips,
            fn_constructor_args=fn_constructor_args)
        return Dataset(node, self._context)

    def map(self, fn: Callable, *, compute=None, num_chips: int = 0,
            fn_args=(), fn_kwargs=None) -> "Dataset":
        """Row-wise transform (reference: Dataset.map)."""
        return self._map("Map", "map_rows", fn, compute=compute,
                         num_chips=num_chips, fn_args=fn_args,
                         fn_kwargs=fn_kwargs)

    def map_batches(self, fn: Union[Callable, type], *,
                    batch_size: Optional[int] = None,
                    batch_format: Optional[str] = None,
                    compute=None, concurrency=None,
                    num_chips: int = 0, fn_args=(), fn_kwargs=None,
                    fn_constructor_args=()) -> "Dataset":
        """Batch transform — the workhorse (reference: Dataset.map_batches).

        Passing a class (callable UDF) implies an actor pool; ``concurrency``
        sets its size (reference's concurrency arg)."""
        if isinstance(concurrency, int) and concurrency < 1:
            raise ValueError(
                f"concurrency must be >= 1, got {concurrency}")
        if compute is None and (isinstance(fn, type) or num_chips):
            # Callable-class UDFs and chip-using UDFs both need stateful
            # workers: chips bind to dedicated actor processes (see
            # runtime._prepare_request — num_tpus is actor-scoped).
            size = concurrency if isinstance(concurrency, int) else None
            lo, hi = (concurrency if isinstance(concurrency, tuple)
                      else (size, size))
            compute = ActorPoolStrategy(min_size=lo, max_size=hi)
        elif isinstance(concurrency, int) and compute is None:
            compute = TaskPoolStrategy(concurrency)
        if num_chips and not isinstance(compute, ActorPoolStrategy):
            raise ValueError(
                "num_chips requires an actor pool: pass compute="
                "ActorPoolStrategy(...) or omit compute")
        return self._map("MapBatches", "map_batches", fn,
                         batch_size=batch_size, batch_format=batch_format,
                         compute=compute, num_chips=num_chips,
                         fn_args=fn_args, fn_kwargs=fn_kwargs,
                         fn_constructor_args=fn_constructor_args)

    def flat_map(self, fn: Callable, **kw) -> "Dataset":
        return self._map("FlatMap", "flat_map", fn, **kw)

    def filter(self, fn: Optional[Callable] = None, *, expr=None,
               **kw) -> "Dataset":
        """Keep rows where ``fn(row)`` (or the vectorized ``expr``) is
        true. Expressions evaluate batch-at-once AND advertise their
        columns to the optimizer (reference: Dataset.filter(expr=...))."""
        from ray_tpu.data.expr import Expr

        if isinstance(fn, Expr) and expr is None:
            fn, expr = None, fn
        if fn is None and expr is None:
            raise ValueError("filter() needs a row fn or an expr")
        if expr is not None:
            if fn is not None:
                raise ValueError("pass fn OR expr, not both")

            def mask(batch, _e=expr):
                m = np.asarray(_e.eval(batch), bool)
                return {k: np.asarray(v)[m] for k, v in batch.items()}

            ds = self._map(f"Filter[{expr!r}]", "map_batches", mask,
                           batch_format="numpy", **kw)
            ds._logical_op.expr_columns = tuple(sorted(expr.columns()))
            ds._logical_op.filter_expr = expr
            return ds
        return self._map("Filter", "filter", fn, **kw)

    def with_column(self, name: str, expr) -> "Dataset":
        """Add/replace a column from an expression (reference:
        Dataset.with_column)."""
        return self.with_columns({name: expr})

    def with_columns(self, exprs: Dict[str, Any]) -> "Dataset":
        from ray_tpu.data.expr import Expr

        for k, e in exprs.items():
            if not isinstance(e, Expr):
                raise TypeError(f"{k}: expected an Expr, got {type(e)}")

        def add(batch, _es=tuple(exprs.items())):
            out = dict(batch)
            n = len(next(iter(batch.values()))) if batch else 0
            for k, e in _es:
                v = np.asarray(e.eval(batch))
                if v.ndim == 0:  # scalar literal: broadcast to the batch
                    v = np.full(n, v[()])
                out[k] = v
            return out

        ds = self._map(f"WithColumns{list(exprs)}", "map_batches", add,
                       batch_format="numpy")
        used = frozenset().union(*(e.columns() for e in exprs.values()))
        ds._logical_op.expr_columns = tuple(sorted(used))
        ds._logical_op.produces = tuple(exprs)
        return ds

    def add_column(self, col: str, fn: Callable) -> "Dataset":
        def add(batch: Dict[str, np.ndarray], _fn=fn, _col=col):
            batch = dict(batch)
            batch[_col] = np.asarray(_fn(batch))
            return batch
        return self._map(f"AddColumn[{col}]", "map_batches", add,
                         batch_format="numpy")

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def drop(batch: Dict[str, np.ndarray], _cols=tuple(cols)):
            return {k: v for k, v in batch.items() if k not in _cols}
        return self._map("DropColumns", "map_batches", drop,
                         batch_format="numpy")

    def select_columns(self, cols: List[str]) -> "Dataset":
        def select(batch: Dict[str, np.ndarray], _cols=tuple(cols)):
            return {k: batch[k] for k in _cols}
        ds = self._map("SelectColumns", "map_batches", select,
                       batch_format="numpy")
        # advertised projection: the optimizer pushes it into
        # column-prunable reads (optimizer.py: ProjectionPushdown)
        ds._logical_op.projection = tuple(cols)
        return ds

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        # Arrow-level rename: zero-copy, and keeps tensor_shape:<name>
        # schema metadata aligned with the new column names.
        def rename(table, _m=dict(mapping)):
            from ray_tpu.data.block import BlockAccessor
            return BlockAccessor(table).rename_columns(_m)
        return self._map("RenameColumns", "map_batches", rename,
                         batch_format="pyarrow")

    def limit(self, n: int) -> "Dataset":
        return Dataset(L.Limit(self._logical_op, n), self._context)

    def repartition(self, num_blocks: int) -> "Dataset":
        node = L.AbstractAllToAll("Repartition", self._logical_op,
                                  "repartition", num_outputs=num_blocks)
        return Dataset(node, self._context)

    def random_shuffle(self, *, seed: Optional[int] = None,
                       num_blocks: Optional[int] = None) -> "Dataset":
        node = L.AbstractAllToAll("RandomShuffle", self._logical_op,
                                  "random_shuffle", seed=seed,
                                  num_outputs=num_blocks)
        return Dataset(node, self._context)

    def sort(self, key: Union[str, List[str]],
             descending: bool = False) -> "Dataset":
        node = L.AbstractAllToAll("Sort", self._logical_op, "sort",
                                  key=key, descending=descending)
        return Dataset(node, self._context)

    def groupby(self, key: Union[str, List[str]]):
        from ray_tpu.data.grouped import GroupedData
        return GroupedData(self, key)

    def union(self, *others: "Dataset") -> "Dataset":
        node = L.Union([self._logical_op] +
                       [o._logical_op for o in others])
        return Dataset(node, self._context)

    def zip(self, other: "Dataset") -> "Dataset":
        node = L.Zip(self._logical_op, other._logical_op)
        return Dataset(node, self._context)

    def random_sample(self, fraction: float,
                      *, seed: Optional[int] = None) -> "Dataset":
        def sample(batch: Dict[str, np.ndarray], _f=fraction, _s=seed):
            n = len(next(iter(batch.values()))) if batch else 0
            if _s is None:
                rng = np.random.default_rng()
            else:
                # Salt the seed per batch, else every batch would reuse
                # the identical keep-mask positions (periodic sample).
                import zlib
                first = next(iter(batch.values()))
                salt = zlib.crc32(np.ascontiguousarray(first).tobytes())
                rng = np.random.default_rng((_s, salt))
            keep = rng.random(n) < _f
            return {k: v[keep] for k, v in batch.items()}
        return self._map("RandomSample", "map_batches", sample,
                         batch_format="numpy")

    # ---- execution ----

    def _execute_bundles(self) -> Iterator[RefBundle]:
        planner = Planner(self._context)
        topo = planner.plan(self._logical_op)
        executor = StreamingExecutor(topo, self._context)
        gen = executor.execute()
        try:
            yield from gen
        finally:
            self._last_stats = executor.stats.summary()

    def _block_lists(self) -> Iterator[List[Block]]:
        for bundle in self._execute_bundles():
            yield ray_tpu.get(bundle.blocks_ref)

    def iterator(self) -> DataIterator:
        return DataIterator(self._block_lists, lambda: self.stats())

    def materialize(self) -> "MaterializedDataset":
        """Execute now; hold blocks in the object store (reference:
        Dataset.materialize)."""
        bundles = list(self._execute_bundles())
        return MaterializedDataset(
            L.InputData(bundles), self._context, bundles)

    # ---- consumption ----

    def iter_rows(self):
        return self.iterator().iter_rows()

    def iter_batches(self, **kw):
        return self.iterator().iter_batches(**kw)

    def iter_jax_batches(self, **kw):
        return self.iterator().iter_jax_batches(**kw)

    def iter_torch_batches(self, **kw):
        return self.iterator().iter_torch_batches(**kw)

    def take(self, n: int = 20) -> List[Any]:
        out = []
        for row in self.limit(n).iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def take_batch(self, batch_size: int = 20,
                   batch_format: Optional[str] = None):
        fmt = batch_format or self._context.batch_format
        for b in self.limit(batch_size).iter_batches(
                batch_size=batch_size, batch_format=fmt,
                prefetch_batches=0):
            return b
        return {}

    def show(self, n: int = 20):
        for row in self.take(n):
            print(row)

    def count(self) -> int:
        node = self._logical_op
        # Fast path: pure reads know their row counts from metadata.
        if isinstance(node, L.Read):
            tasks = node.datasource.get_read_tasks(node.parallelism)
            rows = [t.metadata.num_rows for t in tasks]
            if all(r > 0 for r in rows):
                return sum(rows)
        return sum(bundle.num_rows for bundle in self._execute_bundles())

    # ---- global aggregates (reference: dataset.py sum/min/max/mean/std):
    # a streaming fold over batches on the driver — bounded memory, one
    # pass, no shuffle needed for whole-dataset scalars.

    def aggregate(self, *aggs) -> dict:
        states: dict = {a.name: None for a in aggs}
        for batch in self.iter_batches(batch_format="numpy"):
            for a in aggs:
                col = np.asarray(batch[a.on])
                s = states[a.name]
                if a.arrow_name == "sum":
                    states[a.name] = (0 if s is None else s) + col.sum()
                elif a.arrow_name == "min":
                    m = col.min()
                    states[a.name] = m if s is None else min(s, m)
                elif a.arrow_name == "max":
                    m = col.max()
                    states[a.name] = m if s is None else max(s, m)
                elif a.arrow_name == "count":
                    states[a.name] = (0 if s is None else s) + len(col)
                elif a.arrow_name in ("mean", "stddev"):
                    # Chan et al. parallel Welford merge of (n, mean, M2):
                    # numerically stable for large-mean data (the naive
                    # sumsq formula cancels catastrophically there)
                    col = col.astype(np.float64)
                    nb, mb = len(col), col.mean()
                    m2b = ((col - mb) ** 2).sum()
                    if s is None:
                        states[a.name] = [nb, mb, m2b]
                    else:
                        na, ma, m2a = s
                        n = na + nb
                        d = mb - ma
                        states[a.name] = [
                            n, ma + d * nb / n,
                            m2a + m2b + d * d * na * nb / n]
                else:
                    raise ValueError(
                        f"unknown aggregate {a.arrow_name!r}")
        out = {}
        for a in aggs:
            s = states[a.name]
            if a.arrow_name == "mean":
                out[a.name] = None if s is None or s[0] == 0 else s[1]
            elif a.arrow_name == "stddev":
                if s is None or s[0] < 2:
                    out[a.name] = None
                else:
                    n, _, m2 = s
                    out[a.name] = float(np.sqrt(m2 / (n - 1)))
            else:
                out[a.name] = s
        return out

    def _scalar_agg(self, arrow_name: str, on: str):
        from ray_tpu.data.grouped import AggregateFn

        agg = AggregateFn(on, arrow_name)
        return self.aggregate(agg)[agg.name]

    def sum(self, on: str):
        return self._scalar_agg("sum", on)

    def min(self, on: str):
        return self._scalar_agg("min", on)

    def max(self, on: str):
        return self._scalar_agg("max", on)

    def mean(self, on: str):
        return self._scalar_agg("mean", on)

    def std(self, on: str):
        return self._scalar_agg("stddev", on)

    def schema(self):
        for bundle in self.limit(1)._execute_bundles():
            if bundle.metas and bundle.metas[0].schema is not None:
                return bundle.metas[0].schema
            blocks = ray_tpu.get(bundle.blocks_ref)
            if blocks:
                return blocks[0].schema
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s is not None else []

    def num_blocks(self) -> int:
        return sum(len(b.metas) or 1 for b in self._execute_bundles())

    def size_bytes(self) -> int:
        return sum(b.size_bytes for b in self._execute_bundles())

    def stats(self) -> str:
        return self._last_stats or ""

    def split(self, n: int, *, equal: bool = False
              ) -> List["MaterializedDataset"]:
        """Materialize and split into n datasets (reference: Dataset.split)."""
        mat = self.materialize()
        bundles = mat._bundles
        rows = sum(b.num_rows for b in bundles)
        per = math.ceil(rows / n)
        # Re-chunk bundle metadata row-wise via truncating tasks would be
        # heavy; split at bundle granularity, padding with empties.
        out: List[List[RefBundle]] = [[] for _ in builtins.range(n)]
        counts = [0] * n  # rows per split
        for b in bundles:
            idx = min(builtins.range(n), key=lambda i: counts[i]) \
                if equal else \
                min(builtins.range(n), key=lambda i: len(out[i]))
            out[idx].append(b)
            counts[idx] += b.num_rows
        return [MaterializedDataset(L.InputData(bs), self._context, bs)
                for bs in out]

    def streaming_split(self, n: int, *, equal: bool = True,
                        locality_hints=None):
        """n concurrent iterators over one streaming execution (reference:
        Dataset.streaming_split :1236 — the Train ingest path)."""
        from ray_tpu.data.stream_split import make_stream_split_iterators
        return make_stream_split_iterators(self, n, equal=equal)

    # ---- writes ----

    def _write(self, path: str, file_format: str, **write_kwargs):
        node = L.Write(self._logical_op, path, file_format, write_kwargs)
        ds = Dataset(node, self._context)
        paths = []
        for bundle in ds._execute_bundles():
            for blocks in [ray_tpu.get(bundle.blocks_ref)]:
                for b in blocks:
                    paths.extend(BlockAccessor(b).to_numpy()["path"].tolist())
        return paths

    def write_parquet(self, path: str, **kw):
        return self._write(path, "parquet", **kw)

    def write_bigquery(self, project_id: str, dataset: str) -> int:
        """Append to a BigQuery table via parallel load jobs; returns the
        row count written (reference: Dataset.write_bigquery)."""
        from ray_tpu.data.datasource import write_bigquery_block

        @ray_tpu.remote
        def _write_one(blocks, project_id=project_id, dataset=dataset):
            return sum(write_bigquery_block(b, project_id, dataset)
                       for b in blocks)

        refs = [_write_one.remote(bundle.blocks_ref)
                for bundle in self._execute_bundles()]
        return sum(ray_tpu.get(refs))

    def write_csv(self, path: str, **kw):
        return self._write(path, "csv", **kw)

    def write_json(self, path: str, **kw):
        return self._write(path, "json", **kw)

    def write_numpy(self, path: str, **kw):
        return self._write(path, "npy", **kw)

    def write_tfrecords(self, path: str, column: str = "bytes"):
        """Write one TFRecord file per block from a bytes column, with
        valid masked CRC-32C framing (reference: Dataset.write_tfrecords;
        interoperable with TensorFlow readers)."""
        import os as _os

        from ray_tpu.data.datasource import write_tfrecords_file

        _os.makedirs(path, exist_ok=True)

        @ray_tpu.remote
        def _write_one(blocks, idx, path=path, column=column):
            out = _os.path.join(path, f"part-{idx:05d}.tfrecords")
            recs = []
            for b in blocks:
                recs.extend(BlockAccessor(b).to_numpy()[column].tolist())
            return write_tfrecords_file(recs, out)

        refs = [_write_one.remote(bundle.blocks_ref, i)
                for i, bundle in enumerate(self._execute_bundles())]
        return sum(ray_tpu.get(refs))

    # ---- conversions ----

    def to_pandas(self, limit: Optional[int] = None):
        ds = self.limit(limit) if limit else self
        tables = [b for blocks in ds._block_lists() for b in blocks]
        merged = BlockAccessor.concat(tables)
        return merged.to_pandas()

    def to_arrow_refs(self):
        return [b.blocks_ref for b in self._execute_bundles()]

    def __repr__(self):
        return f"Dataset({self._logical_op!r})"


class MaterializedDataset(Dataset):
    def __init__(self, logical_op, context, bundles: List[RefBundle]):
        super().__init__(logical_op, context)
        self._bundles = bundles

    def count(self) -> int:
        return sum(b.num_rows for b in self._bundles)


# ---- read API (reference: python/ray/data/read_api.py) ---------------------

def _auto_parallelism(ds: Datasource, ctx: DataContext) -> int:
    est = ds.estimate_inmemory_data_size()
    if est:
        return max(1, min(64, est // max(1, ctx.target_min_block_size)))
    return 8


def read_datasource(datasource: Datasource, *,
                    parallelism: int = -1, **_) -> Dataset:
    ctx = DataContext.get_current().copy()
    if parallelism is None or parallelism < 0:
        parallelism = (ctx.read_parallelism if ctx.read_parallelism > 0
                       else _auto_parallelism(datasource, ctx))
    return Dataset(L.Read(datasource, parallelism), ctx)


def range(n: int, *, parallelism: int = -1) -> Dataset:
    return read_datasource(RangeDatasource(n), parallelism=parallelism)


def range_tensor(n: int, *, shape=(1,), parallelism: int = -1) -> Dataset:
    return read_datasource(RangeDatasource(n, use_tensor=True,
                                           tensor_shape=tuple(shape)),
                           parallelism=parallelism)


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    return read_datasource(ItemsDatasource(items), parallelism=parallelism)


def from_numpy(arr: np.ndarray) -> Dataset:
    block = BlockAccessor.batch_to_block({"data": arr})
    return from_blocks([block])


def from_arrow(table) -> Dataset:
    return from_blocks([table])


def from_pandas(df) -> Dataset:
    import pyarrow as pa
    return from_blocks([pa.Table.from_pandas(df, preserve_index=False)])


def from_blocks(blocks: List[Block]) -> Dataset:
    bundles = []
    for b in blocks:
        meta = BlockAccessor(b).get_metadata()
        ref = ray_tpu.put([b])
        bundles.append(RefBundle(ref, meta.num_rows, meta.size_bytes,
                                 [meta]))
    ctx = DataContext.get_current().copy()
    return MaterializedDataset(L.InputData(bundles), ctx, bundles)


def read_parquet(paths, *, columns=None, parallelism: int = -1) -> Dataset:
    return read_datasource(ParquetDatasource(paths, columns),
                           parallelism=parallelism)


def read_csv(paths, *, parallelism: int = -1) -> Dataset:
    return read_datasource(CSVDatasource(paths), parallelism=parallelism)


def read_json(paths, *, parallelism: int = -1) -> Dataset:
    return read_datasource(JSONDatasource(paths), parallelism=parallelism)


def read_huggingface(path, *, split=None, parallelism: int = -1) -> Dataset:
    """Read a dataset saved by HF ``datasets``' ``save_to_disk`` (arrow
    shards; DatasetDict needs ``split=``) as a DISTRIBUTED read — the
    local-format sibling of ``from_huggingface`` (which converts an
    in-memory Dataset). No hub client or network involved."""
    from ray_tpu.data.datasource import HuggingFaceDatasource

    return read_datasource(HuggingFaceDatasource(path, split=split),
                           parallelism=parallelism)


def read_numpy(paths, *, parallelism: int = -1) -> Dataset:
    return read_datasource(NumpyDatasource(paths), parallelism=parallelism)


def read_text(paths, *, parallelism: int = -1) -> Dataset:
    return read_datasource(TextDatasource(paths), parallelism=parallelism)


def read_binary_files(paths, *, parallelism: int = -1) -> Dataset:
    return read_datasource(BinaryDatasource(paths), parallelism=parallelism)


def read_tfrecords(paths, *, parallelism: int = -1) -> Dataset:
    return read_datasource(TFRecordsDatasource(paths),
                           parallelism=parallelism)


def read_webdataset(paths, *, parallelism: int = -1) -> Dataset:
    from ray_tpu.data.datasource import WebDatasetDatasource

    return read_datasource(WebDatasetDatasource(paths),
                           parallelism=parallelism)


def read_sql(sql: str, connection_factory, *, parallelism: int = -1
             ) -> Dataset:
    from ray_tpu.data.datasource import SQLDatasource

    return read_datasource(SQLDatasource(sql, connection_factory),
                           parallelism=parallelism)


def read_images(paths, *, size=None, mode=None,
                parallelism: int = -1) -> Dataset:
    from ray_tpu.data.datasource import ImageDatasource

    return read_datasource(ImageDatasource(paths, size=size, mode=mode),
                           parallelism=parallelism)


def read_avro(paths, *, parallelism: int = -1) -> Dataset:
    from ray_tpu.data.datasource import AvroDatasource

    return read_datasource(AvroDatasource(paths), parallelism=parallelism)


def from_torch(torch_dataset, *, parallelism: int = -1) -> Dataset:
    from ray_tpu.data.datasource import TorchDatasource

    return read_datasource(TorchDatasource(torch_dataset),
                           parallelism=parallelism)


def from_huggingface(hf_dataset, *, parallelism: int = -1) -> Dataset:
    from ray_tpu.data.datasource import huggingface_to_blocks

    return from_blocks(huggingface_to_blocks(hf_dataset, parallelism))


def read_bigquery(project_id: str, dataset: str = None, query: str = None,
                  *, parallelism: int = -1) -> Dataset:
    from ray_tpu.data.datasource import BigQueryDatasource

    return read_datasource(
        BigQueryDatasource(project_id, dataset=dataset, query=query),
        parallelism=parallelism)


def read_iceberg(table_path: str, *, columns=None, snapshot_id=None,
                 parallelism: int = -1) -> Dataset:
    """Read a snapshot of an Apache Iceberg table — implemented in-tree
    over the open table format (JSON metadata + Avro manifest replay +
    parquet data files), no pyiceberg dependency (reference:
    _internal/datasource/iceberg_datasource.py). ``snapshot_id``
    time-travels to any retained snapshot."""
    from ray_tpu.data.datasource import IcebergDatasource

    return read_datasource(
        IcebergDatasource(table_path, columns=columns,
                          snapshot_id=snapshot_id),
        parallelism=parallelism)


def read_delta(table_path: str, *, columns=None,
               parallelism: int = -1) -> Dataset:
    """Read the current snapshot of a Delta Lake table — implemented
    in-tree over the open table format (JSON transaction log + parquet
    checkpoint replay), no deltalake dependency (reference:
    read_delta/delta sharing datasources)."""
    from ray_tpu.data.datasource import DeltaDatasource

    return read_datasource(DeltaDatasource(table_path, columns=columns),
                           parallelism=parallelism)
