"""GroupedData: groupby + aggregations.

Reference: python/ray/data/grouped_data.py — GroupedData.sum/min/max/mean/
count/std, .aggregate(AggregateFn), .map_groups.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.data import logical as L
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata


class AggregateFn:
    """A named aggregation over a column, executed via arrow group-bys."""

    def __init__(self, on: str, arrow_name: str,
                 name: Optional[str] = None):
        self.on = on
        self.arrow_name = arrow_name
        self.name = name or f"{arrow_name}({on})"


def Sum(on: str):
    return AggregateFn(on, "sum")


def Min(on: str):
    return AggregateFn(on, "min")


def Max(on: str):
    return AggregateFn(on, "max")


def Mean(on: str):
    return AggregateFn(on, "mean")


def Count(on: str):
    return AggregateFn(on, "count")


def Std(on: str):
    return AggregateFn(on, "stddev")


@ray_tpu.remote
def _map_groups_partition(key, fn, batch_format: str,
                          *part_lists: List[Block]):
    """Merge one hash partition, then apply fn to each key-group."""
    blocks = [b for parts in part_lists for b in parts]
    merged = BlockAccessor.concat(blocks)
    if merged.num_rows == 0:
        return [], []
    acc = BlockAccessor(merged)
    keys = [key] if isinstance(key, str) else list(key)
    sorted_block = acc.take_rows(acc.sort_indices(keys))
    sacc = BlockAccessor(sorted_block)
    cols = sacc.to_numpy()
    key_col = cols[keys[0]]
    # Boundaries where any key column changes value.
    change = np.zeros(len(key_col), dtype=bool)
    change[0] = True
    for k in keys:
        c = cols[k]
        change[1:] |= c[1:] != c[:-1]
    starts = np.nonzero(change)[0].tolist() + [len(key_col)]
    outs = []
    for s, e in zip(starts[:-1], starts[1:]):
        group = BlockAccessor(sacc.slice(s, e)).to_batch(batch_format)
        res = fn(group)
        outs.append(BlockAccessor.batch_to_block(res))
    out_blocks = [b for b in outs if b.num_rows]
    metas = [BlockAccessor(b).get_metadata() for b in out_blocks]
    return out_blocks, metas


class GroupedData:
    def __init__(self, dataset, key: Union[str, List[str]]):
        self._ds = dataset
        self._key = key

    def aggregate(self, *aggs: AggregateFn):
        from ray_tpu.data.dataset import Dataset
        node = L.Aggregate(self._ds._logical_op, self._key, list(aggs))
        return Dataset(node, self._ds._context)

    def _agg(self, arrow_name: str, on: Union[str, List[str]]):
        cols = [on] if isinstance(on, str) else list(on)
        return self.aggregate(*[AggregateFn(c, arrow_name) for c in cols])

    def sum(self, on):
        return self._agg("sum", on)

    def min(self, on):
        return self._agg("min", on)

    def max(self, on):
        return self._agg("max", on)

    def mean(self, on):
        return self._agg("mean", on)

    def count(self):
        key0 = self._key if isinstance(self._key, str) else self._key[0]
        ds = self.aggregate(AggregateFn(key0, "count", name="count()"))
        return ds

    def std(self, on):
        return self._agg("stddev", on)

    def map_groups(self, fn: Callable, *,
                   batch_format: Optional[str] = None):
        """Apply ``fn`` to each group as one batch (reference:
        grouped_data.py map_groups)."""
        from ray_tpu.data.dataset import Dataset
        fmt = batch_format or self._ds._context.batch_format
        node = _MapGroups(self._ds._logical_op, self._key, fn, fmt)
        return Dataset(node, self._ds._context)


class _MapGroups(L.LogicalOperator):
    def __init__(self, input_op, key, fn, batch_format):
        super().__init__("MapGroups", [input_op])
        self.key = key
        self.fn = fn
        self.batch_format = batch_format


from ray_tpu.data.physical import (  # noqa: E402  (after remote defs)
    AggregateOperator,
    _hash_partition,
    _select_partition,
)


class MapGroupsOperator(AggregateOperator):
    """Physical barrier op for map_groups: hash-partition by key so each
    group lands whole in one partition, then apply the UDF per group."""

    def __init__(self, key, fn, batch_format, num_partitions=None):
        super().__init__(key, [], num_partitions)
        self.name = "MapGroups"
        self.fn = fn
        self.batch_format = batch_format

    def launch_one(self):
        n = self.num_partitions or max(1, min(len(self._collected), 8))
        map_refs = [_hash_partition.remote(b.blocks_ref, self.key, n)
                    for b in self._collected]
        for i in range(n):
            part_i = [_select_partition.remote(mr, i) for mr in map_refs]
            blocks_ref, meta_ref = _map_groups_partition.options(
                num_returns=2).remote(self.key, self.fn,
                                      self.batch_format, *part_i)
            self._track(meta_ref, blocks_ref)
            self.tasks_launched += 1
        self._collected.clear()
        self._phase = "reduce"


def make_map_groups_operator(key, fn, batch_format, num_partitions=None):
    return MapGroupsOperator(key, fn, batch_format, num_partitions)
