"""Logical-plan optimizer: a rule framework over the Data logical DAG.

Reference: python/ray/data/_internal/logical/optimizers.py (LogicalOptimizer
running a Rule list) and rules/ (operator fusion, limit pushdown, ...).
Physical Read->Map / Map->Map fusion stays in the planner's lowering (it
needs physical-operator knowledge); the rules here rewrite the LOGICAL
graph before lowering. Custom rules register via ``register_rule`` (the
extension point the reference exposes through DataContext).
"""

from __future__ import annotations

from typing import Callable, List, Type

from ray_tpu.data import logical as L


class Rule:
    """A logical-plan rewrite. apply() returns the (possibly new) root."""

    def apply(self, root: L.LogicalOperator) -> L.LogicalOperator:
        raise NotImplementedError

    # -- helpers -------------------------------------------------------------

    def _rewrite(self, node: L.LogicalOperator,
                 fn: Callable[[L.LogicalOperator], L.LogicalOperator]
                 ) -> L.LogicalOperator:
        """Bottom-up rewrite over CLONES of the inputs DAG.

        Datasets share logical nodes by reference (every derived Dataset
        wraps its parent's op), so rules must never mutate the originals —
        an in-place rewrite would corrupt sibling pipelines and
        re-executions. Clones are memoized per original so diamond DAGs
        (zip(ds, ds)) keep their sharing."""
        import copy

        memo: dict = {}

        def walk(n: L.LogicalOperator) -> L.LogicalOperator:
            got = memo.get(id(n))
            if got is not None:
                return got
            clone = copy.copy(n)
            clone.inputs = [walk(c) for c in n.inputs]
            out = fn(clone)
            memo[id(n)] = out
            return out

        return walk(node)


class MergeLimits(Rule):
    """Limit(Limit(x, a), b) -> Limit(x, min(a, b))."""

    def apply(self, root):
        def fn(node):
            if (isinstance(node, L.Limit) and node.inputs
                    and isinstance(node.inputs[0], L.Limit)):
                inner = node.inputs[0]
                node.limit = min(node.limit, inner.limit)
                node.name = f"Limit[{node.limit}]"
                node.inputs = list(inner.inputs)
            return node
        return self._rewrite(root, fn)


class LimitPushdown(Rule):
    """Push Limit beneath row-preserving maps so upstream work stops at
    the limit (reference: rules/limit_pushdown.py). Only 'map_rows' maps
    are strictly 1:1; batch maps / flat_map / filter change counts."""

    _PUSHABLE = ("map_rows",)

    def apply(self, root):
        import copy

        def fn(node):
            if (isinstance(node, L.Limit) and node.inputs
                    and isinstance(node.inputs[0], L.AbstractMap)
                    and node.inputs[0].kind in self._PUSHABLE):
                m = node.inputs[0]
                # m may be a memoized clone SHARED with sibling branches
                # (diamond plans: base.union(base.limit(k))) — rewire a
                # fresh copy so the unlimited branches keep plain m
                m2 = copy.copy(m)
                node.inputs = list(m.inputs)
                m2.inputs = [node]
                return m2
            return node
        return self._rewrite(root, fn)


class ProjectionPushdown(Rule):
    """Prune file reads to the columns the plan actually consumes
    (reference: the planner pushing projections into ParquetDatasource).

    Pattern: SelectColumns (op tagged with ``projection``) above a chain
    of expression-built maps (tagged ``expr_columns`` / ``produces``)
    above a column-prunable Read. The read is rewired to a pruned clone
    of the datasource; columns PRODUCED by expressions along the way are
    excluded from the file read (they don't exist in the file)."""

    def apply(self, root):
        def fn(node):
            proj = getattr(node, "projection", None)
            if not (isinstance(node, L.AbstractMap) and proj):
                return node
            needed = set(proj)
            chain = []
            cur = node.inputs[0] if node.inputs else None
            while (isinstance(cur, L.AbstractMap)
                   and getattr(cur, "expr_columns", None) is not None):
                needed -= set(getattr(cur, "produces", ()))
                needed |= set(cur.expr_columns)
                chain.append(cur)
                cur = cur.inputs[0] if cur.inputs else None
            if not needed:
                # e.g. every selected column is expression-produced: a
                # zero-column read would yield empty batches
                return node
            if not (isinstance(cur, L.Read)
                    and getattr(cur.datasource,
                                "supports_column_pruning", False)
                    and cur.datasource._columns is None):
                return node
            import copy

            read2 = copy.copy(cur)
            read2.datasource = cur.datasource.with_columns(sorted(needed))
            read2.name = f"{cur.name}[{sorted(needed)}]"
            # chain members may be memoized clones SHARED with sibling
            # branches (diamond plans) — rewire fresh copies so the
            # other branches keep the unpruned read (same hazard
            # LimitPushdown documents)
            new_chain = [copy.copy(m) for m in chain]
            for a, b in zip(new_chain[:-1], new_chain[1:]):
                a.inputs = [b]
            if new_chain:
                node.inputs = [new_chain[0]]
                new_chain[-1].inputs = [read2]
            else:
                node.inputs = [read2]
            return node
        return self._rewrite(root, fn)


class PredicatePushdown(Rule):
    """Push expression filters into the file scan (reference: the
    planner's filter pushdown into ParquetDatasource). Pattern: a
    ``filter(expr=...)`` map DIRECTLY above a predicate-capable Read.
    The expression converts to a pyarrow dataset filter (expr.to_pyarrow
    — None for sub-expressions without a faithful equivalent, which
    stay as in-memory masks); the filter node is then dropped and the
    Read replaced with a filtered clone, so row groups prune on
    statistics and the filter columns need not be materialized at all.
    Runs BEFORE ProjectionPushdown: a pushed filter's columns drop out
    of the projection's needed set (pyarrow can filter on columns it
    does not project). Stacked filters collapse bottom-up, ANDing into
    the scan."""

    def apply(self, root):
        def fn(node):
            fexpr = getattr(node, "filter_expr", None)
            if not (isinstance(node, L.AbstractMap) and fexpr is not None):
                return node
            # a filter the user pinned to a compute strategy/resources
            # still runs as its own operator
            if node.compute is not None or getattr(node, "num_chips", 0):
                return node
            cur = node.inputs[0] if node.inputs else None
            if not (isinstance(cur, L.Read)
                    and getattr(cur.datasource,
                                "supports_predicate_pushdown", False)):
                return node
            from ray_tpu.data.expr import to_pyarrow

            pa_expr = to_pyarrow(fexpr)
            if pa_expr is None:
                return node
            import copy

            read2 = copy.copy(cur)  # input Read may be diamond-shared
            read2.datasource = cur.datasource.with_filter(pa_expr,
                                                          expr=fexpr)
            read2.name = f"{cur.name}[filter]"
            return read2
        return self._rewrite(root, fn)


_DEFAULT_RULES: List[Type[Rule]] = [MergeLimits, LimitPushdown,
                                    PredicatePushdown,
                                    ProjectionPushdown]
_EXTRA_RULES: List[Type[Rule]] = []


def register_rule(rule_cls: Type[Rule]) -> None:
    """Add a custom rule (applied after the built-ins)."""
    _EXTRA_RULES.append(rule_cls)


class LogicalOptimizer:
    def __init__(self, rules: List[Type[Rule]] = None):
        self._rules = list(rules) if rules is not None else (
            _DEFAULT_RULES + _EXTRA_RULES)

    def optimize(self, root: L.LogicalOperator) -> L.LogicalOperator:
        for rule_cls in self._rules:
            root = rule_cls().apply(root)
        return root
