"""Logical-plan optimizer: a rule framework over the Data logical DAG.

Reference: python/ray/data/_internal/logical/optimizers.py (LogicalOptimizer
running a Rule list) and rules/ (operator fusion, limit pushdown, ...).
Physical Read->Map / Map->Map fusion stays in the planner's lowering (it
needs physical-operator knowledge); the rules here rewrite the LOGICAL
graph before lowering. Custom rules register via ``register_rule`` (the
extension point the reference exposes through DataContext).
"""

from __future__ import annotations

from typing import Callable, List, Type

from ray_tpu.data import logical as L


class Rule:
    """A logical-plan rewrite. apply() returns the (possibly new) root."""

    def apply(self, root: L.LogicalOperator) -> L.LogicalOperator:
        raise NotImplementedError

    # -- helpers -------------------------------------------------------------

    def _rewrite(self, node: L.LogicalOperator,
                 fn: Callable[[L.LogicalOperator], L.LogicalOperator]
                 ) -> L.LogicalOperator:
        """Bottom-up rewrite over CLONES of the inputs DAG.

        Datasets share logical nodes by reference (every derived Dataset
        wraps its parent's op), so rules must never mutate the originals —
        an in-place rewrite would corrupt sibling pipelines and
        re-executions. Clones are memoized per original so diamond DAGs
        (zip(ds, ds)) keep their sharing."""
        import copy

        memo: dict = {}

        def walk(n: L.LogicalOperator) -> L.LogicalOperator:
            got = memo.get(id(n))
            if got is not None:
                return got
            clone = copy.copy(n)
            clone.inputs = [walk(c) for c in n.inputs]
            out = fn(clone)
            memo[id(n)] = out
            return out

        return walk(node)


class MergeLimits(Rule):
    """Limit(Limit(x, a), b) -> Limit(x, min(a, b))."""

    def apply(self, root):
        def fn(node):
            if (isinstance(node, L.Limit) and node.inputs
                    and isinstance(node.inputs[0], L.Limit)):
                inner = node.inputs[0]
                node.limit = min(node.limit, inner.limit)
                node.name = f"Limit[{node.limit}]"
                node.inputs = list(inner.inputs)
            return node
        return self._rewrite(root, fn)


class LimitPushdown(Rule):
    """Push Limit beneath row-preserving maps so upstream work stops at
    the limit (reference: rules/limit_pushdown.py). Only 'map_rows' maps
    are strictly 1:1; batch maps / flat_map / filter change counts."""

    _PUSHABLE = ("map_rows",)

    def apply(self, root):
        import copy

        def fn(node):
            if (isinstance(node, L.Limit) and node.inputs
                    and isinstance(node.inputs[0], L.AbstractMap)
                    and node.inputs[0].kind in self._PUSHABLE):
                m = node.inputs[0]
                # m may be a memoized clone SHARED with sibling branches
                # (diamond plans: base.union(base.limit(k))) — rewire a
                # fresh copy so the unlimited branches keep plain m
                m2 = copy.copy(m)
                node.inputs = list(m.inputs)
                m2.inputs = [node]
                return m2
            return node
        return self._rewrite(root, fn)


_DEFAULT_RULES: List[Type[Rule]] = [MergeLimits, LimitPushdown]
_EXTRA_RULES: List[Type[Rule]] = []


def register_rule(rule_cls: Type[Rule]) -> None:
    """Add a custom rule (applied after the built-ins)."""
    _EXTRA_RULES.append(rule_cls)


class LogicalOptimizer:
    def __init__(self, rules: List[Type[Rule]] = None):
        self._rules = list(rules) if rules is not None else (
            _DEFAULT_RULES + _EXTRA_RULES)

    def optimize(self, root: L.LogicalOperator) -> L.LogicalOperator:
        for rule_cls in self._rules:
            root = rule_cls().apply(root)
        return root
