"""Physical operators for the streaming executor.

Reference: python/ray/data/_internal/execution/operators/ — MapOperator
(TaskPoolMapOperator / ActorPoolMapOperator), InputDataBuffer, LimitOperator,
all-to-all exchange ops (python/ray/data/_internal/planner/exchange/).

Data flows between operators as **RefBundles**: an ObjectRef to a
``List[Block]`` plus fetched-small metadata. Block payloads stay in the
shared-memory object store; the driver-side executor only ever touches
metadata.
"""

from __future__ import annotations

import collections
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.transforms import MapTransformChain


@dataclass
class RefBundle:
    blocks_ref: ObjectRef          # -> List[Block]
    num_rows: int
    size_bytes: int
    metas: List[BlockMetadata] = field(default_factory=list)

    def destroy(self):
        pass  # refcounting is handled by the object store GC


# ---- remote task bodies ----------------------------------------------------

def _measured_metas(out: List[Block], wall_s: float,
                    cpu_s: float) -> List[BlockMetadata]:
    """Metadata with in-task execution stats on the FIRST meta (one dict
    per task — the executor sums per operator; reference:
    BlockExecStats in data/_internal/stats.py)."""
    metas = [BlockAccessor(b).get_metadata() for b in out]
    if metas:
        metas[0].exec_stats = {
            "wall_s": wall_s, "cpu_s": cpu_s,
            "peak_block_bytes": max(m.size_bytes for m in metas),
        }
    return metas


@ray_tpu.remote
def _run_map_task(chain: MapTransformChain, blocks: List[Block]
                  ) -> Tuple[List[Block], List[BlockMetadata]]:
    t0, c0 = time.perf_counter(), time.process_time()
    out = list(chain(blocks))
    return out, _measured_metas(out, time.perf_counter() - t0,
                                time.process_time() - c0)


@ray_tpu.remote
def _run_read_task(read_task, chain: Optional[MapTransformChain]
                   ) -> Tuple[List[Block], List[BlockMetadata]]:
    t0, c0 = time.perf_counter(), time.process_time()
    blocks = read_task()
    if chain is not None:
        blocks = chain(blocks)
    out = list(blocks)
    return out, _measured_metas(out, time.perf_counter() - t0,
                                time.process_time() - c0)


def _stream_blocks(block_iter):
    """Yield (meta, blocks) pairs for a num_returns="streaming" task body:
    even yields carry the block's metadata (small — the driver fetches it
    to build the RefBundle), odd yields carry the block itself. Per-block
    exec stats are incremental so the executor's sums stay correct."""
    t_prev, c_prev = time.perf_counter(), time.process_time()
    for b in block_iter:
        meta = BlockAccessor(b).get_metadata()
        t, c = time.perf_counter(), time.process_time()
        meta.exec_stats = {
            "wall_s": t - t_prev, "cpu_s": c - c_prev,
            "peak_block_bytes": meta.size_bytes,
        }
        t_prev, c_prev = t, c
        yield [meta]
        yield [b]


@ray_tpu.remote
def _run_map_task_stream(chain: MapTransformChain, blocks: List[Block]):
    yield from _stream_blocks(chain(blocks))


@ray_tpu.remote
def _run_read_task_stream(read_task, chain: Optional[MapTransformChain]):
    blocks = read_task()
    if chain is not None:
        blocks = chain(blocks)
    yield from _stream_blocks(blocks)


@ray_tpu.remote
def _truncate_blocks(blocks: List[Block], rows: int
                     ) -> Tuple[List[Block], List[BlockMetadata]]:
    out: List[Block] = []
    remaining = rows
    for b in blocks:
        if remaining <= 0:
            break
        if b.num_rows <= remaining:
            out.append(b)
            remaining -= b.num_rows
        else:
            out.append(BlockAccessor(b).slice(0, remaining))
            remaining = 0
    metas = [BlockAccessor(b).get_metadata() for b in out]
    return out, metas


@ray_tpu.remote
def _partition_blocks(blocks: List[Block], n: int, kind: str,
                      key, descending: bool, seed: Optional[int],
                      boundaries: Optional[List[Any]]) -> List[List[Block]]:
    """Map side of an exchange: split each input block into n partitions."""
    parts: List[List[Block]] = [[] for _ in range(n)]
    for b in blocks:
        acc = BlockAccessor(b)
        if b.num_rows == 0:
            continue
        if kind == "repartition":
            rows_per = -(-b.num_rows // n)
            for i in range(n):
                s = acc.slice(i * rows_per, min((i + 1) * rows_per,
                                                b.num_rows))
                if s.num_rows:
                    parts[i].append(s)
        elif kind == "random_shuffle":
            idx = acc.random_shuffle_indices(seed)
            for i, chunk in enumerate(np.array_split(idx, n)):
                if len(chunk):
                    parts[i].append(acc.take_rows(chunk))
        elif kind == "sort":
            sort_idx = acc.sort_indices(key, descending)
            sorted_block = acc.take_rows(sort_idx)
            sacc = BlockAccessor(sorted_block)
            k0 = key if isinstance(key, str) else key[0]
            col = sacc.to_numpy()[k0]
            if descending:
                cuts = len(col) - np.searchsorted(col[::-1], boundaries,
                                                  side="left")
            else:
                cuts = np.searchsorted(col, boundaries, side="left")
            prev = 0
            for i, cut in enumerate(list(cuts) + [len(col)]):
                s = sacc.slice(prev, cut)
                if s.num_rows:
                    parts[i].append(s)
                prev = cut
        else:
            raise ValueError(kind)
    return [p for p in parts]


@ray_tpu.remote
def _merge_partition(kind: str, key, descending: bool, seed: Optional[int],
                     *part_lists: List[Block]
                     ) -> Tuple[List[Block], List[BlockMetadata]]:
    """Reduce side of an exchange: merge partition i from every map task."""
    blocks = [b for parts in part_lists for b in parts]
    merged = BlockAccessor.concat(blocks)
    acc = BlockAccessor(merged)
    if kind == "sort" and merged.num_rows:
        merged = acc.take_rows(acc.sort_indices(key, descending))
    elif kind == "random_shuffle" and merged.num_rows:
        rng_idx = BlockAccessor(merged).random_shuffle_indices(seed)
        merged = BlockAccessor(merged).take_rows(rng_idx)
    out = [merged] if merged.num_rows else []
    metas = [BlockAccessor(b).get_metadata() for b in out]
    return out, metas


@ray_tpu.remote
def _sample_boundaries(blocks: List[Block], key, n: int) -> List[Any]:
    k0 = key if isinstance(key, str) else key[0]
    vals = []
    for b in blocks:
        col = BlockAccessor(b).to_numpy().get(k0)
        if col is not None and len(col):
            step = max(1, len(col) // 20)
            vals.extend(col[::step].tolist())
    return vals


@ray_tpu.remote
def _zip_block_lists(left: List[Block], right: List[Block]
                     ) -> Tuple[List[Block], List[BlockMetadata]]:
    lt = BlockAccessor.concat(left)
    rt = BlockAccessor.concat(right)
    if lt.num_rows != rt.num_rows:
        raise ValueError(
            f"zip: datasets have different row counts "
            f"({lt.num_rows} vs {rt.num_rows})")
    out = lt
    rmeta = rt.schema.metadata or {}
    extra_meta = {}
    for name in rt.column_names:
        col_name = name if name not in lt.column_names else f"{name}_1"
        out = out.append_column(col_name, rt.column(name))
        shape_key = f"tensor_shape:{name}".encode()
        if shape_key in rmeta:
            # Carry the right table's tensor inner-shape metadata across,
            # under the (possibly de-duplicated) output column name.
            extra_meta[f"tensor_shape:{col_name}".encode()] = rmeta[shape_key]
    if extra_meta:
        out = out.replace_schema_metadata(
            {**(out.schema.metadata or {}), **extra_meta})
    return [out], [BlockAccessor(out).get_metadata()]


@ray_tpu.remote
def _write_blocks(blocks: List[Block], path: str, file_format: str,
                  index: int, write_kwargs: dict
                  ) -> Tuple[List[Block], List[BlockMetadata]]:
    from ray_tpu.data.datasource import write_block
    import pyarrow as pa
    written = []
    for j, b in enumerate(blocks):
        if b.num_rows:
            written.append(write_block(b, path, file_format,
                                       index * 10000 + j, **write_kwargs))
    out = pa.table({"path": pa.array(written)})
    return [out], [BlockAccessor(out).get_metadata()]


# ---- actor pool worker -----------------------------------------------------

@ray_tpu.remote
class _MapWorker:
    """Stateful map worker for ActorPoolStrategy: instantiates callable-class
    UDFs once, then applies the chain per bundle (reference:
    ActorPoolMapOperator._MapWorker)."""

    def __init__(self, udf_cls=None, fn_constructor_args: tuple = ()):
        self._udf = udf_cls(*fn_constructor_args) if udf_cls else None

    def ready(self):
        return True

    def run(self, chain: MapTransformChain, blocks: List[Block]):
        if self._udf is not None:
            # Bind the instantiated UDF into steps whose fn is the marker.
            from ray_tpu.data.transforms import MapStep
            bound = []
            for s in chain.steps:
                if isinstance(s.fn, _CallableClassMarker):
                    bound.append(MapStep("map_batches", self._udf, s.fn_args,
                                         s.fn_kwargs, s.batch_size,
                                         s.batch_format))
                else:
                    bound.append(s)
            chain = MapTransformChain(bound, chain.target_max_block_size)
        t0, c0 = time.perf_counter(), time.process_time()
        out = list(chain(blocks))
        return out, _measured_metas(out, time.perf_counter() - t0,
                                    time.process_time() - c0)


class _CallableClassMarker:
    """Placeholder fn inside a chain; replaced by the actor-held instance."""

    def __call__(self, *a, **k):  # pragma: no cover
        raise RuntimeError("callable-class UDF must run on an actor pool")


_CALLABLE_CLASS_MARKER = _CallableClassMarker()


# ---- physical operators ----------------------------------------------------

class PhysicalOperator:
    """Base: push RefBundles in, pull RefBundles out, track in-flight tasks."""

    def __init__(self, name: str):
        self.name = name
        self.input_queue: collections.deque = collections.deque()
        self.output_queue: collections.deque = collections.deque()
        # meta_ref (waitable) -> (blocks_ref, context)
        self.pending: Dict[ObjectRef, Any] = {}
        self.inputs_complete = False
        self.rows_out = 0
        self.tasks_launched = 0
        # per-op accounting for Dataset.stats() (reference:
        # data/_internal/stats.py); the executor snapshots these
        self.rows_in = 0
        self.bytes_in = 0
        self.bytes_out = 0
        self.task_wall_s = 0.0
        self.task_cpu_s = 0.0
        self.sched_wall_s = 0.0
        self.peak_block_bytes = 0
        self._launch_ts: Dict[ObjectRef, float] = {}
        # In-flight num_returns="streaming" tasks: seed -> poll state
        # (the executor drains ready yields each tick via poll_streams).
        self._streams: Dict[bytes, dict] = {}
        # Ordered emission: outputs enter output_queue in LAUNCH order even
        # though tasks complete out of order (reference: preserve_order in
        # streaming_executor_state; required for sort/zip/limit determinism).
        # A seq buffers a LIST of bundles (a streaming task emits many);
        # the head seq's bundles flow out as produced, and the head only
        # advances once that seq is closed.
        self._seq = 0
        self._emit_next = 0
        self._pending_seq: Dict[ObjectRef, int] = {}
        self._outbuf: Dict[int, List[RefBundle]] = {}
        self._open_seqs: set = set()  # streaming seqs still producing

    def _track(self, meta_ref: ObjectRef, blocks_ref: ObjectRef):
        """Register an in-flight task in launch order."""
        self.pending[meta_ref] = blocks_ref
        self._pending_seq[meta_ref] = self._seq
        self._launch_ts[meta_ref] = time.perf_counter()
        self._seq += 1

    def _track_stream(self, gen):
        """Register an in-flight streaming task (ObjectRefGenerator)."""
        seq = self._seq
        self._seq += 1
        self._streams[gen.seed] = {"gen": gen, "seq": seq, "meta": None,
                                   "launched": time.perf_counter()}
        self._open_seqs.add(seq)
        self._outbuf.setdefault(seq, [])

    def _emit(self, seq: int, bundle: RefBundle):
        self._outbuf.setdefault(seq, []).append(bundle)
        self._flush_emits()

    def _flush_emits(self):
        while True:
            buf = self._outbuf.get(self._emit_next)
            if buf:
                self.output_queue.extend(buf)
                buf.clear()
            if self._emit_next in self._open_seqs or buf is None:
                return
            del self._outbuf[self._emit_next]
            self._emit_next += 1

    def _emit_direct(self, bundle: RefBundle):
        """Pass a bundle through without a task, keeping order."""
        seq = self._seq
        self._seq += 1
        self._emit(seq, bundle)

    def has_streams(self) -> bool:
        return bool(self._streams)

    def poll_streams(self) -> Tuple[bool, int]:
        """Drain every ready yield from in-flight streaming tasks without
        blocking; returns (progressed, tasks_completed). Even yields are
        block metadata, odd yields the block list (see _stream_blocks)."""
        from ray_tpu.exceptions import ObjectTimeoutError

        progressed, completed = False, 0
        for key, stt in list(self._streams.items()):
            while True:
                try:
                    ref = stt["gen"].next_ref(timeout=0)
                except ObjectTimeoutError:
                    break
                except StopIteration:
                    del self._streams[key]
                    self._open_seqs.discard(stt["seq"])
                    self._flush_emits()
                    self.sched_wall_s += (time.perf_counter()
                                          - stt["launched"])
                    progressed = True
                    completed += 1
                    break
                if stt["meta"] is None:
                    # already sealed (the ref was delivered): instant get.
                    # A task error raises here, like on_task_done's get.
                    stt["meta"] = ray_tpu.get(ref)
                    continue
                metas: List[BlockMetadata] = stt["meta"]
                stt["meta"] = None
                num_rows = sum(m.num_rows for m in metas)
                size = sum(m.size_bytes for m in metas)
                self.rows_out += num_rows
                self.bytes_out += size
                for m in metas:
                    es = m.exec_stats
                    if es:
                        self.task_wall_s += es.get("wall_s", 0.0)
                        self.task_cpu_s += es.get("cpu_s", 0.0)
                        self.peak_block_bytes = max(
                            self.peak_block_bytes,
                            es.get("peak_block_bytes", 0))
                self._emit(stt["seq"], RefBundle(ref, num_rows, size, metas))
                progressed = True
        return progressed, completed

    def add_input(self, bundle: RefBundle):
        self.rows_in += bundle.num_rows
        self.bytes_in += bundle.size_bytes
        self.input_queue.append(bundle)

    def mark_inputs_done(self):
        self.inputs_complete = True

    def waitable_refs(self) -> List[ObjectRef]:
        return list(self.pending.keys())

    def can_launch(self, max_in_flight: int) -> bool:
        return (len(self.input_queue) > 0 and
                len(self.pending) + len(self._streams) < max_in_flight)

    def launch_one(self):
        raise NotImplementedError

    def on_task_done(self, meta_ref: ObjectRef):
        """A waited ref completed: fetch metadata, enqueue output bundle."""
        blocks_ref = self.pending.pop(meta_ref)
        seq = self._pending_seq.pop(meta_ref)
        launched = self._launch_ts.pop(meta_ref, None)
        if launched is not None:
            self.sched_wall_s += time.perf_counter() - launched
        metas: List[BlockMetadata] = ray_tpu.get(meta_ref)
        num_rows = sum(m.num_rows for m in metas)
        size = sum(m.size_bytes for m in metas)
        self.rows_out += num_rows
        self.bytes_out += size
        for m in metas:
            es = m.exec_stats
            if es:
                self.task_wall_s += es.get("wall_s", 0.0)
                self.task_cpu_s += es.get("cpu_s", 0.0)
                self.peak_block_bytes = max(
                    self.peak_block_bytes,
                    es.get("peak_block_bytes", 0))
        self._emit(seq, RefBundle(blocks_ref, num_rows, size, metas))

    @property
    def done(self) -> bool:
        return (self.inputs_complete and not self.input_queue and
                not self.pending and not self._streams)

    def all_inputs_ready(self) -> bool:
        return (self.inputs_complete and not self.pending
                and not self._streams)

    def __repr__(self):
        return (f"{self.name}(in={len(self.input_queue)} "
                f"pending={len(self.pending)} "
                f"streams={len(self._streams)} "
                f"out={len(self.output_queue)})")


class InputDataBuffer(PhysicalOperator):
    """Source op over pre-planned read tasks or materialized bundles
    (reference: operators/input_data_buffer.py)."""

    def __init__(self, read_tasks: Optional[List] = None,
                 bundles: Optional[List[RefBundle]] = None,
                 chain: Optional[MapTransformChain] = None,
                 resources: Optional[dict] = None):
        super().__init__("Input")
        self._read_tasks = list(read_tasks or [])
        self._chain = chain
        self._resources = resources or {}
        from ray_tpu.data.context import DataContext
        self._streaming = DataContext.get_current().streaming_map_returns
        if bundles:
            for b in bundles:
                self.rows_out += b.num_rows
                self.bytes_out += b.size_bytes
            self.output_queue.extend(bundles)
        self.inputs_complete = True

    def can_launch(self, max_in_flight: int) -> bool:
        return (bool(self._read_tasks) and
                len(self.pending) + len(self._streams) < max_in_flight)

    def launch_one(self):
        rt = self._read_tasks.pop(0)
        if self._streaming:
            opts = dict(num_returns="streaming", **self._resources)
            gen = _run_read_task_stream.options(**opts).remote(
                rt, self._chain)
            self._track_stream(gen)
        else:
            opts = dict(num_returns=2, **self._resources)
            blocks_ref, meta_ref = _run_read_task.options(**opts).remote(
                rt, self._chain)
            self._track(meta_ref, blocks_ref)
        self.tasks_launched += 1

    @property
    def done(self) -> bool:
        return (not self._read_tasks and not self.pending
                and not self._streams)


class TaskPoolMapOperator(PhysicalOperator):
    """Stateless map over a pool of tasks (reference:
    operators/task_pool_map_operator.py)."""

    def __init__(self, name: str, chain: MapTransformChain,
                 resources: Optional[dict] = None,
                 max_concurrency: Optional[int] = None):
        super().__init__(name)
        self.chain = chain
        self._resources = resources or {}
        from ray_tpu.data.context import DataContext
        self._streaming = DataContext.get_current().streaming_map_returns
        # User-requested concurrency cap (map_batches(concurrency=N) →
        # TaskPoolStrategy(N)); min()-ed with the executor-wide cap.
        self._max_concurrency = max_concurrency

    def can_launch(self, max_in_flight: int) -> bool:
        if self._max_concurrency is not None:
            max_in_flight = min(max_in_flight, self._max_concurrency)
        return super().can_launch(max_in_flight)

    def launch_one(self):
        bundle: RefBundle = self.input_queue.popleft()
        if self._streaming:
            opts = dict(num_returns="streaming", **self._resources)
            gen = _run_map_task_stream.options(**opts).remote(
                self.chain, bundle.blocks_ref)
            self._track_stream(gen)
        else:
            opts = dict(num_returns=2, **self._resources)
            blocks_ref, meta_ref = _run_map_task.options(**opts).remote(
                self.chain, bundle.blocks_ref)
            self._track(meta_ref, blocks_ref)
        self.tasks_launched += 1


class ActorPoolMapOperator(PhysicalOperator):
    """Stateful map over a pool of actors (reference:
    operators/actor_pool_map_operator.py)."""

    def __init__(self, name: str, chain: MapTransformChain, strategy,
                 udf_cls=None, fn_constructor_args: tuple = (),
                 resources: Optional[dict] = None):
        super().__init__(name)
        self.chain = chain
        self._strategy = strategy
        self._actors: List[Any] = []
        self._actor_load: Dict[int, int] = {}
        self._meta_to_actor: Dict[ObjectRef, int] = {}
        self._udf_cls = udf_cls
        self._ctor_args = fn_constructor_args
        self._idle_since: Dict[int, float] = {}
        self._resources = resources or {}
        self._started = False

    def _ensure_pool(self):
        if self._started:
            return
        for _ in range(self._strategy.min_size):
            a = _MapWorker.options(**self._resources).remote(
                self._udf_cls, self._ctor_args)
            self._actors.append(a)
            self._actor_load[len(self._actors) - 1] = 0
        self._started = True

    def can_launch(self, max_in_flight: int) -> bool:
        if not self.input_queue:
            return False
        self._ensure_pool()
        cap = self._strategy.max_tasks_in_flight_per_actor
        return any(load < cap for load in self._actor_load.values())

    def _alive_count(self) -> int:
        return len(self._actor_load)

    def launch_one(self):
        self._ensure_pool()
        idx = min(self._actor_load, key=self._actor_load.get)
        # Scale up if every actor is saturated and we're under max_size.
        if (self._actor_load[idx] > 0 and
                self._alive_count() < self._strategy.max_size):
            a = _MapWorker.options(**self._resources).remote(
                self._udf_cls, self._ctor_args)
            self._actors.append(a)
            idx = len(self._actors) - 1
            self._actor_load[idx] = 0
        bundle: RefBundle = self.input_queue.popleft()
        blocks_ref, meta_ref = self._actors[idx].run.options(
            num_returns=2).remote(self.chain, bundle.blocks_ref)
        self._track(meta_ref, blocks_ref)
        self._meta_to_actor[meta_ref] = idx
        self._actor_load[idx] += 1
        self._idle_since.pop(idx, None)
        self.tasks_launched += 1

    # Seconds an actor must stay idle before scale-down reaps it: a
    # momentary drain of the input queue in a streaming pipeline must not
    # churn workers whose UDF constructors are expensive (model loads).
    IDLE_REAP_S = 2.0

    def on_task_done(self, meta_ref: ObjectRef):
        import time as _time

        idx = self._meta_to_actor.pop(meta_ref)
        self._actor_load[idx] -= 1
        if self._actor_load[idx] == 0:
            self._idle_since[idx] = _time.monotonic()
        self._maybe_reap()
        super().on_task_done(meta_ref)

    def _maybe_reap(self):
        """Scale DOWN: release actors idle past the grace period, above the
        pool floor (reference: the autoscaling actor pool's idle reaping —
        which is likewise timeout-based)."""
        import time as _time

        if self.input_queue:
            return
        now = _time.monotonic()
        for idx, since in list(self._idle_since.items()):
            if idx not in self._actor_load or self._actor_load[idx] != 0:
                self._idle_since.pop(idx, None)
                continue
            if (now - since >= self.IDLE_REAP_S
                    and self._alive_count() > self._strategy.min_size):
                actor = self._actors[idx]
                self._actors[idx] = None  # tombstone keeps indices stable
                del self._actor_load[idx]
                self._idle_since.pop(idx, None)
                try:
                    ray_tpu.kill(actor)
                except Exception:
                    pass

    def shutdown(self):
        for a in self._actors:
            if a is None:
                continue
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self._actors.clear()


class LimitOperator(PhysicalOperator):
    """Truncate the stream at N rows; upstream is halted by the executor
    once the limit is reached (reference: operators/limit_operator.py)."""

    def __init__(self, limit: int):
        super().__init__(f"Limit[{limit}]")
        self.limit = limit
        self.rows_taken = 0

    @property
    def reached(self) -> bool:
        return self.rows_taken >= self.limit

    def can_launch(self, max_in_flight: int) -> bool:
        return bool(self.input_queue) and not self.reached and \
            len(self.pending) < max_in_flight

    def launch_one(self):
        bundle: RefBundle = self.input_queue.popleft()
        want = self.limit - self.rows_taken
        if want <= 0:
            return
        if bundle.num_rows <= want:
            self.rows_taken += bundle.num_rows
            self.rows_out += bundle.num_rows
            self.bytes_out += bundle.size_bytes
            self._emit_direct(bundle)
        else:
            blocks_ref, meta_ref = _truncate_blocks.options(
                num_returns=2).remote(bundle.blocks_ref, want)
            self._track(meta_ref, blocks_ref)
            self.rows_taken += want
            self.tasks_launched += 1

    @property
    def done(self) -> bool:
        return super().done or (self.reached and not self.pending)


class UnionOperator(PhysicalOperator):
    """Pass-through merging multiple upstream streams."""

    def __init__(self):
        super().__init__("Union")

    def can_launch(self, max_in_flight: int) -> bool:
        return bool(self.input_queue)

    def launch_one(self):
        bundle = self.input_queue.popleft()
        self.rows_out += bundle.num_rows
        self.bytes_out += bundle.size_bytes
        self._emit_direct(bundle)


class ZipOperator(PhysicalOperator):
    """Barrier op pairing two input streams row-for-row. Inputs arrive
    tagged by branch via add_tagged_input."""

    def __init__(self):
        super().__init__("Zip")
        self.left: List[RefBundle] = []
        self.right: List[RefBundle] = []
        self._launched = False

    def add_tagged_input(self, branch: int, bundle: RefBundle):
        (self.left if branch == 0 else self.right).append(bundle)

    def can_launch(self, max_in_flight: int) -> bool:
        return self.inputs_complete and not self._launched

    def launch_one(self):
        self._launched = True
        left_refs = [b.blocks_ref for b in self.left]
        right_refs = [b.blocks_ref for b in self.right]
        gather_l = _gather_blocks.remote(*left_refs)
        gather_r = _gather_blocks.remote(*right_refs)
        blocks_ref, meta_ref = _zip_block_lists.options(
            num_returns=2).remote(gather_l, gather_r)
        self._track(meta_ref, blocks_ref)
        self.tasks_launched += 1

    @property
    def done(self) -> bool:
        return self._launched and not self.pending


class AllToAllOperator(PhysicalOperator):
    """Barrier exchange: sort / random_shuffle / repartition (reference:
    planner/exchange/ — ExchangeTaskScheduler map+reduce stages)."""

    def __init__(self, kind: str, key=None, descending: bool = False,
                 num_outputs: Optional[int] = None,
                 seed: Optional[int] = None):
        super().__init__(kind)
        self.kind = kind
        self.key = key
        self.descending = descending
        self.num_outputs = num_outputs
        self.seed = seed
        self._collected: List[RefBundle] = []
        self._phase = "collect"   # collect -> map -> reduce -> done
        self._map_refs: List[ObjectRef] = []
        self._boundary_refs: List[ObjectRef] = []

    def add_input(self, bundle: RefBundle):
        self._collected.append(bundle)

    def can_launch(self, max_in_flight: int) -> bool:
        return self.inputs_complete and self._phase == "collect"

    def launch_one(self):
        n_out = self.num_outputs or max(1, len(self._collected))
        if self.kind == "sort" and n_out > 1:
            sample_refs = [
                _sample_boundaries.remote(b.blocks_ref, self.key, n_out)
                for b in self._collected]
            samples = [v for ref in sample_refs for v in ray_tpu.get(ref)]
            samples.sort(reverse=self.descending)
            if samples:
                qs = np.linspace(0, len(samples) - 1, n_out + 1)[1:-1]
                boundaries = [samples[int(q)] for q in qs]
            else:
                boundaries = []
            # Degenerate boundary list (all-equal samples) still works —
            # empty partitions merge to empty blocks.
            boundaries = boundaries or [samples[0]] * (n_out - 1) if samples \
                else []
            if not boundaries:
                n_out = 1
        else:
            boundaries = ([None] * 0)
        map_refs = []
        for b in self._collected:
            map_refs.append(_partition_blocks.remote(
                b.blocks_ref, n_out, self.kind, self.key, self.descending,
                self.seed, boundaries if self.kind == "sort" else None))
        for i in range(n_out):
            part_i = [_select_partition.remote(mr, i) for mr in map_refs]
            blocks_ref, meta_ref = _merge_partition.options(
                num_returns=2).remote(self.kind, self.key, self.descending,
                                      None if self.seed is None
                                      else self.seed + i + 1,
                                      *part_i)
            self._track(meta_ref, blocks_ref)
            self.tasks_launched += 1
        self._collected.clear()
        self._phase = "reduce"

    @property
    def done(self) -> bool:
        return self._phase == "reduce" and not self.pending


class WriteOperator(PhysicalOperator):
    def __init__(self, path: str, file_format: str, write_kwargs: dict):
        super().__init__(f"Write[{file_format}]")
        self.path = path
        self.file_format = file_format
        self.write_kwargs = write_kwargs
        self._index = 0

    def launch_one(self):
        bundle: RefBundle = self.input_queue.popleft()
        blocks_ref, meta_ref = _write_blocks.options(num_returns=2).remote(
            bundle.blocks_ref, self.path, self.file_format, self._index,
            self.write_kwargs)
        self._index += 1
        self._track(meta_ref, blocks_ref)
        self.tasks_launched += 1


@ray_tpu.remote
def _gather_blocks(*block_lists: List[Block]) -> List[Block]:
    return [b for blocks in block_lists for b in blocks]


@ray_tpu.remote
def _select_partition(parts: List[List[Block]], i: int) -> List[Block]:
    return parts[i]


# ---- aggregation -----------------------------------------------------------

@ray_tpu.remote
def _hash_partition(blocks: List[Block], key, n: int) -> List[List[Block]]:
    """Partition rows so equal keys land in the same partition."""
    parts: List[List[Block]] = [[] for _ in range(n)]
    keys = [key] if isinstance(key, str) else list(key or [])
    for b in blocks:
        if b.num_rows == 0:
            continue
        acc = BlockAccessor(b)
        if not keys:
            parts[0].append(b)
            continue
        cols = acc.to_numpy()
        h = np.zeros(b.num_rows, dtype=np.uint64)
        for k in keys:
            col = cols[k]
            if col.dtype.kind in "iub":
                h = h * np.uint64(1000003) + col.astype(np.uint64)
            else:
                # Process-independent hash: builtin hash() is randomized
                # per interpreter, and map tasks for one exchange run in
                # different worker processes — equal keys MUST collide.
                import zlib
                hv = np.asarray(
                    [zlib.crc32(str(x).encode()) for x in col],
                    dtype=np.uint64)
                h = h * np.uint64(1000003) + hv
        assign = (h % np.uint64(n)).astype(np.int64)
        for i in range(n):
            idx = np.nonzero(assign == i)[0]
            if len(idx):
                parts[i].append(acc.take_rows(idx))
    return parts


@ray_tpu.remote
def _aggregate_partition(key, aggs, *part_lists: List[Block]
                         ) -> Tuple[List[Block], List[BlockMetadata]]:
    """Merge one hash partition and compute grouped aggregates with arrow."""
    import pyarrow as pa
    blocks = [b for parts in part_lists for b in parts]
    merged = BlockAccessor.concat(blocks)
    if merged.num_rows == 0:
        return [], []
    keys = [key] if isinstance(key, str) else list(key or [])
    arrow_aggs = [(a.on, a.arrow_name) for a in aggs]
    if keys:
        result = pa.TableGroupBy(merged, keys).aggregate(arrow_aggs)
        # Rename arrow's col_fn naming to the agg's display name.
        renames = {f"{a.on}_{a.arrow_name}": a.name for a in aggs}
        result = result.rename_columns(
            [renames.get(c, c) for c in result.column_names])
    else:
        cols = {}
        for a in aggs:
            fn = getattr(pa.compute, a.arrow_name.replace("hash_", ""))
            val = fn(merged.column(a.on))
            cols[a.name] = pa.array([val.as_py()])
        result = pa.table(cols)
    return [result], [BlockAccessor(result).get_metadata()]


class AggregateOperator(PhysicalOperator):
    """Barrier groupby: hash-partition then per-partition arrow groupby
    (reference: planner/exchange/aggregate_task_spec.py)."""

    def __init__(self, key, aggs, num_partitions: Optional[int] = None):
        super().__init__("Aggregate")
        self.key = key
        self.aggs = aggs
        self.num_partitions = num_partitions
        self._collected: List[RefBundle] = []
        self._phase = "collect"

    def add_input(self, bundle: RefBundle):
        self._collected.append(bundle)

    def can_launch(self, max_in_flight: int) -> bool:
        return self.inputs_complete and self._phase == "collect"

    def launch_one(self):
        n = self.num_partitions or max(1, min(len(self._collected), 8))
        if self.key is None:
            n = 1
        map_refs = [_hash_partition.remote(b.blocks_ref, self.key, n)
                    for b in self._collected]
        for i in range(n):
            part_i = [_select_partition.remote(mr, i) for mr in map_refs]
            blocks_ref, meta_ref = _aggregate_partition.options(
                num_returns=2).remote(self.key, self.aggs, *part_i)
            self._track(meta_ref, blocks_ref)
            self.tasks_launched += 1
        self._collected.clear()
        self._phase = "reduce"

    @property
    def done(self) -> bool:
        return self._phase == "reduce" and not self.pending
