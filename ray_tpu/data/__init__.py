"""ray_tpu.data: streaming distributed datasets (reference: python/ray/data/).

Lazy logical plans over blocks in the shared-memory object store, lowered
through an operator-fusing planner to a backpressured streaming executor
running ray_tpu tasks/actors. Consumption feeds JAX: ``iter_jax_batches``
stages batches into TPU HBM with double buffering, ``streaming_split``
fans one execution out to a gang of Train workers.
"""

from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata  # noqa: F401
from ray_tpu.data.context import DataContext  # noqa: F401
from ray_tpu.data.dataset import (
    read_delta,  # noqa: F401
    read_iceberg,  # noqa: F401
    Dataset,
    MaterializedDataset,
    from_arrow,
    from_blocks,
    from_items,
    from_numpy,
    from_pandas,
    range,
    range_tensor,
    from_huggingface,
    read_huggingface,
    from_torch,
    read_avro,
    read_bigquery,
    read_binary_files,
    read_csv,
    read_datasource,
    read_images,
    read_json,
    read_numpy,
    read_parquet,
    read_text,
    read_sql,
    read_tfrecords,
    read_webdataset,
)
from ray_tpu.data.datasource import (  # noqa: F401
    _CLOUD_SOURCES,
    Datasource,
    ReadTask,
    make_gated_reader,
)

# cloud-warehouse readers whose client libraries aren't in this image:
# importable API surface that raises an actionable error at call time
for _name, _mod in _CLOUD_SOURCES.items():
    globals()[_name] = make_gated_reader(_name, _mod)
del _name, _mod
from ray_tpu.data.grouped import (  # noqa: F401
    AggregateFn,
    Count,
    GroupedData,
    Max,
    Mean,
    Min,
    Std,
    Sum,
)
from ray_tpu.data.iterator import DataIterator  # noqa: F401
from ray_tpu.data import preprocessors  # noqa: F401
from ray_tpu.data.expr import col, lit  # noqa: F401
from ray_tpu.data.logical import ActorPoolStrategy, TaskPoolStrategy  # noqa: F401

__all__ = [
    "Block", "BlockAccessor", "BlockMetadata", "DataContext",
    "Dataset", "MaterializedDataset", "DataIterator",
    "Datasource", "ReadTask",
    "ActorPoolStrategy", "TaskPoolStrategy",
    "AggregateFn", "Sum", "Min", "Max", "Mean", "Count", "Std",
    "GroupedData", "preprocessors", "col", "lit",
    "range", "range_tensor", "from_items", "from_numpy", "from_arrow",
    "from_pandas", "from_blocks", "from_torch", "from_huggingface",
    "read_huggingface",
    "read_datasource", "read_parquet",
    "read_csv", "read_json", "read_numpy", "read_text",
    "read_binary_files", "read_tfrecords", "read_webdataset", "read_sql",
    "read_images", "read_avro", "read_bigquery", "read_delta",
    "read_iceberg",
] + list(_CLOUD_SOURCES)
