"""DataIterator: batched, prefetched consumption of executed datasets.

Reference: python/ray/data/iterator.py — iter_batches :95, iter_rows, and
the torch/tf variants. TPU-first addition: ``iter_jax_batches`` /
``device_put`` stage batches into HBM with double-buffering so the device
never waits on host formatting (the HBM-prefetch analogue of the
reference's GPU prefetching in iter_torch_batches :257).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor


class DataIterator:
    """Iterates batches over a stream of block lists.

    ``source_fn`` returns a fresh iterator of List[Block] per epoch.
    """

    def __init__(self, source_fn: Callable[[], Iterator[List[Block]]],
                 stats_fn: Optional[Callable[[], str]] = None):
        self._source_fn = source_fn
        self._stats_fn = stats_fn

    # ---- row/batch iteration ----

    def iter_rows(self) -> Iterator[Any]:
        for blocks in self._source_fn():
            for b in blocks:
                yield from BlockAccessor(b).iter_rows()

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     prefetch_batches: int = 2,
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None,
                     _collate_fn: Optional[Callable] = None
                     ) -> Iterator[Any]:
        def produce():
            from ray_tpu.data.transforms import _iter_batches
            blocks = (b for blocks in self._source_fn() for b in blocks)
            if local_shuffle_buffer_size:
                blocks = _shuffle_blocks(blocks, local_shuffle_buffer_size,
                                         local_shuffle_seed)
            count = 0
            last = None
            for batch in _iter_batches(blocks, batch_size, batch_format):
                if last is not None:
                    yield last
                last = batch
                count += 1
            if last is not None:
                if drop_last and batch_size and _batch_rows(last) < batch_size:
                    return
                yield last

        batches: Iterator[Any] = produce()
        if _collate_fn is not None:
            batches = (_collate_fn(b) for b in batches)
        if prefetch_batches and prefetch_batches > 0:
            batches = _prefetch(batches, prefetch_batches)
        return batches

    def iter_jax_batches(self, *, batch_size: Optional[int] = 256,
                         prefetch_batches: int = 2,
                         drop_last: bool = True,
                         dtypes: Optional[Dict[str, Any]] = None,
                         device: Optional[Any] = None,
                         sharding: Optional[Any] = None,
                         local_shuffle_buffer_size: Optional[int] = None,
                         local_shuffle_seed: Optional[int] = None
                         ) -> Iterator[Dict[str, Any]]:
        """Yield batches as jax.Arrays already resident on device/HBM.

        With ``prefetch_batches >= 1`` the host-side formatting and the
        device transfer of batch N+1 overlap the device's work on batch N
        (double buffering). ``sharding`` may be a jax.sharding.Sharding to
        device_put onto a mesh (data-parallel ingest).
        """
        import jax

        def to_device(batch: Dict[str, np.ndarray]):
            if dtypes:
                batch = {k: v.astype(dtypes[k]) if k in dtypes else v
                         for k, v in batch.items()}
            target = sharding if sharding is not None else device
            if target is not None:
                return jax.device_put(batch, target)
            return jax.device_put(batch)

        return self.iter_batches(
            batch_size=batch_size, batch_format="numpy",
            prefetch_batches=prefetch_batches, drop_last=drop_last,
            local_shuffle_buffer_size=local_shuffle_buffer_size,
            local_shuffle_seed=local_shuffle_seed,
            _collate_fn=to_device)

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           prefetch_batches: int = 2,
                           drop_last: bool = False,
                           dtypes=None, device: Optional[str] = None
                           ) -> Iterator[Dict[str, Any]]:
        """CPU-torch variant for parity with the reference's API."""
        import torch

        def collate(batch: Dict[str, np.ndarray]):
            out = {}
            for k, v in batch.items():
                t = torch.as_tensor(np.ascontiguousarray(v))
                if dtypes is not None:
                    dt = dtypes[k] if isinstance(dtypes, dict) else dtypes
                    t = t.to(dt)
                if device:
                    t = t.to(device)
                out[k] = t
            return out

        return self.iter_batches(
            batch_size=batch_size, batch_format="numpy",
            prefetch_batches=prefetch_batches, drop_last=drop_last,
            _collate_fn=collate)

    def stats(self) -> str:
        return self._stats_fn() if self._stats_fn else ""


def _batch_rows(batch) -> int:
    if isinstance(batch, dict):
        return len(next(iter(batch.values()))) if batch else 0
    return len(batch)


def _prefetch(it: Iterator[Any], depth: int) -> Iterator[Any]:
    """Run the producer in a background thread with a bounded queue.

    Abandoning the consumer (break / GC) sets ``stop``: the worker then
    drops out instead of blocking on a full queue forever, and closes the
    source so the streaming executor's cleanup (stats, actor-pool
    shutdown) runs.
    """
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    END = object()
    err: List[BaseException] = []
    stop = threading.Event()

    def worker():
        try:
            for item in it:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    break
        except BaseException as e:  # propagate to consumer
            err.append(e)
        finally:
            if stop.is_set():
                close = getattr(it, "close", None)
                if close:
                    try:
                        close()
                    except BaseException:
                        pass
            # END must not be dropped on a momentarily-full queue (the
            # consumer would block forever); block-put it unless cancelled
            # (a stopped consumer never reads again).
            while not stop.is_set():
                try:
                    q.put(END, timeout=0.1)
                    break
                except queue.Full:
                    continue

    t = threading.Thread(target=worker, daemon=True,
                         name="rtpu-data-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is END:
                if err:
                    raise err[0]
                return
            yield item
    finally:
        stop.set()
        # Unblock a worker stuck on a full queue.
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass


def _shuffle_blocks(blocks: Iterator[Block], buffer_rows: int,
                    seed: Optional[int]) -> Iterator[Block]:
    """Local (approximate) shuffle: accumulate ~buffer_rows rows, emit a
    shuffled block, repeat (reference: local_shuffle_buffer_size)."""
    rng = np.random.default_rng(seed)
    pending: List[Block] = []
    rows = 0
    for b in blocks:
        pending.append(b)
        rows += b.num_rows
        if rows >= buffer_rows:
            merged = BlockAccessor.concat(pending)
            acc = BlockAccessor(merged)
            yield acc.take_rows(rng.permutation(merged.num_rows))
            pending, rows = [], 0
    if pending:
        merged = BlockAccessor.concat(pending)
        acc = BlockAccessor(merged)
        yield acc.take_rows(rng.permutation(merged.num_rows))
