"""Dataset preprocessors: fit statistics once, transform anywhere.

Reference: python/ray/data/preprocessors/ (Preprocessor base in
preprocessor.py; scalers.py, encoder.py, imputer.py, concatenator.py,
chain.py, batch_mapper.py, tokenizer.py, hashing.py). Same contract:
``fit`` folds statistics over the Dataset in one streaming pass,
``transform`` is a ``map_batches`` that ships only the small fitted
state to workers, and ``transform_batch`` applies the same math to a
single in-memory batch (the serving path). Preprocessors pickle, so a
fitted instance can ride a Train/Serve checkpoint.

Numeric columns are handled as numpy arrays; fits are single-pass
(Welford for mean/std, streaming min/max, bounded reservoir for the
quantile-based RobustScaler — documented approximation).
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


class PreprocessorNotFittedError(RuntimeError):
    pass


class Preprocessor:
    """Base (reference: preprocessor.py:Preprocessor)."""

    _is_fittable = True

    def __init__(self):
        self._fitted = False

    # -- subclass hooks ---------------------------------------------------
    def _fit(self, dataset) -> None:
        raise NotImplementedError

    def _transform_numpy(self, batch: Dict[str, np.ndarray]
                         ) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    # -- public surface ---------------------------------------------------
    def fit(self, dataset) -> "Preprocessor":
        if self._is_fittable:
            self._fit(dataset)
        self._fitted = True
        return self

    def fit_transform(self, dataset):
        return self.fit(dataset).transform(dataset)

    def transform(self, dataset):
        self._check_fitted()
        return dataset.map_batches(self._transform_numpy,
                                   batch_format="numpy")

    def transform_batch(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        """Single-batch path for inference (reference:
        Preprocessor.transform_batch)."""
        self._check_fitted()
        return self._transform_numpy(
            {k: np.asarray(v) for k, v in batch.items()})

    def _check_fitted(self):
        if self._is_fittable and not self._fitted:
            raise PreprocessorNotFittedError(
                f"{type(self).__name__} must be fit() before transform")


# ---------------------------------------------------------------- scalers


def _welford_fold(dataset, columns) -> Dict[str, Tuple[float, float]]:
    """One streaming pass -> {col: (mean, std)} (Chan et al. merge)."""
    state = {c: None for c in columns}
    for batch in dataset.iter_batches(batch_format="numpy"):
        for c in columns:
            col = np.asarray(batch[c], dtype=np.float64).ravel()
            nb, mb = len(col), float(col.mean())
            m2b = float(((col - mb) ** 2).sum())
            s = state[c]
            if s is None:
                state[c] = [nb, mb, m2b]
            else:
                na, ma, m2a = s
                n = na + nb
                d = mb - ma
                state[c] = [n, ma + d * nb / n,
                            m2a + m2b + d * d * na * nb / n]
    out = {}
    for c, (n, mean, m2) in state.items():
        std = float(np.sqrt(m2 / n)) if n > 0 else 0.0
        out[c] = (mean, std)
    return out


class StandardScaler(Preprocessor):
    """(x - mean) / std (reference: scalers.py:StandardScaler)."""

    def __init__(self, columns: Sequence[str]):
        super().__init__()
        self.columns = list(columns)
        self.stats_: Dict[str, Tuple[float, float]] = {}

    def _fit(self, dataset):
        self.stats_ = _welford_fold(dataset, self.columns)

    def _transform_numpy(self, batch):
        out = dict(batch)
        for c in self.columns:
            mean, std = self.stats_[c]
            out[c] = (np.asarray(batch[c], np.float64) - mean) \
                / (std or 1.0)
        return out


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) (reference: scalers.py:MinMaxScaler)."""

    def __init__(self, columns: Sequence[str]):
        super().__init__()
        self.columns = list(columns)
        self.stats_: Dict[str, Tuple[float, float]] = {}

    def _fit(self, dataset):
        lo = {c: np.inf for c in self.columns}
        hi = {c: -np.inf for c in self.columns}
        for batch in dataset.iter_batches(batch_format="numpy"):
            for c in self.columns:
                col = np.asarray(batch[c], np.float64)
                lo[c] = min(lo[c], float(col.min()))
                hi[c] = max(hi[c], float(col.max()))
        self.stats_ = {c: (lo[c], hi[c]) for c in self.columns}

    def _transform_numpy(self, batch):
        out = dict(batch)
        for c in self.columns:
            lo, hi = self.stats_[c]
            span = (hi - lo) or 1.0
            out[c] = (np.asarray(batch[c], np.float64) - lo) / span
        return out


class MaxAbsScaler(Preprocessor):
    """x / max|x| (reference: scalers.py:MaxAbsScaler)."""

    def __init__(self, columns: Sequence[str]):
        super().__init__()
        self.columns = list(columns)
        self.stats_: Dict[str, float] = {}

    def _fit(self, dataset):
        m = {c: 0.0 for c in self.columns}
        for batch in dataset.iter_batches(batch_format="numpy"):
            for c in self.columns:
                m[c] = max(m[c], float(np.abs(
                    np.asarray(batch[c], np.float64)).max()))
        self.stats_ = m

    def _transform_numpy(self, batch):
        out = dict(batch)
        for c in self.columns:
            out[c] = np.asarray(batch[c], np.float64) \
                / (self.stats_[c] or 1.0)
        return out


class RobustScaler(Preprocessor):
    """(x - median) / IQR (reference: scalers.py:RobustScaler).
    Quantiles come from a bounded reservoir sample (100k values/column),
    exact for datasets under the reservoir size."""

    RESERVOIR = 100_000

    def __init__(self, columns: Sequence[str],
                 quantile_range: Tuple[float, float] = (0.25, 0.75)):
        super().__init__()
        self.columns = list(columns)
        self.quantile_range = quantile_range
        self.stats_: Dict[str, Tuple[float, float]] = {}

    def _fit(self, dataset):
        rng = np.random.default_rng(0)
        seen = {c: 0 for c in self.columns}
        res: Dict[str, np.ndarray] = {c: np.empty(0) for c in self.columns}
        for batch in dataset.iter_batches(batch_format="numpy"):
            for c in self.columns:
                col = np.asarray(batch[c], np.float64).ravel()
                if seen[c] < self.RESERVOIR:
                    take = min(self.RESERVOIR - seen[c], len(col))
                    res[c] = np.concatenate([res[c], col[:take]])
                else:  # classic reservoir replacement, batch-at-once
                    idx = rng.integers(0, seen[c] + len(col), len(col))
                    repl = idx < self.RESERVOIR
                    res[c][idx[repl]] = col[repl]
                seen[c] += len(col)
        lo_q, hi_q = self.quantile_range
        for c in self.columns:
            lo, med, hi = np.quantile(res[c], [lo_q, 0.5, hi_q])
            self.stats_[c] = (float(med), float(hi - lo))

    def _transform_numpy(self, batch):
        out = dict(batch)
        for c in self.columns:
            med, iqr = self.stats_[c]
            out[c] = (np.asarray(batch[c], np.float64) - med) / (iqr or 1.0)
        return out


class Normalizer(Preprocessor):
    """Row-wise norm across ``columns`` (reference: scalers.py:Normalizer).
    Stateless: no fit pass."""

    _is_fittable = False

    def __init__(self, columns: Sequence[str], norm: str = "l2"):
        super().__init__()
        if norm not in ("l1", "l2", "max"):
            raise ValueError(f"unknown norm {norm!r}")
        self.columns = list(columns)
        self.norm = norm

    def _transform_numpy(self, batch):
        out = dict(batch)
        mat = np.stack([np.asarray(batch[c], np.float64)
                        for c in self.columns], axis=1)
        if self.norm == "l2":
            d = np.sqrt((mat ** 2).sum(axis=1))
        elif self.norm == "l1":
            d = np.abs(mat).sum(axis=1)
        else:
            d = np.abs(mat).max(axis=1)
        d[d == 0] = 1.0
        for i, c in enumerate(self.columns):
            out[c] = mat[:, i] / d
        return out


class PowerTransformer(Preprocessor):
    """Box-Cox / Yeo-Johnson with a GIVEN power (reference:
    scalers.py:PowerTransformer — the reference likewise takes ``power``
    as a parameter rather than estimating it)."""

    _is_fittable = False

    def __init__(self, columns: Sequence[str], power: float,
                 method: str = "yeo-johnson"):
        super().__init__()
        if method not in ("yeo-johnson", "box-cox"):
            raise ValueError(method)
        self.columns = list(columns)
        self.power = power
        self.method = method

    def _transform_numpy(self, batch):
        out = dict(batch)
        lam = self.power
        for c in self.columns:
            x = np.asarray(batch[c], np.float64)
            if self.method == "box-cox":
                out[c] = np.log(x) if lam == 0 else (x ** lam - 1) / lam
            else:
                pos = x >= 0
                y = np.empty_like(x)
                if lam != 0:
                    y[pos] = ((x[pos] + 1) ** lam - 1) / lam
                else:
                    y[pos] = np.log1p(x[pos])
                if lam != 2:
                    y[~pos] = -(((-x[~pos] + 1) ** (2 - lam)) - 1) / (2 - lam)
                else:
                    y[~pos] = -np.log1p(-x[~pos])
                out[c] = y
        return out


# --------------------------------------------------------------- encoders


def _unique_fold(dataset, columns) -> Dict[str, List]:
    uniq: Dict[str, set] = {c: set() for c in columns}
    for batch in dataset.iter_batches(batch_format="numpy"):
        for c in columns:
            uniq[c].update(np.asarray(batch[c]).ravel().tolist())
    return {c: sorted(v) for c, v in uniq.items()}


class OrdinalEncoder(Preprocessor):
    """Category -> stable integer index (reference: encoder.py:
    OrdinalEncoder). Unseen values at transform map to -1."""

    def __init__(self, columns: Sequence[str]):
        super().__init__()
        self.columns = list(columns)
        self.stats_: Dict[str, Dict[Any, int]] = {}

    def _fit(self, dataset):
        self.stats_ = {c: {v: i for i, v in enumerate(vals)}
                       for c, vals in
                       _unique_fold(dataset, self.columns).items()}

    def _transform_numpy(self, batch):
        out = dict(batch)
        for c in self.columns:
            # the table's keys are sorted (built from sorted uniques), so
            # the category lookup vectorizes as a binary search; unseen
            # values fall out of the equality check -> -1
            keys = np.asarray(list(self.stats_[c]))
            vals = np.asarray(batch[c]).ravel()
            if len(keys) == 0:
                out[c] = np.full(len(vals), -1, np.int64)
                continue
            idx = np.searchsorted(keys, vals)
            idx = np.clip(idx, 0, len(keys) - 1)
            found = keys[idx] == vals
            out[c] = np.where(found, idx, -1).astype(np.int64)
        return out


class LabelEncoder(OrdinalEncoder):
    """Single label column -> index (reference: encoder.py:LabelEncoder)."""

    def __init__(self, label_column: str):
        super().__init__([label_column])
        self.label_column = label_column

    def inverse_transform_labels(self, idx: np.ndarray) -> List:
        inv = {i: v for v, i in self.stats_[self.label_column].items()}
        return [inv.get(int(i)) for i in np.asarray(idx).ravel()]


class OneHotEncoder(Preprocessor):
    """Category -> indicator columns ``{col}_{value}`` (reference:
    encoder.py:OneHotEncoder). Unseen values encode all-zeros."""

    def __init__(self, columns: Sequence[str]):
        super().__init__()
        self.columns = list(columns)
        self.stats_: Dict[str, List] = {}

    def _fit(self, dataset):
        self.stats_ = _unique_fold(dataset, self.columns)

    def _transform_numpy(self, batch):
        out = dict(batch)
        for c in self.columns:
            vals = np.asarray(batch[c]).ravel()
            for v in self.stats_[c]:
                out[f"{c}_{v}"] = (vals == v).astype(np.int64)
            del out[c]
        return out


class SimpleImputer(Preprocessor):
    """Fill missing (NaN) values (reference: imputer.py:SimpleImputer).
    Strategies: mean, most_frequent, constant(fill_value)."""

    def __init__(self, columns: Sequence[str], strategy: str = "mean",
                 fill_value: Optional[Any] = None):
        super().__init__()
        if strategy not in ("mean", "most_frequent", "constant"):
            raise ValueError(strategy)
        if strategy == "constant" and fill_value is None:
            raise ValueError("constant strategy needs fill_value")
        self.columns = list(columns)
        self.strategy = strategy
        self.fill_value = fill_value
        self.stats_: Dict[str, Any] = {}

    @property
    def _is_fittable(self):  # type: ignore[override]
        return self.strategy != "constant"

    def _fit(self, dataset):
        if self.strategy == "mean":
            sums = {c: [0.0, 0] for c in self.columns}
            for batch in dataset.iter_batches(batch_format="numpy"):
                for c in self.columns:
                    col = np.asarray(batch[c], np.float64)
                    ok = ~np.isnan(col)
                    sums[c][0] += float(col[ok].sum())
                    sums[c][1] += int(ok.sum())
            self.stats_ = {c: (s / n if n else 0.0)
                           for c, (s, n) in sums.items()}
        else:  # most_frequent
            counts = {c: collections.Counter() for c in self.columns}
            for batch in dataset.iter_batches(batch_format="numpy"):
                for c in self.columns:
                    vals = np.asarray(batch[c]).ravel()
                    if vals.dtype.kind == "f":
                        vals = vals[~np.isnan(vals)]
                    counts[c].update(vals.tolist())
            self.stats_ = {c: (counts[c].most_common(1)[0][0]
                               if counts[c] else 0)
                           for c in self.columns}

    def _transform_numpy(self, batch):
        out = dict(batch)
        for c in self.columns:
            fill = (self.fill_value if self.strategy == "constant"
                    else self.stats_[c])
            col = np.asarray(batch[c])
            if col.dtype.kind == "f":
                col = col.astype(np.float64).copy()
                col[np.isnan(col)] = fill
            else:
                # categorical path: missing = None / float NaN cells
                col = col.astype(object).copy()
                mask = np.asarray(
                    [v is None or (isinstance(v, float) and np.isnan(v))
                     for v in col.ravel().tolist()]).reshape(col.shape)
                col[mask] = fill
            out[c] = col
        return out


# ------------------------------------------------------------ structural


class Concatenator(Preprocessor):
    """Pack columns into one vector column (reference:
    concatenator.py:Concatenator)."""

    _is_fittable = False

    def __init__(self, columns: Sequence[str],
                 output_column_name: str = "concat_out",
                 drop: bool = True):
        super().__init__()
        self.columns = list(columns)
        self.output_column_name = output_column_name
        self.drop = drop

    def _transform_numpy(self, batch):
        out = dict(batch)
        mat = np.stack([np.asarray(batch[c], np.float64)
                        for c in self.columns], axis=1)
        out[self.output_column_name] = mat
        if self.drop:
            for c in self.columns:
                out.pop(c, None)
        return out


class BatchMapper(Preprocessor):
    """Arbitrary user function as a preprocessor (reference:
    batch_mapper.py:BatchMapper)."""

    _is_fittable = False

    def __init__(self, fn: Callable[[Dict[str, np.ndarray]],
                                    Dict[str, np.ndarray]]):
        super().__init__()
        self.fn = fn

    def _transform_numpy(self, batch):
        return self.fn(batch)


class Tokenizer(Preprocessor):
    """String column -> list-of-tokens column (reference:
    tokenizer.py:Tokenizer; default whitespace split)."""

    _is_fittable = False

    def __init__(self, columns: Sequence[str],
                 tokenization_fn: Optional[Callable[[str], List[str]]]
                 = None):
        super().__init__()
        self.columns = list(columns)
        self.fn = tokenization_fn or (lambda s: s.split())

    def _transform_numpy(self, batch):
        out = dict(batch)
        for c in self.columns:
            vals = np.asarray(batch[c]).ravel().tolist()
            # one object cell per ROW — np.asarray would instead build a
            # 2-D array whenever every row tokenizes to the same length
            # (or a single-row batch), silently changing the row count
            col = np.empty(len(vals), dtype=object)
            for i, v in enumerate(vals):
                col[i] = self.fn(str(v))
            out[c] = col
        return out


class FeatureHasher(Preprocessor):
    """Token lists -> fixed-width hashed count vectors (reference:
    hashing.py:FeatureHasher). Stateless by construction — the hash IS
    the vocabulary."""

    _is_fittable = False

    def __init__(self, columns: Sequence[str], num_features: int,
                 output_column_name: str = "hashed_features"):
        super().__init__()
        self.columns = list(columns)
        self.num_features = int(num_features)
        self.output_column_name = output_column_name

    def _transform_numpy(self, batch):
        import zlib

        out = dict(batch)
        n = len(np.asarray(batch[self.columns[0]]).ravel())
        mat = np.zeros((n, self.num_features), np.float64)
        for c in self.columns:
            col = np.asarray(batch[c]).ravel()
            for i, tokens in enumerate(col.tolist()):
                if isinstance(tokens, str):
                    tokens = [tokens]
                for tok in tokens:
                    h = zlib.crc32(str(tok).encode()) % self.num_features
                    mat[i, h] += 1.0
        out[self.output_column_name] = mat
        for c in self.columns:
            out.pop(c, None)
        return out


class Chain(Preprocessor):
    """Sequential composition (reference: chain.py:Chain): fit stage k
    on the data as transformed by stages 0..k-1."""

    def __init__(self, *preprocessors: Preprocessor):
        super().__init__()
        self.preprocessors = list(preprocessors)

    def _fit(self, dataset):
        ds = dataset
        for p in self.preprocessors:
            p.fit(ds)
            ds = p.transform(ds)

    def transform(self, dataset):
        self._check_fitted()
        ds = dataset
        for p in self.preprocessors:
            ds = p.transform(ds)
        return ds

    def transform_batch(self, batch):
        self._check_fitted()
        for p in self.preprocessors:
            batch = p.transform_batch(batch)
        return batch
