"""Blocks: the unit of data in ray_tpu.data.

Reference: python/ray/data/block.py — Block (Arrow table / pandas frame),
BlockAccessor :221, BlockMetadata. Here the canonical in-store block is a
``pyarrow.Table``; simple (untabular) rows are wrapped in a single ``item``
column, mirroring the reference's strict-mode behavior.

TPU note: batch extraction favors numpy (dict of contiguous ndarrays) since
that is the zero-copy path into ``jax.device_put`` / HBM.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

# A Block is a pyarrow Table; a Batch is what UDFs/iterators see.
Block = pa.Table
Batch = Union[pa.Table, Dict[str, np.ndarray], "pandas.DataFrame"]

ITEM_COL = "item"


@dataclass
class BlockMetadata:
    """Sidecar metadata, kept small so the executor can plan without
    fetching block payloads (reference: BlockMetadata in data/block.py)."""

    num_rows: int
    size_bytes: int
    schema: Optional[pa.Schema] = None
    input_files: Optional[List[str]] = None
    exec_stats: Optional[dict] = None


def _is_tabular_row(row: Any) -> bool:
    return isinstance(row, dict)


class BlockAccessor:
    """Uniform view over a block (reference: BlockAccessor, block.py:221)."""

    def __init__(self, block: Block):
        if not isinstance(block, pa.Table):
            raise TypeError(f"Block must be a pyarrow.Table, got {type(block)}")
        self._table = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    # ---- builders ----

    @staticmethod
    def batch_to_block(batch: Batch) -> Block:
        """Normalize a UDF return / input batch into a pyarrow Table."""
        import pandas as pd

        if isinstance(batch, pa.Table):
            return batch
        if isinstance(batch, pd.DataFrame):
            return pa.Table.from_pandas(batch, preserve_index=False)
        if isinstance(batch, dict):
            cols = {}
            shapes = {}
            for k, v in batch.items():
                v = np.asarray(v)
                if v.ndim > 1:
                    # Tensor column: flattened FixedSizeList; the inner
                    # shape rides on the table schema metadata so numpy
                    # round-trips keep (N, *inner_shape).
                    cols[k] = _tensor_to_arrow(v)
                    shapes[k] = v.shape[1:]
                else:
                    cols[k] = pa.array(v)
            table = pa.table(cols)
            if shapes:
                meta = {f"tensor_shape:{k}".encode(): repr(tuple(s)).encode()
                        for k, s in shapes.items()}
                table = table.replace_schema_metadata(
                    {**(table.schema.metadata or {}), **meta})
            return table
        raise TypeError(
            "Batches must be pyarrow.Table, pandas.DataFrame, or "
            f"Dict[str, np.ndarray]; got {type(batch)}")

    @staticmethod
    def rows_to_block(rows: List[Any]) -> Block:
        if rows and all(_is_tabular_row(r) for r in rows):
            # Union of keys across rows, first-seen order; rows missing a
            # key contribute nulls (reference fills missing fields with
            # null rather than raising).
            keys = list(rows[0].keys())
            seen = set(keys)
            for r in rows[1:]:
                for k in r:
                    if k not in seen:
                        seen.add(k)
                        keys.append(k)
            batch = {}
            obj_cols = {}
            for k in keys:
                vals = [r.get(k) for r in rows]
                if any(v is None for v in vals):
                    obj_cols[k] = vals
                    continue
                try:
                    arr = np.asarray(vals)
                except ValueError:
                    arr = np.empty(len(vals), dtype=object)
                    arr[:] = vals
                if arr.dtype == object:
                    obj_cols[k] = vals
                else:
                    batch[k] = arr
            table = BlockAccessor.batch_to_block(batch) if batch else None
            if obj_cols:
                obj_table = pa.table({k: pa.array(v)
                                      for k, v in obj_cols.items()})
                if table is None:
                    table = obj_table
                else:
                    for name in obj_table.column_names:
                        table = table.append_column(
                            name, obj_table.column(name))
                    table = table.select(keys)
            return table
        return pa.table({ITEM_COL: pa.array(rows)})

    # ---- views ----

    @property
    def table(self) -> pa.Table:
        return self._table

    def num_rows(self) -> int:
        return self._table.num_rows

    def size_bytes(self) -> int:
        return self._table.nbytes

    def schema(self) -> pa.Schema:
        return self._table.schema

    def get_metadata(self, input_files: Optional[List[str]] = None,
                     exec_stats: Optional[dict] = None) -> BlockMetadata:
        return BlockMetadata(
            num_rows=self.num_rows(),
            size_bytes=self.size_bytes(),
            schema=self.schema(),
            input_files=input_files,
            exec_stats=exec_stats,
        )

    def to_batch(self, batch_format: str) -> Batch:
        if batch_format in ("numpy", "default"):
            return self.to_numpy()
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format in ("pyarrow", "arrow"):
            return self._table
        raise ValueError(f"Unknown batch_format {batch_format!r}")

    def to_numpy(self) -> Dict[str, np.ndarray]:
        shapes = _tensor_shapes(self._table)
        out = {}
        for name in self._table.column_names:
            col = self._table.column(name)
            arr = _arrow_to_numpy(col)
            if name in shapes and arr.ndim == 2:
                arr = arr.reshape((arr.shape[0],) + shapes[name])
            out[name] = arr
        return out

    def to_pandas(self):
        return self._table.to_pandas()

    def iter_rows(self) -> Iterator[Any]:
        cols = self._table.column_names
        simple = cols == [ITEM_COL]
        # per-column inner tensor shape from schema metadata, so rows see
        # (d0, d1, ...) cells, not the flattened storage layout
        shapes = _tensor_shapes(self._table)
        for i in range(self._table.num_rows):
            if simple:
                yield self._table.column(0)[i].as_py()
            else:
                yield {c: _cell(self._table.column(c), i, shapes.get(c))
                       for c in cols}

    # ---- ops ----

    def slice(self, start: int, end: int) -> Block:
        return self._table.slice(start, end - start)

    def take_rows(self, indices: np.ndarray) -> Block:
        return self._table.take(pa.array(indices))

    def select_columns(self, cols: List[str]) -> Block:
        return self._table.select(cols)

    def drop_columns(self, cols: List[str]) -> Block:
        keep = [c for c in self._table.column_names if c not in cols]
        return self._table.select(keep)

    def rename_columns(self, mapping: Dict[str, str]) -> Block:
        names = [mapping.get(c, c) for c in self._table.column_names]
        out = self._table.rename_columns(names)
        # Tensor columns carry their inner shape in schema metadata keyed
        # by column name — remap those keys or the renamed column decodes
        # as a flattened (N, prod(shape)) array.
        meta = self._table.schema.metadata
        if meta:
            new_meta = {}
            for k, v in meta.items():
                ks = k.decode() if isinstance(k, bytes) else k
                if ks.startswith("tensor_shape:"):
                    col = ks[len("tensor_shape:"):]
                    ks = f"tensor_shape:{mapping.get(col, col)}"
                new_meta[ks.encode()] = v
            out = out.replace_schema_metadata(new_meta)
        return out

    def sort_indices(self, key: Union[str, List[str]],
                     descending: bool = False) -> np.ndarray:
        keys = [key] if isinstance(key, str) else list(key)
        order = "descending" if descending else "ascending"
        idx = pa.compute.sort_indices(
            self._table, sort_keys=[(k, order) for k in keys])
        return idx.to_numpy()

    @staticmethod
    def concat(blocks: List[Block]) -> Block:
        blocks = [b for b in blocks if b.num_rows > 0]
        if not blocks:
            return pa.table({})
        if len(blocks) == 1:
            return blocks[0]
        return pa.concat_tables(blocks, promote_options="default")

    def random_shuffle_indices(self, seed: Optional[int]) -> np.ndarray:
        rng = np.random.default_rng(seed)
        return rng.permutation(self.num_rows())


# ---- tensor column helpers -------------------------------------------------

def _tensor_to_arrow(arr: np.ndarray) -> pa.Array:
    """Store an (N, ...) ndarray as a FixedSizeList arrow column, keeping
    the inner shape in the field metadata so round-trips preserve it."""
    n = arr.shape[0]
    inner_shape = arr.shape[1:]
    flat = np.ascontiguousarray(arr).reshape(n, -1)
    inner_len = flat.shape[1]
    values = pa.array(flat.reshape(-1))
    fsl = pa.FixedSizeListArray.from_arrays(values, inner_len)
    # Shape travels via an extension-free side channel: a struct of
    # (data, shape) would bloat; we instead rebuild from metadata-carrying
    # schema at table level. Simplest robust approach: attach to field meta.
    field = pa.field("t", fsl.type,
                     metadata={b"tensor_shape": repr(inner_shape).encode()})
    return fsl.cast(field.type)


def _arrow_to_numpy(col: pa.ChunkedArray) -> np.ndarray:
    typ = col.type
    if pa.types.is_fixed_size_list(typ):
        combined = col.combine_chunks()
        if isinstance(combined, pa.ChunkedArray):
            combined = combined.chunk(0) if combined.num_chunks else \
                pa.array([], type=typ)
        values = combined.values.to_numpy(zero_copy_only=False)
        n = len(combined)
        width = typ.list_size
        return values.reshape(n, width)
    try:
        return col.to_numpy(zero_copy_only=False)
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
        return np.asarray(col.to_pylist(), dtype=object)


def _tensor_shapes(table: pa.Table) -> dict:
    """Inner tensor shape per column, from ``tensor_shape:<col>`` schema
    metadata (written by batch_to_block for ndim>1 columns)."""
    import ast

    meta = table.schema.metadata or {}
    shapes = {}
    for c in table.column_names:
        key = f"tensor_shape:{c}".encode()
        if key in meta:
            shapes[c] = tuple(ast.literal_eval(meta[key].decode()))
    return shapes


def _cell(col: pa.ChunkedArray, i: int, inner_shape=None):
    v = col[i]
    if pa.types.is_fixed_size_list(col.type):
        # .values keeps the arrow value dtype (as_py would widen to
        # int64); copy so row cells stay writable (arrow views are not)
        arr = v.values.to_numpy(zero_copy_only=False).copy()
        if inner_shape is not None:
            arr = arr.reshape(inner_shape)
        return arr
    return v.as_py()
