"""StreamingExecutor: backpressured pull-based pipeline execution.

Reference: python/ray/data/_internal/execution/streaming_executor.py:48 —
execute() :89, _scheduling_loop_step :272, and streaming_executor_state.py
(select_operator_to_run :517, process_completed_tasks :379).

The executor topologically orders the physical operators, then loops:
  1. wait (briefly) on all in-flight task metadata refs,
  2. route completed outputs downstream,
  3. launch new tasks on operators that have inputs, respecting per-op
     concurrency caps and downstream output-queue backpressure,
  4. yield finished RefBundles from the sink operator to the consumer.
Because it is a generator, consumer pull rate naturally backpressures the
whole pipeline.
"""

from __future__ import annotations

import time
from typing import Dict, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.data.context import DataContext
from ray_tpu.data.physical import (
    ActorPoolMapOperator,
    LimitOperator,
    PhysicalOperator,
    RefBundle,
    ZipOperator,
)
from ray_tpu.data.stats import DatasetStats, OpStats


class Topology:
    """Operators in topological order with explicit edges.

    edges: map op -> list of (downstream_op, branch_tag). branch_tag
    matters only for Zip (0=left, 1=right).
    """

    def __init__(self, ops: List[PhysicalOperator],
                 edges: Dict[int, List[Tuple[PhysicalOperator, int]]]):
        self.ops = ops
        self.edges = edges

    def downstream(self, op: PhysicalOperator):
        return self.edges.get(id(op), [])

    def upstream_of(self, op: PhysicalOperator) -> List[PhysicalOperator]:
        return [u for u in self.ops
                if any(d is op for d, _ in self.downstream(u))]


class StreamingExecutor:
    def __init__(self, topology: Topology,
                 context: Optional[DataContext] = None):
        self._topo = topology
        self._ctx = context or DataContext.get_current()
        self._stats = DatasetStats()

    @property
    def stats(self) -> DatasetStats:
        return self._stats

    def execute(self) -> Iterator[RefBundle]:
        """Run the pipeline, yielding output bundles of the sink op."""
        topo = self._topo
        ops = topo.ops
        sink = ops[-1]
        ctx = self._ctx
        max_in_flight = ctx.max_tasks_in_flight_per_op or self._default_cap()
        op_stats = {id(op): self._stats.add_op(op.name) for op in ops}
        self._bp_since: Dict[int, float] = {}  # op id -> gated since
        t0 = time.perf_counter()
        try:
            while True:
                progressed = self._process_completed(ops, op_stats)
                self._route_outputs(topo, sink)
                launched = self._launch_ready(topo, max_in_flight,
                                              op_stats)
                self._mark_timeline(ops, op_stats, t0)
                while sink.output_queue:
                    bundle = sink.output_queue.popleft()
                    op_stats[id(sink)].rows += bundle.num_rows
                    yield bundle
                # Sink done ⇒ nothing further can reach the consumer, even
                # if upstream ops were halted mid-stream by a Limit.
                if sink.done and not sink.output_queue:
                    break
                if all(op.done for op in ops) and not sink.output_queue:
                    break
                if not progressed and not launched:
                    # Nothing moved: block on in-flight work instead of
                    # spinning. Streaming tasks have no waitable ref — the
                    # next yield only shows up to poll_streams — so cap
                    # the block while any stream is live.
                    streaming = any(op.has_streams() for op in ops)
                    refs = [r for op in ops for r in op.waitable_refs()]
                    if refs:
                        ray_tpu.wait(refs, num_returns=1,
                                     timeout=0.05 if streaming else 10.0)
                    else:
                        time.sleep(0.01 if streaming else 0.002)
        finally:
            self._stats.wall_time_s = time.perf_counter() - t0
            now = time.perf_counter()
            self._mark_timeline(ops, op_stats, t0)
            for op in ops:
                since = self._bp_since.pop(id(op), None)
                if since is not None:
                    op_stats[id(op)].backpressure_s += now - since
                self._snapshot_op(op, op_stats[id(op)])
                if isinstance(op, ActorPoolMapOperator):
                    op.shutdown()

    def _mark_timeline(self, ops, op_stats, t0):
        """Per-op start / first-output / done timestamps relative to
        pipeline start. With streaming map returns, a downstream op's
        start predates its upstream's done — Dataset.stats() shows it."""
        now = time.perf_counter() - t0
        for op in ops:
            s = op_stats[id(op)]
            if s.started_s is None and (op.tasks_launched or op.rows_out):
                s.started_s = now
            if s.first_output_s is None and op.rows_out:
                s.first_output_s = now
            if s.finished_s is None and op.done:
                s.finished_s = now

    @staticmethod
    def _snapshot_op(op, s):
        """Copy the operator's live counters into its OpStats row
        (reference: per-op breakdown in data/_internal/stats.py)."""
        s.tasks_launched = op.tasks_launched
        s.rows_in = op.rows_in
        s.rows_out = op.rows_out
        s.bytes_in = op.bytes_in
        s.bytes_out = op.bytes_out
        s.task_wall_s = op.task_wall_s
        s.task_cpu_s = op.task_cpu_s
        s.sched_wall_s = op.sched_wall_s
        s.peak_block_bytes = op.peak_block_bytes

    # ---- internals ----

    def _default_cap(self) -> int:
        core = ray_tpu.get_runtime_context()
        n = getattr(core, "num_workers", None) or 8
        return max(2, int(n))

    def _process_completed(self, ops, op_stats) -> bool:
        refs: List[ObjectRef] = []
        owner: Dict[ObjectRef, PhysicalOperator] = {}
        for op in ops:
            for r in op.waitable_refs():
                refs.append(r)
                owner[r] = op
        if refs:
            ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0)
            for r in ready:
                op = owner[r]
                op.on_task_done(r)
                op_stats[id(op)].tasks_finished += 1
            progressed = bool(ready)
        else:
            progressed = False
        # streaming tasks: drain whatever yields are ready right now
        for op in ops:
            p, finished = op.poll_streams()
            progressed = progressed or p
            op_stats[id(op)].tasks_finished += finished
        return progressed

    def _route_outputs(self, topo: Topology, sink):
        for op in topo.ops:
            if op is sink:
                continue
            targets = topo.downstream(op)
            if not targets:
                continue
            while op.output_queue:
                bundle = op.output_queue.popleft()
                for down, branch in targets:
                    if isinstance(down, ZipOperator):
                        down.add_tagged_input(branch, bundle)
                    else:
                        down.add_input(bundle)
            # Propagate completion: once every direct upstream of `down` is
            # done and drained, `down` will receive no more inputs. Note a
            # reached Limit is done even while ops further up were halted
            # mid-stream — its downstreams must still be released.
            if op.done and not op.output_queue:
                for down, _ in targets:
                    if not down.inputs_complete and all(
                            u.done and not u.output_queue
                            for u in topo.upstream_of(down)):
                        down.mark_inputs_done()

    def _launch_ready(self, topo: Topology, max_in_flight: int,
                      op_stats=None) -> bool:
        launched = False
        ctx = self._ctx
        # Favor draining downstream ops first (iterate sink -> source) so
        # the pipeline stays shallow; skip ops whose downstream input
        # queues are saturated (backpressure). Gating on the DOWNSTREAM
        # op's routed-but-unconsumed depth (input_queue + in-flight) is
        # what actually engages: _route_outputs drains our own
        # output_queue every tick, so gating on it alone never fires
        # (reference: OpBufferQueue accounting in streaming_executor_state).
        now = time.perf_counter()
        for op in reversed(topo.ops):
            # Limit reached upstream: stop feeding.
            if self._limit_reached_below(topo, op):
                continue
            launched_here = False
            while (op.can_launch(max_in_flight) and
                   len(op.output_queue) < ctx.max_op_output_queue_blocks and
                   not self._backpressured(topo, op, ctx)):
                op.launch_one()
                launched = launched_here = True
            if op_stats is not None:
                # backpressure accounting: has runnable work but is gated
                gated = (not launched_here and op.can_launch(max_in_flight)
                         and self._backpressured(topo, op, ctx))
                since = self._bp_since.get(id(op))
                if gated and since is None:
                    self._bp_since[id(op)] = now
                elif not gated and since is not None:
                    op_stats[id(op)].backpressure_s += now - since
                    del self._bp_since[id(op)]
        return launched

    def _backpressured(self, topo: Topology, op: PhysicalOperator,
                       ctx) -> bool:
        """True if any downstream op has too many routed-but-unconsumed
        bundles. Barrier ops (AllToAll/Aggregate/Zip) collect into side
        buffers rather than input_queue, so they are never gated — they
        need every input before running."""
        for down, _ in topo.downstream(op):
            if (len(down.input_queue) + len(down.pending) >=
                    ctx.max_op_output_queue_blocks):
                return True
        return False

    def _limit_reached_below(self, topo: Topology,
                             op: PhysicalOperator) -> bool:
        for down, _ in topo.downstream(op):
            if isinstance(down, LimitOperator) and down.reached:
                return True
            if self._limit_reached_below(topo, down):
                return True
        return False
