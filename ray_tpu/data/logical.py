"""Logical plan: lazy operator DAG built by Dataset transforms.

Reference: python/ray/data/_internal/logical/ — LogicalOperator nodes,
LogicalPlan, and optimizer rules (operator_fusion.py, limit pushdown).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Union

from ray_tpu.data.context import DataContext


class LogicalOperator:
    def __init__(self, name: str, inputs: List["LogicalOperator"]):
        self.name = name
        self.inputs = inputs

    def __repr__(self):
        return self.name


class Read(LogicalOperator):
    def __init__(self, datasource, parallelism: int):
        super().__init__(f"Read{datasource.get_name()}", [])
        self.datasource = datasource
        self.parallelism = parallelism


class InputData(LogicalOperator):
    """Pre-materialized input: list of (block_ref, metadata)."""

    def __init__(self, ref_bundles):
        super().__init__("InputData", [])
        self.ref_bundles = ref_bundles


class AbstractMap(LogicalOperator):
    """Row/batch transform; fusable with adjacent maps.

    kind: one of 'map_batches' | 'map_rows' | 'flat_map' | 'filter'.
    """

    def __init__(self, name: str, input_op: LogicalOperator, kind: str,
                 fn: Callable, fn_args: tuple = (), fn_kwargs: dict = None,
                 batch_size: Optional[int] = None,
                 batch_format: Optional[str] = None,
                 compute: Optional["ComputeStrategy"] = None,
                 num_chips: int = 0,
                 fn_constructor_args: tuple = ()):
        super().__init__(name, [input_op])
        self.kind = kind
        self.fn = fn
        self.fn_args = fn_args
        self.fn_kwargs = fn_kwargs or {}
        self.batch_size = batch_size
        self.batch_format = batch_format or DataContext.get_current().batch_format
        self.compute = compute
        self.num_chips = num_chips
        self.fn_constructor_args = fn_constructor_args


class Limit(LogicalOperator):
    def __init__(self, input_op: LogicalOperator, limit: int):
        super().__init__(f"Limit[{limit}]", [input_op])
        self.limit = limit


class AbstractAllToAll(LogicalOperator):
    """Materializing exchange: sort, shuffle, repartition (reference:
    python/ray/data/_internal/planner/exchange/)."""

    def __init__(self, name: str, input_op: LogicalOperator, kind: str,
                 key: Union[str, List[str], None] = None,
                 descending: bool = False,
                 num_outputs: Optional[int] = None,
                 seed: Optional[int] = None):
        super().__init__(name, [input_op])
        self.kind = kind  # 'sort' | 'random_shuffle' | 'repartition'
        self.key = key
        self.descending = descending
        self.num_outputs = num_outputs
        self.seed = seed


class Aggregate(LogicalOperator):
    def __init__(self, input_op: LogicalOperator,
                 key: Optional[Union[str, List[str]]], aggs: List[Any]):
        super().__init__("Aggregate", [input_op])
        self.key = key
        self.aggs = aggs


class Union(LogicalOperator):
    def __init__(self, inputs: List[LogicalOperator]):
        super().__init__("Union", inputs)


class Zip(LogicalOperator):
    def __init__(self, left: LogicalOperator, right: LogicalOperator):
        super().__init__("Zip", [left, right])


class Write(LogicalOperator):
    def __init__(self, input_op: LogicalOperator, path: str,
                 file_format: str, write_kwargs: dict = None):
        super().__init__(f"Write[{file_format}]", [input_op])
        self.path = path
        self.file_format = file_format
        self.write_kwargs = write_kwargs or {}


class ComputeStrategy:
    pass


class TaskPoolStrategy(ComputeStrategy):
    def __init__(self, size: Optional[int] = None):
        self.size = size


class ActorPoolStrategy(ComputeStrategy):
    """Run the map UDF on a pool of actors (stateful UDF classes;
    reference: python/ray/data/_internal/compute.py ActorPoolStrategy)."""

    def __init__(self, size: Optional[int] = None,
                 min_size: Optional[int] = None,
                 max_size: Optional[int] = None,
                 max_tasks_in_flight_per_actor: int = 2):
        self.min_size = min_size or size or 1
        self.max_size = max_size or size or self.min_size
        self.max_tasks_in_flight_per_actor = max_tasks_in_flight_per_actor


class LogicalPlan:
    def __init__(self, dag: LogicalOperator, context: DataContext):
        self.dag = dag
        self.context = context

    def sources(self) -> List[LogicalOperator]:
        out, seen, stack = [], set(), [self.dag]
        while stack:
            op = stack.pop()
            if id(op) in seen:
                continue
            seen.add(id(op))
            if not op.inputs:
                out.append(op)
            stack.extend(op.inputs)
        return out
