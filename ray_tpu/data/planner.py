"""Planner: lower the logical plan to physical operators, with fusion.

Reference: python/ray/data/_internal/planner/planner.py plus the optimizer
rules in _internal/logical/rules/operator_fusion.py — Read→Map and Map→Map
fusion so a fused pipeline runs as one task per block.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ray_tpu.data import logical as L
from ray_tpu.data.context import DataContext
from ray_tpu.data.physical import (
    ActorPoolMapOperator,
    AggregateOperator,
    AllToAllOperator,
    InputDataBuffer,
    LimitOperator,
    PhysicalOperator,
    TaskPoolMapOperator,
    UnionOperator,
    WriteOperator,
    ZipOperator,
    _CALLABLE_CLASS_MARKER,
)
from ray_tpu.data.streaming_executor import Topology
from ray_tpu.data.transforms import MapStep, MapTransformChain


def _map_step_of(op: L.AbstractMap) -> MapStep:
    fn = op.fn
    if isinstance(fn, type):
        # Callable class: instantiated per actor-pool worker.
        fn = _CALLABLE_CLASS_MARKER
    return MapStep(op.kind, fn, op.fn_args, op.fn_kwargs, op.batch_size,
                   op.batch_format)


def _resources_of(op: L.AbstractMap) -> dict:
    # TPU chips are bound to dedicated actor workers in the core runtime
    # (runtime.py _prepare_request: num_tpus is actor-scoped), so chip
    # requests are only meaningful on actor-pool map operators.
    return {"num_tpus": op.num_chips} if op.num_chips else {}


class Planner:
    def __init__(self, context: Optional[DataContext] = None):
        self._ctx = context or DataContext.get_current()

    def plan(self, dag: L.LogicalOperator) -> Topology:
        from ray_tpu.data.optimizer import LogicalOptimizer

        dag = LogicalOptimizer().optimize(dag)
        ops: List[PhysicalOperator] = []
        edges: Dict[int, List[Tuple[PhysicalOperator, int]]] = {}

        def emit(op: PhysicalOperator) -> PhysicalOperator:
            ops.append(op)
            return op

        def connect(up: PhysicalOperator, down: PhysicalOperator,
                    branch: int = 0):
            edges.setdefault(id(up), []).append((down, branch))

        def lower(node: L.LogicalOperator) -> PhysicalOperator:
            ctx = self._ctx
            if isinstance(node, L.Read):
                tasks = node.datasource.get_read_tasks(node.parallelism)
                return emit(InputDataBuffer(read_tasks=tasks))
            if isinstance(node, L.InputData):
                return emit(InputDataBuffer(bundles=node.ref_bundles))
            if isinstance(node, L.AbstractMap):
                up = lower(node.inputs[0])
                step = _map_step_of(node)
                use_actors = isinstance(node.compute, L.ActorPoolStrategy)
                has_user_cap = (isinstance(node.compute, L.TaskPoolStrategy)
                                and node.compute.size is not None)
                if ctx.optimizer_enabled and not use_actors \
                        and not has_user_cap:
                    # Fuse into an upstream read with no consumers yet.
                    if (isinstance(up, InputDataBuffer) and
                            not edges.get(id(up)) and
                            up is ops[-1] and up._read_tasks):
                        up._chain = (up._chain.fuse(MapTransformChain([step]))
                                     if up._chain else
                                     MapTransformChain(
                                         [step],
                                         ctx.target_max_block_size))
                        up.name = f"{up.name}->{node.name}"
                        return up
                    # Fuse into an upstream task-pool map — but never into
                    # one carrying a user concurrency cap, which would
                    # silently throttle this uncapped stage too.
                    if (isinstance(up, TaskPoolMapOperator) and
                            up._max_concurrency is None and
                            not edges.get(id(up)) and up is ops[-1]):
                        up.chain = up.chain.fuse(MapTransformChain([step]))
                        up.name = f"{up.name}->{node.name}"
                        return up
                chain = MapTransformChain([step], ctx.target_max_block_size)
                if use_actors:
                    udf_cls = node.fn if isinstance(node.fn, type) else None
                    phys = ActorPoolMapOperator(
                        node.name, chain, node.compute, udf_cls,
                        node.fn_constructor_args,
                        resources=_resources_of(node))
                else:
                    cap = (node.compute.size
                           if isinstance(node.compute, L.TaskPoolStrategy)
                           else None)
                    phys = TaskPoolMapOperator(
                        node.name, chain, resources=_resources_of(node),
                        max_concurrency=cap)
                emit(phys)
                connect(up, phys)
                return phys
            if isinstance(node, L.Limit):
                up = lower(node.inputs[0])
                phys = emit(LimitOperator(node.limit))
                connect(up, phys)
                return phys
            if isinstance(node, L.AbstractAllToAll):
                up = lower(node.inputs[0])
                phys = emit(AllToAllOperator(
                    node.kind, node.key, node.descending,
                    node.num_outputs, node.seed))
                connect(up, phys)
                return phys
            if isinstance(node, L.Aggregate):
                up = lower(node.inputs[0])
                phys = emit(AggregateOperator(node.key, node.aggs))
                connect(up, phys)
                return phys
            from ray_tpu.data.grouped import (make_map_groups_operator,
                                              _MapGroups)
            if isinstance(node, _MapGroups):
                up = lower(node.inputs[0])
                phys = emit(make_map_groups_operator(node.key, node.fn,
                                                     node.batch_format))
                connect(up, phys)
                return phys
            if isinstance(node, L.Union):
                phys = UnionOperator()
                for inp in node.inputs:
                    up = lower(inp)
                    connect(up, phys)
                emit(phys)
                return phys
            if isinstance(node, L.Zip):
                left = lower(node.inputs[0])
                right = lower(node.inputs[1])
                phys = emit(ZipOperator())
                connect(left, phys, branch=0)
                connect(right, phys, branch=1)
                return phys
            if isinstance(node, L.Write):
                up = lower(node.inputs[0])
                phys = emit(WriteOperator(node.path, node.file_format,
                                          node.write_kwargs))
                connect(up, phys)
                return phys
            raise TypeError(f"Cannot lower {node!r}")

        sink = lower(dag)
        # Topological order: ops were emitted post-order (inputs first);
        # ensure the sink is last.
        if ops[-1] is not sink:
            ops.remove(sink)
            ops.append(sink)
        return Topology(ops, edges)
