"""streaming_split(n): fan a dataset's output out to n concurrent consumers.

Reference: python/ray/data/dataset.py streaming_split :1236 +
_internal/iterator/stream_split_iterator.py (SplitCoordinator actor :124).

Design here: a SplitCoordinator actor holds per-split queues of block
ObjectRefs; a driver-side thread runs the streaming executor and feeds
finished bundles round-robin (or least-loaded when equal=False) into the
coordinator. Each consumer (e.g. a Train worker) pulls via
``coordinator.get_next(split)`` and fetches blocks from the shared object
store — blocks move driver→worker through shm, never through the actor.
"""

from __future__ import annotations

import threading  # noqa: F401  (also used inside the SplitCoordinator actor)
from typing import List, Optional

import ray_tpu
from ray_tpu.data.block import Block
from ray_tpu.data.iterator import DataIterator


@ray_tpu.remote
class SplitCoordinator:
    """Queues of blocks_refs per split; epoch-aware.

    Refs arrive/leave wrapped in a 1-element list: top-level ObjectRef
    arguments are dereferenced by the runtime (pass-by-value semantics),
    nested ones travel as refs — the blocks themselves never flow through
    this actor.
    """

    def __init__(self, n: int):
        self._n = n
        self._queues: List[list] = [[] for _ in range(n)]
        self._done = [False] * n
        self._lock = threading.Lock()

    def put(self, split: int, wrapped_ref: list):
        with self._lock:
            self._queues[split].append(wrapped_ref[0])

    def finish_epoch(self):
        with self._lock:
            for i in range(self._n):
                self._done[i] = True

    def start_epoch(self):
        with self._lock:
            self._done = [False] * self._n
            self._queues = [[] for _ in range(self._n)]

    def get_next(self, split: int):
        """Returns ([blocks_ref] | None, epoch_done: bool)."""
        with self._lock:
            if self._queues[split]:
                return [self._queues[split].pop(0)], False
            return None, self._done[split]


class StreamSplitDataIterator(DataIterator):
    """One consumer's view of a streaming_split; blocking iterator over the
    coordinator's queue for this split index."""

    def __init__(self, coordinator, split: int):
        self._coord = coordinator
        self._split = split
        super().__init__(self._block_lists)

    def _block_lists(self):
        import time
        while True:
            wrapped, done = ray_tpu.get(
                self._coord.get_next.remote(self._split))
            if wrapped is not None:
                yield ray_tpu.get(wrapped[0])
            elif done:
                return
            else:
                time.sleep(0.005)


def make_stream_split_iterators(dataset, n: int, equal: bool = True
                                ) -> List[StreamSplitDataIterator]:
    """Launch the feeder thread + coordinator; return n iterators.

    Each call starts ONE epoch of execution feeding all n splits; the
    feeder re-executes the dataset for subsequent epochs on demand is NOT
    implemented — Train re-calls per epoch.
    """
    coord = SplitCoordinator.remote(n)
    ray_tpu.get(coord.start_epoch.remote())

    def feed():
        rows_per_split = [0] * n
        rr = 0
        try:
            for bundle in dataset._execute_bundles():
                if equal:
                    # Least-loaded by rows keeps splits balanced.
                    idx = min(range(n), key=lambda i: rows_per_split[i])
                else:
                    idx = rr % n
                    rr += 1
                rows_per_split[idx] += bundle.num_rows
                ray_tpu.get(coord.put.remote(idx, [bundle.blocks_ref]))
        finally:
            ray_tpu.get(coord.finish_epoch.remote())

    t = threading.Thread(target=feed, daemon=True, name="rtpu-split-feeder")
    t.start()
    return [StreamSplitDataIterator(coord, i) for i in range(n)]
