"""streaming_split(n): fan a dataset's output out to n concurrent consumers.

Reference: python/ray/data/dataset.py streaming_split :1236 +
_internal/iterator/stream_split_iterator.py (SplitCoordinator actor :124).

Design here: a SplitCoordinator actor holds per-split queues of block
ObjectRefs; a driver-side feeder thread runs the streaming executor and
distributes finished bundles into the coordinator. Each consumer (e.g. a
Train worker) pulls via ``coordinator.get_next(split, epoch)`` and fetches
blocks from the shared object store — blocks move driver→worker through
shm, never through the actor.

Two guarantees the reference makes that matter for SPMD training:

* **Exactly-equal splits** (``equal=True``): bundles are re-cut at ROW
  granularity so every split receives exactly the same row count each
  epoch (the sub-``n``-row tail is truncated, as the reference does).
  Whole-bundle balancing is not enough — lockstep gangs doing per-batch
  collectives hang if one worker's shard runs dry early.
* **Multi-epoch iteration**: each split iterator can be re-iterated; the
  feeder re-executes the dataset for epoch ``e`` once ALL ``n`` consumers
  have requested epoch ``e`` (a coordinator handshake), mirroring the
  reference's per-epoch pipeline re-execution.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.iterator import DataIterator


@ray_tpu.remote
def _slice_pieces(spec: List[Tuple[int, int, int]], *block_lists
                  ) -> List[Block]:
    """Cut row ranges out of block lists: spec entries are
    (arg_index, start_row, stop_row) into the corresponding block list."""
    out: List[Block] = []
    for arg_i, start, stop in spec:
        blocks = block_lists[arg_i]
        pos = 0
        for b in blocks:
            n = b.num_rows
            lo = max(start - pos, 0)
            hi = min(stop - pos, n)
            if hi > lo:
                if lo == 0 and hi == n:
                    out.append(b)
                else:
                    out.append(BlockAccessor(b).slice(lo, hi))
            pos += n
            if pos >= stop:
                break
    return out


@ray_tpu.remote
class SplitCoordinator:
    """Per-split queues of blocks_refs; epoch-aware with a consumer
    handshake for multi-epoch re-execution.

    Refs arrive/leave wrapped in a 1-element list: top-level ObjectRef
    arguments are dereferenced by the runtime (pass-by-value semantics),
    nested ones travel as refs — the blocks themselves never flow through
    this actor.
    """

    def __init__(self, n: int):
        self._n = n
        self._queues: List[list] = [[] for _ in range(n)]
        self._epoch = -1            # epoch currently being fed (or fed last)
        self._epoch_done = False
        self._requested = [-1] * n  # highest epoch each consumer asked for
        self._error: Optional[str] = None
        self._lock = threading.Lock()

    # -- consumer side --

    def request_epoch(self, split: int, epoch: int):
        with self._lock:
            self._requested[split] = max(self._requested[split], epoch)

    def get_next(self, split: int, epoch: int):
        """Returns ([blocks_ref] | None, epoch_done: bool).

        Raises if the feeder hit a pipeline error — consumers must not
        see a silently truncated epoch as a normal end-of-stream.
        """
        with self._lock:
            if self._error is not None:
                raise RuntimeError(
                    f"streaming_split pipeline failed: {self._error}")
            if self._epoch < epoch:
                return None, False          # epoch not started yet
            if self._epoch > epoch:
                # Stale caller (consumer abandoned this epoch and the
                # feeder moved on): its epoch is over. Never pop — the
                # queue now holds the CURRENT epoch's blocks.
                return None, True
            if self._queues[split]:
                return [self._queues[split].pop(0)], False
            return None, self._epoch_done

    # -- feeder side --

    def ready_epoch(self) -> Optional[int]:
        """Next epoch to feed, or None.

        Epoch 0 starts as soon as ANY consumer asks (queues are empty, so
        there is nothing to wipe) — this keeps sequential / partial
        consumption of splits working. Later epochs wait for ALL
        consumers, because begin_epoch resets queues and a straggler
        still draining epoch e must not lose its blocks.
        """
        with self._lock:
            if self._epoch < 0:
                if max(self._requested) >= 0:
                    return 0
                return None
            if min(self._requested) > self._epoch:
                return self._epoch + 1
            return None

    def begin_epoch(self, epoch: int):
        with self._lock:
            self._epoch = epoch
            self._epoch_done = False
            self._queues = [[] for _ in range(self._n)]

    def put(self, split: int, wrapped_ref: list):
        with self._lock:
            self._queues[split].append(wrapped_ref[0])

    def feed_status(self):
        """(queue sizes, per-consumer requested epochs, current epoch) —
        one locked snapshot for the feeder's backpressure decisions."""
        with self._lock:
            return ([len(q) for q in self._queues],
                    list(self._requested), self._epoch)

    def finish_epoch(self, error: Optional[str] = None):
        with self._lock:
            self._epoch_done = True
            if error is not None:
                self._error = error


class _SplitGroup:
    """Driver-side liveness token shared by one streaming_split's
    iterators. Once any iterator is serialized (shipped to a worker
    process), GC of the driver-side copies no longer implies the split is
    dead — remote consumers still hold it — so the feeder's GC-based
    teardown is disabled and cleanup falls to runtime shutdown killing
    the coordinator actor."""

    def __init__(self):
        self.exported = False


class StreamSplitDataIterator(DataIterator):
    """One consumer's view of a streaming_split; blocking iterator over the
    coordinator's queue for this split index. Re-iterating requests the
    next epoch from the coordinator."""

    def __init__(self, coordinator, split: int, group=None):
        self._coord = coordinator
        self._split = split
        self._epoch = 0
        self._group = group
        super().__init__(self._block_lists)

    def __getstate__(self):
        if self._group is not None:
            self._group.exported = True
        state = dict(self.__dict__)
        state["_group"] = None
        return state

    def _block_lists(self):
        epoch = self._epoch
        self._epoch += 1
        ray_tpu.get(self._coord.request_epoch.remote(self._split, epoch))
        while True:
            wrapped, done = ray_tpu.get(
                self._coord.get_next.remote(self._split, epoch))
            if wrapped is not None:
                yield ray_tpu.get(wrapped[0])
            elif done:
                return
            else:
                time.sleep(0.005)


class _EqualDistributor:
    """Re-cuts the bundle stream at row granularity so each of n splits
    receives exactly ``total_rows // n`` rows (tail truncated)."""

    def __init__(self, coord, n: int):
        self._coord = coord
        self._n = n
        # FIFO of (blocks_ref, start_row, rows_remaining) pieces.
        self._carry: List[Tuple[object, int, int]] = []
        self._avail = 0
        # Splits whose consumer abandoned the epoch: their cuts are
        # discarded (never enqueued) so their queues stay bounded.
        self.abandoned: List[bool] = [False] * n

    def add(self, bundle):
        if bundle.num_rows <= 0:
            return
        self._carry.append((bundle.blocks_ref, 0, bundle.num_rows))
        self._avail += bundle.num_rows
        self._flush()

    def _flush(self):
        n = self._n
        k = self._avail // n
        if k == 0:
            return
        # One contiguous k-row cut per split, consuming the carry FIFO in
        # order (split 0 gets rows [0,k), split 1 [k,2k), ...).
        for split in range(n):
            spec: List[Tuple[int, int, int]] = []
            refs: List[object] = []
            need = k
            while need > 0:
                ref, start, rows = self._carry[0]
                take = min(rows, need)
                refs.append(ref)
                spec.append((len(refs) - 1, start, start + take))
                if take == rows:
                    self._carry.pop(0)
                else:
                    self._carry[0] = (ref, start + take, rows - take)
                need -= take
            if not self.abandoned[split]:
                out_ref = _slice_pieces.remote(spec, *refs)
                ray_tpu.get(self._coord.put.remote(split, [out_ref]))
        self._avail -= k * n

    def finish(self):
        # Truncate the sub-n-row tail (reference behavior) so every split
        # saw exactly the same number of rows this epoch.
        self._carry.clear()
        self._avail = 0


def make_stream_split_iterators(dataset, n: int, equal: bool = True
                                ) -> List[StreamSplitDataIterator]:
    """Launch the feeder thread + coordinator; return n iterators.

    The feeder serves one epoch each time all n consumers have requested
    it (standard multi-epoch loop: ``for epoch in range(E): for batch in
    shard.iter_batches()``), re-executing the dataset pipeline per epoch.
    """
    coord = SplitCoordinator.remote(n)
    max_queued_per_split = 8

    def feed_epoch(epoch: int):
        rr = 0
        dist = _EqualDistributor(coord, n) if equal else None
        for bundle in dataset._execute_bundles():
            # Backpressure: don't run the whole epoch ahead of consumers.
            # Only splits ACTIVELY consuming this epoch (requested ==
            # epoch) count: a consumer that hasn't started yet
            # (requested < epoch, e.g. sequential consumption) must keep
            # receiving — its queue grows, but its blocks have to be
            # retained for it regardless — and one that moved on
            # (requested > epoch) is abandoned; counting either would
            # deadlock the feeder on a queue nobody is draining.
            while True:
                qsizes, requested, _ = ray_tpu.get(
                    coord.feed_status.remote())
                if min(requested) > epoch:
                    return      # everyone moved on: abort this epoch
                live = [q for q, r in zip(qsizes, requested) if r == epoch]
                if not live or max(live) < max_queued_per_split:
                    break
                time.sleep(0.005)
            if equal:
                dist.abandoned = [r > epoch for r in requested]
                dist.add(bundle)
            else:
                if requested[rr % n] <= epoch:
                    ray_tpu.get(coord.put.remote(rr % n,
                                                 [bundle.blocks_ref]))
                rr += 1
        if equal:
            dist.finish()

    def feed_forever():
        while True:
            # All split iterators garbage-collected (and none were ever
            # shipped to a worker process) ⇒ nobody can ever request
            # another epoch: tear down the coordinator and exit instead
            # of leaking a polling thread + actor per streaming_split.
            if not group.exported and all(w() is None for w in iter_refs):
                try:
                    ray_tpu.kill(coord)
                except Exception:
                    pass
                return
            try:
                epoch = ray_tpu.get(coord.ready_epoch.remote())
            except Exception:
                return  # coordinator death / runtime shutdown
            if epoch is None:
                time.sleep(0.05)
                continue
            try:
                ray_tpu.get(coord.begin_epoch.remote(epoch))
                err = None
                try:
                    feed_epoch(epoch)
                except Exception as e:   # noqa: BLE001 — surfaced below
                    err = repr(e)
                ray_tpu.get(coord.finish_epoch.remote(err))
                if err is not None:
                    return  # error latched; consumers will raise
            except Exception:
                return  # coordinator death / runtime shutdown

    group = _SplitGroup()
    iterators = [StreamSplitDataIterator(coord, i, group) for i in range(n)]
    import weakref
    iter_refs = [weakref.ref(it) for it in iterators]
    t = threading.Thread(target=feed_forever, daemon=True,
                         name="rtpu-split-feeder")
    t.start()
    return iterators
