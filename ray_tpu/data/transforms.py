"""Map transform chains: the fused per-task data path.

Reference: python/ray/data/_internal/execution/operators/map_transformer.py —
a MapOperator's work is a chain of transforms applied blocks-in → blocks-out
inside a single task. Fusion = concatenating chains, so a fused
read→map_batches→filter pipeline runs as ONE task per block with no
intermediate materialization.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, Iterator, List, Optional

from ray_tpu.data.block import Block, BlockAccessor


class MapStep:
    def __init__(self, kind: str, fn: Callable, fn_args: tuple = (),
                 fn_kwargs: dict = None, batch_size: Optional[int] = None,
                 batch_format: str = "numpy"):
        self.kind = kind  # 'map_batches' | 'map_rows' | 'flat_map' | 'filter'
        self.fn = fn
        self.fn_args = fn_args
        self.fn_kwargs = fn_kwargs or {}
        self.batch_size = batch_size
        self.batch_format = batch_format


def _iter_batches(blocks: Iterable[Block], batch_size: Optional[int],
                  batch_format: str) -> Iterator[Any]:
    """Re-batch a stream of blocks to ``batch_size`` rows (None = one batch
    per input block), emitting batches in the requested format."""
    if batch_size is None:
        for b in blocks:
            if b.num_rows > 0:
                yield BlockAccessor(b).to_batch(batch_format)
        return
    pending: List[Block] = []
    pending_rows = 0
    for b in blocks:
        if b.num_rows == 0:
            continue
        pending.append(b)
        pending_rows += b.num_rows
        while pending_rows >= batch_size:
            merged = BlockAccessor.concat(pending)
            acc = BlockAccessor(merged)
            yield BlockAccessor(acc.slice(0, batch_size)).to_batch(batch_format)
            rest = acc.slice(batch_size, merged.num_rows)
            pending = [rest] if rest.num_rows else []
            pending_rows = rest.num_rows
    if pending_rows:
        merged = BlockAccessor.concat(pending)
        yield BlockAccessor(merged).to_batch(batch_format)


def _apply_step(step: MapStep, blocks: Iterable[Block]) -> Iterator[Block]:
    if step.kind == "map_batches":
        fn = step.fn
        for batch in _iter_batches(blocks, step.batch_size, step.batch_format):
            out = fn(batch, *step.fn_args, **step.fn_kwargs)
            if not isinstance(out, Iterator) and not hasattr(out, "__next__"):
                out = iter([out])
            for ob in out:
                yield BlockAccessor.batch_to_block(ob)
    elif step.kind == "map_rows":
        fn = step.fn
        for b in blocks:
            rows = [fn(r, *step.fn_args, **step.fn_kwargs)
                    for r in BlockAccessor(b).iter_rows()]
            if rows:
                yield BlockAccessor.rows_to_block(rows)
    elif step.kind == "flat_map":
        fn = step.fn
        for b in blocks:
            rows = list(itertools.chain.from_iterable(
                fn(r, *step.fn_args, **step.fn_kwargs)
                for r in BlockAccessor(b).iter_rows()))
            if rows:
                yield BlockAccessor.rows_to_block(rows)
    elif step.kind == "filter":
        fn = step.fn
        for b in blocks:
            acc = BlockAccessor(b)
            keep = [i for i, r in enumerate(acc.iter_rows())
                    if fn(r, *step.fn_args, **step.fn_kwargs)]
            if keep:
                import numpy as np
                yield acc.take_rows(np.asarray(keep))
    else:
        raise ValueError(f"Unknown map step kind {step.kind!r}")


class MapTransformChain:
    """A serializable pipeline of MapSteps, applied lazily per task.

    Callable-class UDFs (ActorPoolStrategy) are instantiated once per worker
    via ``init_fns``.
    """

    def __init__(self, steps: List[MapStep],
                 target_max_block_size: Optional[int] = None):
        self.steps = list(steps)
        self.target_max_block_size = target_max_block_size

    def fuse(self, other: "MapTransformChain") -> "MapTransformChain":
        return MapTransformChain(self.steps + other.steps,
                                 other.target_max_block_size or
                                 self.target_max_block_size)

    def __call__(self, blocks: Iterable[Block]) -> Iterator[Block]:
        stream: Iterable[Block] = blocks
        for step in self.steps:
            stream = _apply_step(step, stream)
        yield from _shape_output(stream, self.target_max_block_size)


def _shape_output(blocks: Iterable[Block],
                  target_max_block_size: Optional[int]) -> Iterator[Block]:
    """Split oversized output blocks so downstream backpressure has
    reasonable granularity."""
    if not target_max_block_size:
        yield from blocks
        return
    for b in blocks:
        nbytes = b.nbytes
        if nbytes <= target_max_block_size or b.num_rows <= 1:
            yield b
            continue
        n_splits = -(-nbytes // target_max_block_size)
        rows_per = max(1, b.num_rows // n_splits)
        acc = BlockAccessor(b)
        for start in range(0, b.num_rows, rows_per):
            yield acc.slice(start, min(start + rows_per, b.num_rows))
