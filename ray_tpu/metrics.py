"""Metrics: counters/gauges/histograms + Prometheus text exposition.

Reference: python/ray/util/metrics.py (Counter/Gauge/Histogram backed by
opencensus + the dashboard's /metrics endpoint). Here a process-local
registry renders the Prometheus text format, served by a stdlib HTTP
endpoint (start_metrics_server) — scrapeable by any Prometheus.
"""

from __future__ import annotations

import bisect
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple


class _Registry:
    def __init__(self):
        self._metrics: List["Metric"] = []
        self._lock = threading.Lock()

    def register(self, m: "Metric"):
        with self._lock:
            self._metrics.append(m)

    def render(self) -> str:
        with self._lock:
            return "".join(m.render() for m in self._metrics)


REGISTRY = _Registry()


def _fmt_tags(tags: Dict[str, str]) -> str:
    if not tags:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(tags.items()))
    return "{" + inner + "}"


class Metric:
    kind = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self.name = name
        self.description = description
        self._tag_keys = tuple(tag_keys)
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()
        REGISTRY.register(self)

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple[str, ...]:
        tags = tags or {}
        return tuple(str(tags.get(k, "")) for k in self._tag_keys)

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.description}",
                 f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            items = list(self._values.items())
        if not items and not self._tag_keys:
            items = [((), 0.0)]
        for key, v in items:
            tags = dict(zip(self._tag_keys, key))
            lines.append(f"{self.name}{_fmt_tags(tags)} {v}")
        return "\n".join(lines) + "\n"


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None):
        k = self._key(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        with self._lock:
            self._values[self._key(tags)] = float(value)


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] = (0.01, 0.1, 1, 10),
                 tag_keys: Sequence[str] = ()):
        super().__init__(name, description, tag_keys)
        self._bounds = sorted(boundaries)
        self._buckets: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        self._counts: Dict[Tuple[str, ...], int] = {}

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None):
        k = self._key(tags)
        with self._lock:
            b = self._buckets.setdefault(k, [0] * (len(self._bounds) + 1))
            b[bisect.bisect_left(self._bounds, value)] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._counts[k] = self._counts.get(k, 0) + 1

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.description}",
                 f"# TYPE {self.name} histogram"]
        with self._lock:
            for k, buckets in self._buckets.items():
                tags = dict(zip(self._tag_keys, k))
                cum = 0
                for bound, n in zip(self._bounds, buckets):
                    cum += n
                    t = {**tags, "le": str(bound)}
                    lines.append(f"{self.name}_bucket{_fmt_tags(t)} {cum}")
                t = {**tags, "le": "+Inf"}
                lines.append(
                    f"{self.name}_bucket{_fmt_tags(t)} {self._counts[k]}")
                lines.append(f"{self.name}_sum{_fmt_tags(tags)} "
                             f"{self._sums[k]}")
                lines.append(f"{self.name}_count{_fmt_tags(tags)} "
                             f"{self._counts[k]}")
        return "\n".join(lines) + "\n"


class _MetricsHandler(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_GET(self):
        body = REGISTRY.render().encode()
        # core runtime gauges refresh lazily on scrape
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


_server = None


def start_metrics_server(host: str = "127.0.0.1", port: int = 0):
    """Expose REGISTRY at http://host:port/ (Prometheus text format)."""
    global _server
    if _server is None:
        _server = ThreadingHTTPServer((host, port), _MetricsHandler)
        threading.Thread(target=_server.serve_forever, daemon=True,
                         name="metrics-http").start()
    return _server.server_address


def stop_metrics_server():
    global _server
    if _server is not None:
        _server.shutdown()
        _server.server_close()  # release the listening socket now
        _server = None
