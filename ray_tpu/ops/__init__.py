"""TPU compute ops: attention kernels, ring attention, transformer layers."""

from ray_tpu.ops.attention import attention_reference, flash_attention  # noqa: F401
from ray_tpu.ops.layers import (  # noqa: F401
    apply_rope,
    repeat_kv,
    rms_norm,
    rope_frequencies,
    swiglu,
)
from ray_tpu.ops.ring_attention import ring_attention, ring_attention_local  # noqa: F401
from ray_tpu.ops.ulysses import ulysses_attention, ulysses_attention_local  # noqa: F401
