"""Ring attention: exact attention over sequence shards on the "sp" axis.

Long-context substrate (SURVEY §5: the reference ships none — only the
NCCL send/recv primitives a ring could be hand-built from; here it is a
first-class op). Each rank holds 1/n of the sequence; KV blocks rotate
around the ICI ring (ppermute) for n steps while each rank accumulates
online-softmax statistics, so no rank ever materializes more than
[chunk, chunk] scores and the full sequence is never gathered.

Causality uses absolute positions: rank r owns positions
[r*chunk, (r+1)*chunk); a KV block originating at rank j is fully attended
when j < r, causally masked when j == r, fully masked when j > r.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array,
                         axis_name: str, causal: bool = True,
                         sm_scale: Optional[float] = None) -> jax.Array:
    """Ring attention body — call inside shard_map over ``axis_name``.

    q, k, v: local shards [batch, chunk, heads, head_dim] (KV heads may be
    fewer; GQA is applied blockwise). Returns [batch, chunk, heads, head_dim].
    """
    from ray_tpu.ops.layers import repeat_kv

    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    from ray_tpu.parallel.device_collectives import axis_size

    n = axis_size(axis_name)
    my_rank = jax.lax.axis_index(axis_name)
    b, chunk, h, d = q.shape
    n_rep = h // k.shape[2]

    qf = q.astype(jnp.float32) * sm_scale
    q_pos = my_rank * chunk + jnp.arange(chunk)

    def step(i, carry):
        acc, m, l, k_cur, v_cur = carry
        # The block currently held arrived from `i` hops upstream.
        src_rank = (my_rank - i) % n
        k_rep = repeat_kv(k_cur, n_rep).astype(jnp.float32)
        v_rep = repeat_kv(v_cur, n_rep).astype(jnp.float32)
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_rep,
                       preferred_element_type=jnp.float32)
        if causal:
            k_pos = src_rank * chunk + jnp.arange(chunk)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_rep, preferred_element_type=jnp.float32
        )
        # rotate kv to the next rank (one ICI hop)
        perm = [(r, (r + 1) % n) for r in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return acc_new, m_new, l_new, k_nxt, v_nxt

    # pvary marks the fresh accumulators as varying over the ring axis so the
    # fori_loop carry types match (outputs depend on axis_index); jax < 0.6
    # has no varying-axes typing, so the identity is the correct no-op there.
    pvary = getattr(jax.lax, "pvary", lambda x, _: x)
    acc0 = pvary(jnp.zeros((b, h, chunk, d), jnp.float32), axis_name)
    m0 = pvary(
        jnp.full((b, h, chunk, 1), _NEG_INF, jnp.float32), axis_name)
    l0 = pvary(jnp.zeros((b, h, chunk, 1), jnp.float32), axis_name)
    acc, m, l, _, _ = jax.lax.fori_loop(0, n, step, (acc0, m0, l0, k, v))
    out = acc / jnp.maximum(l, 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh,
                   axis_name: str = "sp", causal: bool = True,
                   sm_scale: Optional[float] = None) -> jax.Array:
    """Global-array entry: q/k/v [batch, seq, heads, head_dim] with seq
    sharded over ``axis_name``; returns the same layout."""
    try:
        from jax import shard_map
    except ImportError:  # jax < 0.5: public alias not exported yet
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis_name, None, None)
    f = shard_map(
        partial(ring_attention_local, axis_name=axis_name, causal=causal,
                sm_scale=sm_scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    return f(q, k, v)
