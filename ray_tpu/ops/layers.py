"""Transformer building blocks: RMSNorm, RoPE, SwiGLU.

Pure-jax implementations — XLA fuses these elementwise chains into the
surrounding matmuls on TPU (the guide's rule: don't hand-schedule what the
compiler already fuses). Pallas is reserved for ops XLA can't fuse well
(attention — see ops/attention.py).

The reference framework has no kernel library (it delegates to torch); these
ops underpin the model zoo (models/llama.py etc.).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm in fp32 accumulation, cast back to input dtype."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(dtype)


def rope_frequencies(head_dim: int, max_seq_len: int, theta: float = 10_000.0,
                     dtype=jnp.float32, scaling: Optional[dict] = None
                     ) -> Tuple[jax.Array, jax.Array]:
    """Precompute RoPE cos/sin tables: [max_seq_len, head_dim//2].

    ``scaling``: optional Llama-3.x long-context frequency scaling (the
    HF ``rope_scaling`` dict with rope_type="llama3"): low-frequency
    components are divided by ``factor`` (stretching their period to the
    extended context), high-frequency components are untouched, and the
    band between ``low_freq_factor`` and ``high_freq_factor`` wavelengths
    interpolates smoothly — matching transformers'
    modeling_rope_utils._compute_llama3_parameters.
    """
    import math

    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    attention_factor = 1.0
    if scaling:
        rope_type = scaling.get("rope_type") or scaling.get("type")
        if rope_type == "llama3":
            factor = float(scaling["factor"])
            low = float(scaling.get("low_freq_factor", 1.0))
            high = float(scaling.get("high_freq_factor", 4.0))
            old_len = float(scaling.get(
                "original_max_position_embeddings", 8192))
            wavelen = 2.0 * jnp.pi / inv_freq
            # short wavelengths (high freq): keep; long wavelengths (low
            # freq): divide by factor; the band between interpolates
            smooth = (old_len / wavelen - low) / (high - low)
            scaled = ((1.0 - smooth) * (inv_freq / factor)
                      + smooth * inv_freq)
            inv_freq = jnp.where(
                wavelen < old_len / high, inv_freq,
                jnp.where(wavelen > old_len / low, inv_freq / factor,
                          scaled))
        elif rope_type == "linear":
            # position interpolation (transformers
            # _compute_linear_scaling_rope): all frequencies divide by
            # the factor
            inv_freq = inv_freq / float(scaling["factor"])
        elif rope_type == "yarn":
            # NTK-by-parts (YaRN, arXiv:2309.00071) — mirrors
            # transformers' _compute_yarn_parameters exactly: low-freq
            # dims interpolate (1/factor), high-freq dims extrapolate
            # (untouched), a linear ramp blends between, and the cos/sin
            # tables scale by the attention factor (mscale).
            factor = float(scaling["factor"])
            beta_fast = float(scaling.get("beta_fast") or 32)
            beta_slow = float(scaling.get("beta_slow") or 1)
            old_len = float(
                scaling.get("original_max_position_embeddings")
                or max_seq_len)
            mscale = scaling.get("mscale")
            mscale_all_dim = scaling.get("mscale_all_dim")

            def get_mscale(scale, ms=1.0):
                if scale <= 1:
                    return 1.0
                return 0.1 * ms * math.log(scale) + 1.0

            attention_factor = scaling.get("attention_factor")
            if attention_factor is None:
                if mscale and mscale_all_dim:
                    attention_factor = float(
                        get_mscale(factor, mscale)
                        / get_mscale(factor, mscale_all_dim))
                else:
                    attention_factor = get_mscale(factor)

            def correction_dim(num_rotations):
                return (head_dim * math.log(
                    old_len / (num_rotations * 2 * math.pi))
                    ) / (2 * math.log(theta))

            low = correction_dim(beta_fast)
            high = correction_dim(beta_slow)
            if scaling.get("truncate", True):
                low, high = math.floor(low), math.ceil(high)
            low, high = max(low, 0), min(high, head_dim - 1)
            if low == high:
                high += 0.001  # prevent singularity
            ramp = jnp.clip(
                (jnp.arange(head_dim // 2, dtype=jnp.float32) - low)
                / (high - low), 0.0, 1.0)
            extrapolation_factor = 1.0 - ramp
            inv_freq = ((inv_freq / factor)
                        * (1.0 - extrapolation_factor)
                        + inv_freq * extrapolation_factor)
        else:
            raise ValueError(
                f"unsupported rope_scaling type {rope_type!r} "
                f"(implemented: 'llama3', 'linear', 'yarn')")
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    # attention factor (yarn mscale) scales the tables in float32 first,
    # like transformers' cos() * attention_scaling before the cast
    return ((jnp.cos(freqs) * attention_factor).astype(dtype),
            (jnp.sin(freqs) * attention_factor).astype(dtype))


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array,
               positions: Optional[jax.Array] = None) -> jax.Array:
    """Apply rotary embeddings.

    x: [..., seq, heads, head_dim]; cos/sin: [max_seq, head_dim//2];
    positions: [..., seq] absolute positions (defaults to arange).
    """
    seq = x.shape[-3]
    if positions is None:
        c = cos[:seq][:, None, :]
        s = sin[:seq][:, None, :]
    else:
        c = cos[positions][..., None, :]
        s = sin[positions][..., None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    cf = c.astype(jnp.float32)
    sf = s.astype(jnp.float32)
    x1f, x2f = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out = jnp.concatenate(
        [x1f * cf - x2f * sf, x2f * cf + x1f * sf], axis=-1
    )
    return out.astype(x.dtype)


def swiglu(x: jax.Array, w_gate: jax.Array, w_up: jax.Array,
           w_down: jax.Array, act: str = "silu") -> jax.Array:
    """Gated MLP: down( act(x @ gate) * (x @ up) ).

    ``act`` selects the gate nonlinearity: "silu" (llama's SwiGLU),
    "gelu_tanh" (gemma's GeGLU — HF hidden_act gelu_pytorch_tanh), or
    "gelu" (exact erf GELU). Unknown names raise — a typo'd activation
    must not silently train the wrong model.
    All matmuls in input dtype (bf16 on TPU) with fp32 accumulation via
    preferred_element_type.
    """
    try:
        act_fn = {"silu": jax.nn.silu,
                  "gelu_tanh": partial(jax.nn.gelu, approximate=True),
                  "gelu": partial(jax.nn.gelu, approximate=False)}[act]
    except KeyError:
        raise ValueError(f"unknown gated-MLP activation {act!r} "
                         "(silu | gelu_tanh | gelu)") from None
    # accumulate in f32 INSIDE the dot, but store the [b, s, ffn]
    # intermediates in the input dtype: keeping gate/up in f32 doubled
    # the MLP's HBM activation traffic and measured ~7% of the whole
    # 1B train step on v5e (profile: three f32[8,2048,5504] fusions per
    # layer). The activation itself is bounded, so bf16 is safe — and
    # XLA folds the convert into the matmul epilogue.
    gate = jnp.dot(x, w_gate,
                   preferred_element_type=jnp.float32).astype(x.dtype)
    up = jnp.dot(x, w_up,
                 preferred_element_type=jnp.float32).astype(x.dtype)
    h = act_fn(gate) * up
    return jnp.dot(h, w_down, preferred_element_type=jnp.float32).astype(x.dtype)


def repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """Expand KV heads for grouped-query attention.

    x: [batch, seq, kv_heads, head_dim] → [batch, seq, kv_heads*n_rep, hd].
    """
    if n_rep == 1:
        return x
    b, s, h, d = x.shape
    return jnp.broadcast_to(
        x[:, :, :, None, :], (b, s, h, n_rep, d)
    ).reshape(b, s, h * n_rep, d)
