"""Transformer building blocks: RMSNorm, RoPE, SwiGLU.

Pure-jax implementations — XLA fuses these elementwise chains into the
surrounding matmuls on TPU (the guide's rule: don't hand-schedule what the
compiler already fuses). Pallas is reserved for ops XLA can't fuse well
(attention — see ops/attention.py).

The reference framework has no kernel library (it delegates to torch); these
ops underpin the model zoo (models/llama.py etc.).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm in fp32 accumulation, cast back to input dtype."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(dtype)


def rope_frequencies(head_dim: int, max_seq_len: int, theta: float = 10_000.0,
                     dtype=jnp.float32, scaling: Optional[dict] = None
                     ) -> Tuple[jax.Array, jax.Array]:
    """Precompute RoPE cos/sin tables: [max_seq_len, head_dim//2].

    ``scaling``: optional Llama-3.x long-context frequency scaling (the
    HF ``rope_scaling`` dict with rope_type="llama3"): low-frequency
    components are divided by ``factor`` (stretching their period to the
    extended context), high-frequency components are untouched, and the
    band between ``low_freq_factor`` and ``high_freq_factor`` wavelengths
    interpolates smoothly — matching transformers'
    modeling_rope_utils._compute_llama3_parameters.
    """
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    if scaling:
        rope_type = scaling.get("rope_type") or scaling.get("type")
        if rope_type != "llama3":
            raise ValueError(
                f"unsupported rope_scaling type {rope_type!r} "
                f"(only 'llama3' is implemented)")
        factor = float(scaling["factor"])
        low = float(scaling.get("low_freq_factor", 1.0))
        high = float(scaling.get("high_freq_factor", 4.0))
        old_len = float(scaling.get(
            "original_max_position_embeddings", 8192))
        wavelen = 2.0 * jnp.pi / inv_freq
        # short wavelengths (high freq): keep; long wavelengths (low
        # freq): divide by factor; the band between interpolates
        smooth = (old_len / wavelen - low) / (high - low)
        scaled = (1.0 - smooth) * (inv_freq / factor) + smooth * inv_freq
        inv_freq = jnp.where(
            wavelen < old_len / high, inv_freq,
            jnp.where(wavelen > old_len / low, inv_freq / factor, scaled))
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array,
               positions: Optional[jax.Array] = None) -> jax.Array:
    """Apply rotary embeddings.

    x: [..., seq, heads, head_dim]; cos/sin: [max_seq, head_dim//2];
    positions: [..., seq] absolute positions (defaults to arange).
    """
    seq = x.shape[-3]
    if positions is None:
        c = cos[:seq][:, None, :]
        s = sin[:seq][:, None, :]
    else:
        c = cos[positions][..., None, :]
        s = sin[positions][..., None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    cf = c.astype(jnp.float32)
    sf = s.astype(jnp.float32)
    x1f, x2f = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out = jnp.concatenate(
        [x1f * cf - x2f * sf, x2f * cf + x1f * sf], axis=-1
    )
    return out.astype(x.dtype)


def swiglu(x: jax.Array, w_gate: jax.Array, w_up: jax.Array,
           w_down: jax.Array) -> jax.Array:
    """SwiGLU MLP: down( silu(x @ gate) * (x @ up) ).

    All matmuls in input dtype (bf16 on TPU) with fp32 accumulation via
    preferred_element_type.
    """
    gate = jnp.dot(x, w_gate, preferred_element_type=jnp.float32)
    up = jnp.dot(x, w_up, preferred_element_type=jnp.float32)
    h = (jax.nn.silu(gate) * up).astype(x.dtype)
    return jnp.dot(h, w_down, preferred_element_type=jnp.float32).astype(x.dtype)


def repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """Expand KV heads for grouped-query attention.

    x: [batch, seq, kv_heads, head_dim] → [batch, seq, kv_heads*n_rep, hd].
    """
    if n_rep == 1:
        return x
    b, s, h, d = x.shape
    return jnp.broadcast_to(
        x[:, :, :, None, :], (b, s, h, n_rep, d)
    ).reshape(b, s, h * n_rep, d)
