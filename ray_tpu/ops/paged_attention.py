"""Paged-KV attention for serving decode: Pallas page-gather kernel.

vLLM-style paged KV re-thought for TPU (reference serves via torch/GPU
with no paging of its own; the vLLM PagedAttention paper is the public
analogue): the KV cache is a POOL of fixed-size pages ``[num_pages,
kv_heads, page_size, head_dim]`` shared by all sequences; each sequence
owns an ordered list of page ids (its block table). Decode attention for
slot s must read exactly s's pages — a data-dependent gather.

The XLA path (``paged_attention_reference``) materializes the gather:
pages → a dense [S, T] view → einsum. Correct everywhere (CPU,
GSPMD/tensor-parallel), but it writes the gathered copy to HBM before
reading it back — extra cache traffic the dense engine never pays.

The Pallas kernel streams pages straight from HBM into VMEM through the
BlockSpec pipeline: the grid walks (slot, kv_head, page), the page index
map reads the SCALAR-PREFETCHED block table, and an online-softmax
accumulator (flash-style m/l/acc scratch) folds each page as it arrives —
the gathered tensor never exists. Pages past a slot's context length are
clamped to the last valid page in the index map (no re-DMA: Pallas skips
the copy when consecutive grid steps map to the same block) and skipped
by ``pl.when``. The pool layout [P, KVH, page, hd] keeps (page, hd) as
the block's minor dims — the TPU tiling requirement (minor dims ÷(8,128)).

Both paths compute HISTORY attention only (positions < ctx_len); the
in-flight token's self-attention term is merged by the caller
(models/llama_paged.py) from the returned (acc, m, l) triple, mirroring
the dense decode design.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def paged_attention_reference(q: jax.Array, k_pages: jax.Array,
                              v_pages: jax.Array, block_table: jax.Array,
                              ctx_len: jax.Array,
                              sm_scale: Optional[float] = None):
    """History attention over paged KV, XLA gather path.

    q: [S, KVH, G, hd] (G = query heads per KV head, rope applied)
    k_pages/v_pages: [P, KVH, page, hd]
    block_table: [S, MAXP] int32 page ids (entries past a sequence's
        allocation may be arbitrary valid ids — they are masked)
    ctx_len: [S] int32 history length in tokens (EXCLUDING the in-flight
        token). Slots with ctx_len == 0 return zeros.
    Returns (acc f32 [S, KVH, G, hd], m [S, KVH, G], l [S, KVH, G]):
    the flash-style UN-normalized accumulator, row max, and softmax
    denominator over history only, so the caller can merge the in-flight
    token's self term exactly before normalizing.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    S, KVH, G, hd = q.shape
    page = k_pages.shape[2]
    MAXP = block_table.shape[1]
    T = MAXP * page
    # [S, MAXP, KVH, page, hd] -> [S, KVH, T, hd]
    ks = jnp.moveaxis(k_pages[block_table], 2, 1).reshape(S, KVH, T, hd)
    vs = jnp.moveaxis(v_pages[block_table], 2, 1).reshape(S, KVH, T, hd)
    scores = jnp.einsum("skgd,sktd->skgt", q, ks,
                        preferred_element_type=jnp.float32) * sm_scale
    mask = jnp.arange(T)[None] < ctx_len[:, None]          # [S, T]
    scores = jnp.where(mask[:, None, None], scores, _NEG_INF)
    m = jnp.max(scores, axis=-1)                           # [S, KVH, G]
    # all-masked rows (ctx 0): exp(-1e30 - -1e30) would be 1 — zero them
    p = jnp.exp(scores - m[..., None])
    p = jnp.where(mask[:, None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)                                # [S, KVH, G]
    acc = jnp.einsum("skgt,sktd->skgd", p.astype(vs.dtype), vs,
                     preferred_element_type=jnp.float32)
    return acc, m, l


def _paged_kernel(bt_ref, ctx_ref, q_ref, k_ref, v_ref,
                  o_ref, m_ref, l_ref, acc_ref, mm_ref, ll_ref, *,
                  page: int, maxp: int, kvh: int, sm_scale: float):
    """Grid (S, MAXP); scratch acc [KVH*G, hd] f32, mm/ll [KVH*G, 1].

    q_ref: [1, KVH, G, hd]; k_ref/v_ref: [1, KVH, page, hd] — one whole
    page across ALL kv heads per step (一 ~512 KB DMA instead of KVH
    small ones; the per-head grid variant measured 30% slower at 1B).
    The KVH loop below is a python unroll over static slices.
    Outputs (written at the final page step): o [1,KVH,G,hd]
    un-normalized accumulator, m/l [1,KVH,G,1] row max and denominator.
    """
    import jax.experimental.pallas as pl

    s = pl.program_id(0)
    p = pl.program_id(1)
    ctx = ctx_ref[s]
    G = q_ref.shape[2]

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        mm_ref[...] = jnp.full_like(mm_ref, _NEG_INF)
        ll_ref[...] = jnp.zeros_like(ll_ref)

    @pl.when(p * page < ctx)
    def _compute():
        pos = p * page + jax.lax.broadcasted_iota(
            jnp.int32, (G, page), 1)
        valid = pos < ctx
        for h in range(kvh):
            q = q_ref[0, h].astype(jnp.float32)            # [G, hd]
            k = k_ref[0, h].astype(jnp.float32)            # [page, hd]
            v = v_ref[0, h].astype(jnp.float32)            # [page, hd]
            s_blk = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale
            s_blk = jnp.where(valid, s_blk, _NEG_INF)      # [G, page]
            row = slice(h * G, (h + 1) * G)
            m_old = mm_ref[row, :]
            m_new = jnp.maximum(m_old,
                                jnp.max(s_blk, axis=-1, keepdims=True))
            pr = jnp.exp(s_blk - m_new)
            pr = jnp.where(valid, pr, 0.0)
            alpha = jnp.exp(m_old - m_new)
            ll_ref[row, :] = ll_ref[row, :] * alpha + jnp.sum(
                pr, axis=-1, keepdims=True)
            acc_ref[row, :] = acc_ref[row, :] * alpha + jax.lax.dot_general(
                pr, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            mm_ref[row, :] = m_new

    @pl.when(p == maxp - 1)
    def _finalize():
        o_ref[0] = acc_ref[...].reshape(kvh, G, -1).astype(o_ref.dtype)
        m_ref[0] = mm_ref[...].reshape(kvh, G, 1)
        l_ref[0] = ll_ref[...].reshape(kvh, G, 1)


def paged_attention(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                    block_table: jax.Array, ctx_len: jax.Array,
                    sm_scale: Optional[float] = None,
                    interpret: bool = False):
    """Pallas page-gather history attention (see module docstring).

    Shapes as paged_attention_reference; returns the same
    (acc f32 [S, KVH, G, hd], m [S, KVH, G], l [S, KVH, G]) triple.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    S, KVH, G, hd = q.shape
    page = k_pages.shape[2]
    MAXP = block_table.shape[1]

    def q_map(s, p, bt, ctx):
        return (s, 0, 0, 0)

    def kv_map(s, p, bt, ctx):
        # clamp trailing pages to the last valid one: consecutive grid
        # steps with the same index skip the DMA, and pl.when skips the
        # compute, so fully-padded tables cost (almost) nothing
        last = jnp.maximum(ctx[s] - 1, 0) // page
        return (bt[s, jnp.minimum(p, last)], 0, 0, 0)

    kernel = functools.partial(_paged_kernel, page=page, maxp=MAXP,
                               kvh=KVH, sm_scale=sm_scale)
    out, m, l = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(S, MAXP),
            in_specs=[
                pl.BlockSpec((1, KVH, G, hd), q_map),
                pl.BlockSpec((1, KVH, page, hd), kv_map),
                pl.BlockSpec((1, KVH, page, hd), kv_map),
            ],
            out_specs=[
                pl.BlockSpec((1, KVH, G, hd), q_map),
                pl.BlockSpec((1, KVH, G, 1),
                             lambda s, p, bt, ctx: (s, 0, 0, 0)),
                pl.BlockSpec((1, KVH, G, 1),
                             lambda s, p, bt, ctx: (s, 0, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((KVH * G, hd), jnp.float32),
                pltpu.VMEM((KVH * G, 1), jnp.float32),
                pltpu.VMEM((KVH * G, 1), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((S, KVH, G, hd), jnp.float32),
            jax.ShapeDtypeStruct((S, KVH, G, 1), jnp.float32),
            jax.ShapeDtypeStruct((S, KVH, G, 1), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("parallel", "arbitrary")),
    )(block_table, ctx_len, q, k_pages, v_pages)
    return out, m[..., 0], l[..., 0]
