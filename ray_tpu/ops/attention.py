"""Attention: reference jax implementation + Pallas flash-attention kernels.

The Pallas kernels are the TPU hot path: blocked online-softmax attention
that never materializes the [seq, seq] score matrix in HBM (VMEM-resident
tiles, MXU matmuls, fp32 accumulation). Grouped-query attention is supported
by mapping each query head to its KV group via the BlockSpec index maps.

Training uses ``flash_attention`` through a custom_vjp with FlashAttention-2
style Pallas *backward* kernels: the forward saves only O and the per-row
logsumexp; backward recomputes score tiles in VMEM and accumulates dQ in a
query-block kernel and dK/dV in a key-block kernel (per query head, reduced
over the GQA group outside). The reference ships no flash kernels at all
(SURVEY §5: NCCL/GPU paths only) — numerics oracle is ``attention_reference``
below.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.layers import repeat_kv

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
_NEG_INF = -1e30


def _auto_block(seq: int, target: int) -> int:
    """Largest power-of-two block <= target that divides seq (measured on
    v5e: 512 blocks are ~2-3x faster than 128 at long seq — MXU stays fed
    and the online-softmax VPU work amortizes)."""
    c = target
    while c > 128:
        if seq % c == 0:
            return c
        c //= 2
    return c


def attention_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True,
                        sm_scale: Optional[float] = None) -> jax.Array:
    """Plain softmax attention (fp32 softmax), GQA-aware.

    q: [batch, seq_q, heads, head_dim]
    k, v: [batch, seq_k, kv_heads, head_dim]
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    n_rep = q.shape[2] // k.shape[2]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    # [b, h, sq, sk]
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * sm_scale
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        qi = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        scores = jnp.where(qi + (sk - sq) >= ki, scores, _NEG_INF)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


# ---------------------------------------------------------------- pallas fwd


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                  causal: bool, sm_scale: float, seq_k: int, block_q: int,
                  causal_offset: int = 0):
    # q_ref: [1, block_q, d]; k_ref/v_ref: [1, seq_k, d]; o_ref: [1, block_q, d]
    # lse_ref: [1, block_q] per-row logsumexp of the scaled scores (the only
    # extra forward state the FA-2 backward needs).
    # causal_offset = seq_k - seq_q: query row i sits at absolute key
    # position offset + i (decode/chunked-prefill alignment, matching
    # attention_reference).
    import jax.experimental.pallas as pl

    qb = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale          # [block_q, d]
    d = q.shape[-1]

    num_kv_blocks = seq_k // block_k
    if causal:
        # only blocks whose start is <= the last query's absolute position
        last_q = causal_offset + (qb + 1) * block_q - 1

    def body(kb, carry):
        acc, m, l = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        if causal:
            qi = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            ki = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = (causal_offset + qb * block_q + qi) >= (kb * block_k + ki)
            s = jnp.where(mask, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    init = (
        jnp.zeros((block_q, d), jnp.float32),
        jnp.full((block_q, 1), _NEG_INF, jnp.float32),
        jnp.zeros((block_q, 1), jnp.float32),
    )
    if causal:
        upper = jax.lax.div(last_q, block_k) + 1
    else:
        upper = num_kv_blocks
    acc, m, l = jax.lax.fori_loop(0, upper, body, init)
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)  # [block_q, 1]


def _check_blocks(sq, sk, block_q, block_k):
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"seq lengths ({sq}, {sk}) must be divisible by blocks "
            f"({block_q}, {block_k}); pad inputs first"
        )
    return block_q, block_k


def _flash_forward(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    """q: [b, sq, h, d]; k/v: [b, sk, kvh, d] → ([b, sq, h, d], lse[b*h, sq, 1]).

    The logsumexp rides in a trailing singleton lane dim — TPU block shapes
    need the last dim divisible by 128 *or* equal to the array dim, and a
    1-lane column costs 128x less HBM than broadcasting to MIN_BLOCK_SIZE
    lanes the way jax's in-tree kernel stores l/m."""
    import jax.experimental.pallas as pl

    b, sq, h, d = q.shape
    _, sk, kvh, _ = k.shape
    group = h // kvh
    block_q, block_k = _check_blocks(sq, sk, block_q, block_k)

    # [b*h, s, d] layout for the kernel
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * kvh, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * kvh, sk, d)

    def q_map(i, qb):
        return (i, qb, 0)

    def kv_map(i, qb):
        batch = i // h
        head = i % h
        return (batch * kvh + head // group, 0, 0)

    kernel = functools.partial(
        _flash_kernel, block_k=block_k, causal=causal, sm_scale=sm_scale,
        seq_k=sk, block_q=block_q, causal_offset=sk - sq,
    )
    out, lse = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
        ],
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, sk, d), kv_map),
            pl.BlockSpec((1, sk, d), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, block_q, 1), q_map),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3), lse


# ---------------------------------------------------------------- pallas bwd
#
# FlashAttention-2 split backward: a query-block kernel for dQ and a
# key-block kernel for dK/dV, both recomputing P = exp(S - lse) tile by tile
# in VMEM. delta = rowsum(dO ⊙ O) is a cheap fused elementwise reduction
# left to XLA outside the kernels.


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k: int, causal: bool,
                         sm_scale: float, seq_k: int, block_q: int,
                         causal_offset: int):
    import jax.experimental.pallas as pl

    qb = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)                     # [block_q, d]
    do = do_ref[0].astype(jnp.float32)                   # [block_q, d]
    lse = lse_ref[0]                                     # [block_q, 1]
    delta = delta_ref[0]                                 # [block_q, 1]
    d = q.shape[-1]

    num_kv_blocks = seq_k // block_k
    if causal:
        last_q = causal_offset + (qb + 1) * block_q - 1

    def body(kb, dq):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale                                     # [block_q, block_k]
        if causal:
            qi = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            ki = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = (causal_offset + qb * block_q + qi) >= (kb * block_k + ki)
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse)                             # [block_q, block_k]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                # [block_q, block_k]
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    upper = jax.lax.div(last_q, block_k) + 1 if causal else num_kv_blocks
    dq = jax.lax.fori_loop(
        0, upper, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = (dq * sm_scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, causal: bool,
                          sm_scale: float, seq_q: int, block_k: int,
                          causal_offset: int):
    import jax.experimental.pallas as pl

    kb = pl.program_id(1)
    k_blk = k_ref[0].astype(jnp.float32)                 # [block_k, d]
    v_blk = v_ref[0].astype(jnp.float32)                 # [block_k, d]
    d = k_blk.shape[-1]

    num_q_blocks = seq_q // block_q

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qb * block_q, block_q), :]   # [block_q, 1]
        delta = delta_ref[0, pl.ds(qb * block_q, block_q), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale                                     # [block_q, block_k]
        if causal:
            qi = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            ki = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = (causal_offset + qb * block_q + qi) >= (kb * block_k + ki)
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse)                             # [block_q, block_k]
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                # [block_k, d]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                # [block_q, block_k]
        ds = p * (dp - delta)
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                # [block_k, d]
        return dk_new, dv_new

    if causal:
        # first q row that can see this key block: qrow >= k_start - offset
        lower = jnp.maximum(
            0, jax.lax.div(kb * block_k - causal_offset, block_q))
    else:
        lower = 0
    dk, dv = jax.lax.fori_loop(
        lower, num_q_blocks, body,
        (jnp.zeros((block_k, d), jnp.float32),
         jnp.zeros((block_k, d), jnp.float32)))
    dk_ref[0] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, sm_scale, block_q, block_k,
                    interpret):
    import jax.experimental.pallas as pl

    b, sq, h, d = q.shape
    _, sk, kvh, _ = k.shape
    group = h // kvh
    block_q, block_k = _check_blocks(sq, sk, block_q, block_k)

    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * kvh, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * kvh, sk, d)
    dot = g.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    ot = out.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    # delta_i = dO_i · O_i  (rowwise), the softmax-jacobian correction term.
    delta = jnp.sum(dot.astype(jnp.float32) * ot.astype(jnp.float32),
                    axis=-1, keepdims=True)              # [b*h, sq, 1]

    def q_map(i, qb):
        return (i, qb, 0)

    def kv_map(i, qb):
        batch = i // h
        head = i % h
        return (batch * kvh + head // group, 0, 0)

    def full_q_map(i, kb):
        return (i, 0, 0)

    def k_map(i, kb):
        batch = i // h
        head = i % h
        return (batch * kvh + head // group, kb, 0)

    causal_offset = sk - sq
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, block_k=block_k, causal=causal,
            sm_scale=sm_scale, seq_k=sk, block_q=block_q,
            causal_offset=causal_offset),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, sk, d), kv_map),
            pl.BlockSpec((1, sk, d), kv_map),
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, block_q, 1), q_map),
            pl.BlockSpec((1, block_q, 1), q_map),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), q_map),
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    # dK/dV are computed per *query* head (grid over b*h) and reduced over
    # the GQA group afterwards — the group sum is a cheap XLA reduction and
    # keeps the kernel free of cross-program accumulation.
    dk_per, dv_per = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, block_q=block_q, causal=causal,
            sm_scale=sm_scale, seq_q=sq, block_k=block_k,
            causal_offset=causal_offset),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ],
        grid=(b * h, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, sq, d), full_q_map),
            pl.BlockSpec((1, block_k, d), k_map),
            pl.BlockSpec((1, block_k, d), k_map),
            pl.BlockSpec((1, sq, d), full_q_map),
            pl.BlockSpec((1, sq, 1), full_q_map),
            pl.BlockSpec((1, sq, 1), full_q_map),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, kb: (i, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, kb: (i, kb, 0)),
        ],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    dq = dq.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    # Sum query heads within each KV group: head = kv*group + g.
    dk = dk_per.reshape(b, kvh, group, sk, d).sum(axis=2)
    dv = dv_per.reshape(b, kvh, group, sk, d).sum(axis=2)
    dk = dk.transpose(0, 2, 1, 3).astype(k.dtype)
    dv = dv.transpose(0, 2, 1, 3).astype(v.dtype)
    return dq.astype(q.dtype), dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, _ = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                            interpret)
    return out


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                              interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, g, causal, sm_scale, block_q,
                           block_k, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, sm_scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    use_pallas: Optional[bool] = None,
                    interpret: bool = False) -> jax.Array:
    """Flash attention. Layout: q [b, sq, heads, d]; k/v [b, sk, kv_heads, d].

    ``use_pallas=None`` auto-selects: the Pallas kernel on TPU backends, the
    reference path elsewhere (tests force the kernel with interpret=True).
    ``block_q``/``block_k`` default to the largest power-of-two divisor of
    the sequence length up to 512.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if use_pallas is None:
        use_pallas = jax.default_backend() not in ("cpu",)
    if not use_pallas:
        return attention_reference(q, k, v, causal, sm_scale)
    if block_q is None:
        block_q = _auto_block(q.shape[1], DEFAULT_BLOCK_Q)
    if block_k is None:
        block_k = _auto_block(k.shape[1], DEFAULT_BLOCK_K)
    return _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret)
