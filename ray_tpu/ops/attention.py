"""Attention: reference jax implementation + Pallas flash-attention kernel.

The Pallas kernel is the TPU hot path: blocked online-softmax attention that
never materializes the [seq, seq] score matrix in HBM (VMEM-resident tiles,
MXU matmuls, fp32 accumulation). Grouped-query attention is supported by
mapping each query head to its KV group via the BlockSpec index maps.

Training uses ``flash_attention`` through a custom_vjp whose backward pass
recomputes attention with the reference implementation (flash backward
kernel is a follow-up; ring attention chunks the sequence for long-context
training so the recompute stays bounded).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.layers import repeat_kv

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


def attention_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True,
                        sm_scale: Optional[float] = None) -> jax.Array:
    """Plain softmax attention (fp32 softmax), GQA-aware.

    q: [batch, seq_q, heads, head_dim]
    k, v: [batch, seq_k, kv_heads, head_dim]
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    n_rep = q.shape[2] // k.shape[2]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    # [b, h, sq, sk]
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * sm_scale
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        qi = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        scores = jnp.where(qi + (sk - sq) >= ki, scores, _NEG_INF)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


# ---------------------------------------------------------------- pallas fwd


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  sm_scale: float, seq_k: int, block_q: int,
                  causal_offset: int = 0):
    # q_ref: [1, block_q, d]; k_ref/v_ref: [1, seq_k, d]; o_ref: [1, block_q, d]
    # causal_offset = seq_k - seq_q: query row i sits at absolute key
    # position offset + i (decode/chunked-prefill alignment, matching
    # attention_reference).
    import jax.experimental.pallas as pl

    qb = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale          # [block_q, d]
    d = q.shape[-1]

    num_kv_blocks = seq_k // block_k
    if causal:
        # only blocks whose start is <= the last query's absolute position
        last_q = causal_offset + (qb + 1) * block_q - 1

    def body(kb, carry):
        acc, m, l = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        if causal:
            qi = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            ki = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = (causal_offset + qb * block_q + qi) >= (kb * block_k + ki)
            s = jnp.where(mask, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    init = (
        jnp.zeros((block_q, d), jnp.float32),
        jnp.full((block_q, 1), _NEG_INF, jnp.float32),
        jnp.zeros((block_q, 1), jnp.float32),
    )
    if causal:
        upper = jax.lax.div(last_q, block_k) + 1
    else:
        upper = num_kv_blocks
    acc, m, l = jax.lax.fori_loop(0, upper, body, init)
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    """q: [b, sq, h, d]; k/v: [b, sk, kvh, d] → [b, sq, h, d]."""
    import jax.experimental.pallas as pl

    b, sq, h, d = q.shape
    _, sk, kvh, _ = k.shape
    group = h // kvh
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"seq lengths ({sq}, {sk}) must be divisible by blocks "
            f"({block_q}, {block_k}); pad inputs first"
        )

    # [b*h, s, d] layout for the kernel
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * kvh, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * kvh, sk, d)

    def q_map(i, qb):
        return (i, qb, 0)

    def kv_map(i, qb):
        batch = i // h
        head = i % h
        return (batch * kvh + head // group, 0, 0)

    kernel = functools.partial(
        _flash_kernel, block_k=block_k, causal=causal, sm_scale=sm_scale,
        seq_k=sk, block_q=block_q, causal_offset=sk - sq,
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, sk, d), kv_map),
            pl.BlockSpec((1, sk, d), kv_map),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), q_map),
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret)


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                         interpret)
    return out, (q, k, v)


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    # Recompute-based backward: differentiate the reference implementation.
    _, vjp = jax.vjp(
        lambda q_, k_, v_: attention_reference(q_, k_, v_, causal, sm_scale),
        q, k, v,
    )
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, sm_scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    use_pallas: Optional[bool] = None,
                    interpret: bool = False) -> jax.Array:
    """Flash attention. Layout: q [b, sq, heads, d]; k/v [b, sk, kv_heads, d].

    ``use_pallas=None`` auto-selects: the Pallas kernel on TPU backends, the
    reference path elsewhere (tests force the kernel with interpret=True).
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if use_pallas is None:
        use_pallas = jax.default_backend() not in ("cpu",)
    if not use_pallas:
        return attention_reference(q, k, v, causal, sm_scale)
    return _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret)
