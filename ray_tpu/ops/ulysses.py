"""Ulysses sequence parallelism: all-to-all head/sequence re-sharding.

The second long-context substrate next to ring attention (SURVEY §5 —
the reference ships neither; it only provides the NCCL send/recv these
are hand-built from). Where ring attention keeps the sequence sharded
and rotates KV blocks around the ICI ring, Ulysses re-shards with two
all-to-alls: ranks swap their sequence shard for a head shard, compute
exact full-sequence attention for their head subset with the best local
kernel (Pallas flash on TPU), and swap back. Comm volume is O(s·h·d/n)
per all-to-all — independent of the ring's n-step pipeline — which
makes it the better fit when heads are plentiful and the per-step
latency of the ring would dominate (short-ish chunks, small n).

q/k/v locals are [batch, chunk, heads, head_dim] with chunk = seq/n.
all_to_all(split=heads, concat=seq) yields [batch, seq, heads/n,
head_dim]; tiled concatenation orders blocks by rank index, so the
gathered sequence is in global order and a plain causal mask is exact.

GQA: the head blocks handed to rank i are q[i·h/n:(i+1)·h/n] and
kv[i·kv/n:(i+1)·kv/n]; when kv % n == 0 these correspond exactly (the
local attention applies the remaining repeat factor). When kv heads
don't divide n, KV is first repeated by the minimal factor
r = n / gcd(kv, n) (r divides h/kv whenever n divides h, so the local
repeat stays integral) — correctness is preserved at the cost of a
larger KV all-to-all, matching DeepSpeed-Ulysses' replication strategy.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp


def _a2a_seq_to_heads(x: jax.Array, axis_name: str) -> jax.Array:
    # [b, chunk, h, d] -> [b, seq, h/n, d]
    return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)


def _a2a_heads_to_seq(x: jax.Array, axis_name: str) -> jax.Array:
    # [b, seq, h/n, d] -> [b, chunk, h, d]
    return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def ulysses_attention_local(q: jax.Array, k: jax.Array, v: jax.Array,
                            axis_name: str, causal: bool = True,
                            sm_scale: Optional[float] = None,
                            attn_fn: Optional[Callable] = None) -> jax.Array:
    """Ulysses body — call inside shard_map over ``axis_name``.

    q: [batch, chunk, heads, head_dim]; k/v may have fewer (GQA) heads.
    Returns [batch, chunk, heads, head_dim].
    """
    from ray_tpu.ops.layers import repeat_kv

    from ray_tpu.parallel.device_collectives import axis_size

    n = axis_size(axis_name)
    h, kvh = q.shape[2], k.shape[2]
    if h % n:
        raise ValueError(
            f"ulysses attention requires num_heads ({h}) divisible by the "
            f"'{axis_name}' axis size ({n}); use ring attention otherwise")
    if kvh % n:
        r = n // math.gcd(kvh, n)
        k = repeat_kv(k, r)
        v = repeat_kv(v, r)

    qh = _a2a_seq_to_heads(q, axis_name)
    kh = _a2a_seq_to_heads(k, axis_name)
    vh = _a2a_seq_to_heads(v, axis_name)

    if attn_fn is None:
        if jax.default_backend() == "tpu":
            from ray_tpu.ops.attention import flash_attention as attn_fn
        else:
            from ray_tpu.ops.attention import attention_reference as attn_fn
    out = attn_fn(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    return _a2a_heads_to_seq(out, axis_name)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh,
                      axis_name: str = "sp", causal: bool = True,
                      sm_scale: Optional[float] = None,
                      attn_fn: Optional[Callable] = None) -> jax.Array:
    """Global-array entry: q/k/v [batch, seq, heads, head_dim] with seq
    sharded over ``axis_name``; returns the same layout."""
    try:
        from jax import shard_map
    except ImportError:  # jax < 0.5: public alias not exported yet
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis_name, None, None)
    f = shard_map(
        partial(ulysses_attention_local, axis_name=axis_name, causal=causal,
                sm_scale=sm_scale, attn_fn=attn_fn),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    return f(q, k, v)
