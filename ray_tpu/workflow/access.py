"""Workflow management actor: the cluster-wide control surface.

Reference: python/ray/workflow/workflow_access.py — a named detached
``WorkflowManagementActor`` that every driver registers runs with, so
any process in the cluster can list, query, and cancel workflows
without knowing which driver launched them. Storage stays the source
of truth for step state (as in the reference); the actor is the
directory of live runs and the cancellation broadcast point.

Cancellation is cooperative and durable: ``cancel()`` drops a CANCEL
marker in the workflow's storage directory (visible to the driving
process through shared storage, exactly the reference's assumption)
and the workflow driver checks it between step waves and while waiting
on step results, aborting outstanding tasks via ``ray_tpu.cancel``.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import ray_tpu
from ray_tpu.exceptions import RayTpuError

MANAGEMENT_ACTOR_NAME = "__workflow_manager"


class WorkflowCancellationError(RayTpuError):
    """Raised from run()/result() when a workflow was canceled."""

    def __init__(self, workflow_id: str):
        super().__init__(f"workflow {workflow_id!r} was canceled")
        self.workflow_id = workflow_id


class WorkflowManagementActor:
    """Registry of known workflow runs (reference:
    workflow_access.WorkflowManagementActor). Methods are plain data
    ops — the actor's value is its NAME: one instance per cluster."""

    def __init__(self):
        self._runs: Dict[str, Dict[str, str]] = {}

    def register(self, workflow_id: str, storage: str):
        self._runs[workflow_id] = {"workflow_id": workflow_id,
                                   "storage": storage}
        return True

    def storage_of(self, workflow_id: str) -> Optional[str]:
        run = self._runs.get(workflow_id)
        return run["storage"] if run else None

    def list_registered(self) -> List[Dict[str, str]]:
        return list(self._runs.values())

    def unregister(self, workflow_id: str):
        self._runs.pop(workflow_id, None)
        return True


def _cancel_path(wf_dir: str) -> str:
    return os.path.join(wf_dir, "CANCEL")


def cancel_requested(wf_dir: str) -> bool:
    return os.path.exists(_cancel_path(wf_dir))


def get_management_actor():
    """Get-or-create the named detached management actor. Returns None
    when no runtime is initialized (pure-local workflow use keeps
    working without a cluster)."""
    from ray_tpu.core import runtime_context

    try:
        runtime_context.get_core()
    except Exception:  # noqa: BLE001 — not initialized
        return None
    try:
        return ray_tpu.get_actor(MANAGEMENT_ACTOR_NAME)
    except Exception:  # noqa: BLE001 — not created yet (or raced)
        pass
    try:
        cls = ray_tpu.remote(WorkflowManagementActor)
        return cls.options(name=MANAGEMENT_ACTOR_NAME,
                           lifetime="detached").remote()
    except Exception:  # noqa: BLE001 — lost a creation race
        try:
            return ray_tpu.get_actor(MANAGEMENT_ACTOR_NAME)
        except Exception:  # noqa: BLE001
            return None


def register_run(workflow_id: str, wf_dir: str):
    mgr = get_management_actor()
    if mgr is not None:
        try:
            ray_tpu.get(mgr.register.remote(workflow_id,
                                            os.path.dirname(wf_dir)))
        except Exception:  # noqa: BLE001 — registry is best-effort
            pass


def cancel(workflow_id: str, *, storage: Optional[str] = None):
    """Request cancellation of a (possibly remote) workflow run.

    Reference: workflow.cancel (workflow_access.py). With no explicit
    ``storage``, the management actor resolves where the run lives.
    """
    from ray_tpu import workflow as wf

    if storage is None:
        mgr = get_management_actor()
        if mgr is not None:
            try:
                storage = ray_tpu.get(
                    mgr.storage_of.remote(workflow_id))
            except Exception:  # noqa: BLE001
                storage = None
    wf_dir = wf._wf_dir(workflow_id, storage)
    if not os.path.isdir(wf_dir):
        raise KeyError(f"no workflow {workflow_id!r}")
    # canceling a finished workflow is a no-op (reference behavior):
    # never clobber a terminal SUCCESSFUL/FAILED status
    try:
        if wf.get_status(workflow_id, storage=storage) in (
                "SUCCESSFUL", "FAILED"):
            return
    except KeyError:
        pass
    with open(_cancel_path(wf_dir), "w") as f:
        f.write("1")
    wf._set_status(wf_dir, "CANCELED")


def get_output(workflow_id: str, *, storage: Optional[str] = None,
               timeout: Optional[float] = None):
    """Return the final result of a workflow, blocking while it is
    still RUNNING (reference: workflow.get_output). The result loads
    from the root step's checkpoint, so it works from any process with
    access to the storage — not just the launching driver."""
    import pickle
    import time as _time

    from ray_tpu import workflow as wf

    wf_dir = wf._wf_dir(workflow_id, storage)
    deadline = None if timeout is None else _time.monotonic() + timeout
    while True:
        status = wf.get_status(workflow_id, storage=storage)
        if status == "SUCCESSFUL":
            break
        if status == "CANCELED":
            raise WorkflowCancellationError(workflow_id)
        if status == "FAILED":
            raise RuntimeError(f"workflow {workflow_id!r} failed")
        if deadline is not None and _time.monotonic() > deadline:
            raise TimeoutError(
                f"workflow {workflow_id!r} still {status}")
        _time.sleep(0.1)

    import cloudpickle

    with open(os.path.join(wf_dir, "dag.pkl"), "rb") as f:
        dag = cloudpickle.load(f)
    root_id = wf._topo(dag)[-1].step_id
    with open(wf._result_path(wf_dir, root_id), "rb") as f:
        return pickle.load(f)
