"""Workflows: durable step-graph execution with resume.

Reference: python/ray/workflow/api.py:123 (workflow.run / resume /
get_status / list_all) over a step DAG persisted to storage. Here each
step is a ray_tpu task whose result is checkpointed under
``<storage>/<workflow_id>/<step>.pkl``; re-running (or resuming after a
crash) skips completed steps and replays only the missing suffix —
exactly-once per step as long as storage survives.

Usage::

    @workflow.step
    def fetch(url): ...

    @workflow.step
    def combine(a, b): ...

    out = workflow.run(combine.bind(fetch.bind(u1), fetch.bind(u2)),
                       workflow_id="ingest-2024-07", storage="/data/wf")
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu

_DEFAULT_STORAGE = "/tmp/ray_tpu_workflows"

from ray_tpu.workflow import access  # noqa: E402  (needs ray_tpu bound)
from ray_tpu.workflow.access import (  # noqa: E402,F401
    WorkflowCancellationError,
    WorkflowManagementActor,
    cancel,
    get_output,
)


class StepNode:
    """A bound step invocation (DAG node). Step ids are assigned at run
    time from the DAG's deterministic traversal order, so rebuilding the
    same graph in a fresh process maps onto the same checkpoints."""

    def __init__(self, fn, args, kwargs, name: Optional[str] = None,
                 max_retries: int = 3):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.name = name or fn.__name__
        self.max_retries = max_retries
        self.step_id: Optional[str] = None

    def upstream(self) -> List["StepNode"]:
        out = []
        for v in list(self.args) + list(self.kwargs.values()):
            if isinstance(v, StepNode):
                out.append(v)
        return out


class EventNode(StepNode):
    """DAG node resolved by an external event, not a task (see
    events.py; reference: workflow.wait_for_event). Executes INLINE in
    the workflow driver — it blocks the graph by design — and its
    payload checkpoints like any step result, so resume never re-waits a
    consumed event."""

    def __init__(self, key: str, provider, timeout=None):
        super().__init__(fn=None, args=(), kwargs={},
                         name=f"event__{key}")
        self.key = key
        self.provider = provider
        self.timeout = timeout

    def __getstate__(self):
        # providers hold live sockets/servers: the persisted DAG drops
        # them; resume(event_providers={key: provider}) re-attaches for
        # events that had not yet arrived
        state = dict(self.__dict__)
        state["provider"] = None
        return state


class _Step:
    def __init__(self, fn, name: Optional[str] = None,
                 max_retries: int = 3):
        self._fn = fn
        self._name = name
        self._max_retries = max_retries
        self.__name__ = fn.__name__

    def bind(self, *args, **kwargs) -> StepNode:
        return StepNode(self._fn, args, kwargs, name=self._name,
                        max_retries=self._max_retries)

    # parity alias with the reference's legacy .step()
    step = bind

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


def step(_fn=None, *, name: Optional[str] = None, max_retries: int = 3):
    """Decorator: mark a function as a durable workflow step."""
    def wrap(fn):
        return _Step(fn, name=name, max_retries=max_retries)
    return wrap(_fn) if _fn is not None else wrap


# ------------------------------------------------------------------ engine


def _wf_dir(workflow_id: str, storage: Optional[str]) -> str:
    return os.path.join(storage or _DEFAULT_STORAGE, workflow_id)


def _result_path(wf_dir: str, step_id: str) -> str:
    return os.path.join(wf_dir, f"{step_id}.pkl")


def _status_path(wf_dir: str) -> str:
    return os.path.join(wf_dir, "STATUS")


def _set_status(wf_dir: str, status: str):
    with open(_status_path(wf_dir), "w") as f:
        f.write(status)


def _new_workflow_id() -> str:
    # timestamp for human sort order + random suffix so concurrent
    # launches (run_async) can never collide on a checkpoint directory
    import uuid

    return f"wf_{int(time.time() * 1e3):x}_{uuid.uuid4().hex[:8]}"


def run(dag: StepNode, *, workflow_id: Optional[str] = None,
        storage: Optional[str] = None) -> Any:
    """Execute the DAG durably; completed steps are never re-executed."""
    workflow_id = workflow_id or _new_workflow_id()
    wf_dir = _wf_dir(workflow_id, storage)
    os.makedirs(wf_dir, exist_ok=True)
    _clear_cancel(wf_dir)
    _set_status(wf_dir, "RUNNING")
    access.register_run(workflow_id, wf_dir)

    # persist the dag so resume() can re-execute without the caller
    # rebuilding it
    dag_path = os.path.join(wf_dir, "dag.pkl")
    if not os.path.exists(dag_path):
        import cloudpickle

        with open(dag_path, "wb") as f:
            cloudpickle.dump(dag, f)

    try:
        out = _execute(dag, wf_dir)
        _set_status(wf_dir, "SUCCESSFUL")
        return out
    except access.WorkflowCancellationError:
        _set_status(wf_dir, "CANCELED")
        raise
    except BaseException:
        _set_status(wf_dir, "FAILED")
        raise


def _clear_cancel(wf_dir: str):
    try:
        os.remove(os.path.join(wf_dir, "CANCEL"))
    except OSError:
        pass


def _topo(node: StepNode) -> List[StepNode]:
    """Deterministic post-order traversal; assigns stable step ids."""
    order: List[StepNode] = []
    seen: Dict[int, bool] = {}

    def visit(n: StepNode):
        if id(n) in seen:
            return
        seen[id(n)] = True
        for u in n.upstream():
            visit(u)
        order.append(n)

    visit(node)
    counts: Dict[str, int] = {}
    for n in order:
        i = counts.get(n.name, 0)
        counts[n.name] = i + 1
        n.step_id = f"{n.name}_{i}"
    return order


def _execute(node: StepNode, wf_dir: str) -> Any:
    """Submit every incomplete step as a ray_tpu task with ObjectRef
    wiring (independent branches run in parallel), then persist results
    in topological order."""
    order = _topo(node)
    refs: Dict[str, Any] = {}      # step_id -> pending ObjectRef
    values: Dict[str, Any] = {}    # step_id -> completed value

    def resolve(v):
        if isinstance(v, StepNode):
            return values[v.step_id] if v.step_id in values \
                else refs[v.step_id]
        return v

    def checkpoint(step_id: str, value):
        path = _result_path(wf_dir, step_id)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, path)  # atomic: a crash never half-writes
        values[step_id] = value

    for n in order:
        path = _result_path(wf_dir, n.step_id)
        if os.path.exists(path):
            with open(path, "rb") as f:
                values[n.step_id] = pickle.load(f)

    def submit(n: StepNode):
        args = [resolve(v) for v in n.args]
        kwargs = {k: resolve(v) for k, v in n.kwargs.items()}
        remote_fn = ray_tpu.remote(max_retries=n.max_retries)(n.fn)
        refs[n.step_id] = remote_fn.remote(*args, **kwargs)

    def submittable(n: StepNode) -> bool:
        return all(u.step_id in values or u.step_id in refs
                   for u in n.upstream())

    # Submit every step whose deps don't hang on an unresolved event
    # BEFORE blocking on any event: independent branches (including the
    # step whose side effect may TRIGGER the event) run in parallel
    # with the wait. Then resolve events in topo order, releasing their
    # dependents as payloads arrive.
    def check_cancel():
        if access.cancel_requested(wf_dir):
            for r in refs.values():
                try:
                    ray_tpu.cancel(r)
                except Exception:  # noqa: BLE001 — best-effort abort
                    pass
            wf_id = os.path.basename(wf_dir)
            raise access.WorkflowCancellationError(wf_id)

    unplaced = [n for n in order if n.step_id not in values]
    while unplaced:
        check_cancel()
        rest = []
        for n in unplaced:
            if not isinstance(n, EventNode) and submittable(n):
                submit(n)
            else:
                rest.append(n)
        if not rest:
            break
        ev = next((n for n in rest if isinstance(n, EventNode)), None)
        if ev is None:
            raise RuntimeError(
                "workflow DAG has unsatisfiable dependencies: "
                + ", ".join(n.step_id for n in rest))
        if ev.provider is None:
            raise RuntimeError(
                f"event {ev.key!r} has not arrived and its provider "
                f"did not survive persistence; pass "
                f"resume(..., event_providers={{{ev.key!r}: provider}})")
        # the payload checkpoints so resume never re-waits it; the wait
        # polls in slices so cancel() can interrupt a blocked event
        deadline = (None if ev.timeout is None
                    else time.monotonic() + ev.timeout)
        while True:
            check_cancel()
            if deadline is None:
                remain = 0.25
            else:
                remain = min(0.25, deadline - time.monotonic())
            try:
                payload = ev.provider.poll(ev.key, max(remain, 0.0))
                break
            except TimeoutError:
                if deadline is not None and \
                        time.monotonic() >= deadline:
                    raise
        checkpoint(ev.step_id, payload)
        rest.remove(ev)
        unplaced = rest

    for n in order:
        if n.step_id not in refs:
            continue
        while True:
            check_cancel()
            done, _ = ray_tpu.wait([refs[n.step_id]], timeout=0.25)
            if done:
                break
        checkpoint(n.step_id, ray_tpu.get(refs[n.step_id]))

    return values[node.step_id]


def resume(workflow_id: str, *, storage: Optional[str] = None,
           event_providers: Optional[Dict[str, Any]] = None) -> Any:
    """Re-run an interrupted workflow; completed steps load from disk.
    ``event_providers`` re-attaches providers (keyed by event key) to
    event nodes whose payloads had not yet arrived."""
    wf_dir = _wf_dir(workflow_id, storage)
    dag_path = os.path.join(wf_dir, "dag.pkl")
    if not os.path.exists(dag_path):
        raise KeyError(f"no workflow {workflow_id!r}")
    import cloudpickle

    with open(dag_path, "rb") as f:
        dag = cloudpickle.load(f)
    if event_providers:
        for n in _topo(dag):
            if isinstance(n, EventNode) and n.key in event_providers:
                n.provider = event_providers[n.key]
    _clear_cancel(wf_dir)
    _set_status(wf_dir, "RUNNING")
    access.register_run(workflow_id, wf_dir)
    try:
        out = _execute(dag, wf_dir)
        _set_status(wf_dir, "SUCCESSFUL")
        return out
    except access.WorkflowCancellationError:
        _set_status(wf_dir, "CANCELED")
        raise
    except BaseException:
        _set_status(wf_dir, "FAILED")
        raise


def get_status(workflow_id: str, *, storage: Optional[str] = None) -> str:
    path = _status_path(_wf_dir(workflow_id, storage))
    if not os.path.exists(path):
        raise KeyError(f"no workflow {workflow_id!r}")
    with open(path) as f:
        return f.read().strip()


def list_all(*, storage: Optional[str] = None) -> List[Dict[str, str]]:
    base = storage or _DEFAULT_STORAGE
    if not os.path.isdir(base):
        return []
    out = []
    for wf in sorted(os.listdir(base)):
        try:
            out.append({"workflow_id": wf, "status": get_status(
                wf, storage=base)})
        except KeyError:
            continue
    return out


def delete(workflow_id: str, *, storage: Optional[str] = None):
    import shutil

    shutil.rmtree(_wf_dir(workflow_id, storage), ignore_errors=True)
    mgr = access.get_management_actor()
    if mgr is not None:
        try:
            ray_tpu.get(mgr.unregister.remote(workflow_id))
        except Exception:  # noqa: BLE001 — registry is best-effort
            pass


from ray_tpu.workflow.events import (  # noqa: E402,F401
    EventProvider,
    HTTPEventProvider,
    LocalEventProvider,
    wait_for_event,
)


class WorkflowRun:
    """Handle for an in-flight async workflow (reference:
    workflow.run_async returns an ObjectRef; here a thread-backed future
    — the workflow driver orchestrates its steps through the caller's
    core, so a thread in the caller is the honest executor)."""

    def __init__(self, workflow_id: str, thread, box: list):
        self.workflow_id = workflow_id
        self._thread = thread
        self._box = box

    def done(self) -> bool:
        return not self._thread.is_alive()

    def result(self, timeout: Optional[float] = None) -> Any:
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError(
                f"workflow {self.workflow_id!r} still running")
        kind, value = self._box[0]
        if kind == "err":
            raise value
        return value


def run_async(dag: StepNode, *, workflow_id: Optional[str] = None,
              storage: Optional[str] = None) -> WorkflowRun:
    """Start a workflow without blocking; returns a ``WorkflowRun``
    whose ``result()`` is ``run``'s return value. Steps still run as
    parallel ray_tpu tasks; only the orchestration loop moves off the
    caller's thread."""
    workflow_id = workflow_id or _new_workflow_id()
    box: list = [("err", RuntimeError("workflow never ran"))]

    def drive():
        try:
            box[0] = ("ok", run(dag, workflow_id=workflow_id,
                                storage=storage))
        except BaseException as e:  # noqa: BLE001
            box[0] = ("err", e)

    t = threading.Thread(target=drive, daemon=True,
                         name=f"wf-{workflow_id}")
    t.start()
    return WorkflowRun(workflow_id, t, box)
