"""Workflow events: durably wait on EXTERNAL signals inside a step DAG.

Reference: python/ray/workflow/http_event_provider.py (HTTP ingress for
events) + workflow.wait_for_event (event_listener.py). An event node
blocks the workflow until its payload arrives; once received it
checkpoints exactly like a step result, so a resumed workflow never
waits for (or double-consumes) an event it already saw.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Dict, Optional, Tuple


class EventProvider:
    """Interface: block until the payload for ``key`` arrives."""

    def poll(self, key: str, timeout: Optional[float] = None) -> Any:
        raise NotImplementedError


class LocalEventProvider(EventProvider):
    """In-process provider: tests and same-process producers call
    ``send_event`` directly."""

    def __init__(self):
        self._cv = threading.Condition()
        self._events: Dict[str, Any] = {}

    def send_event(self, key: str, payload: Any):
        with self._cv:
            self._events[key] = payload
            self._cv.notify_all()

    def poll(self, key: str, timeout: Optional[float] = None) -> Any:
        with self._cv:
            if not self._cv.wait_for(lambda: key in self._events,
                                     timeout):
                raise TimeoutError(f"event {key!r} never arrived")
            return self._events[key]


class HTTPEventProvider(LocalEventProvider):
    """HTTP ingress for external event producers (reference:
    http_event_provider.py — there a Serve deployment; here a stdlib
    HTTP listener).

        POST /event/<key>      body: JSON payload

    resolves any workflow waiting on ``key`` with the decoded body."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        super().__init__()
        import http.server

        provider = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 — stdlib API
                if not self.path.startswith("/event/"):
                    self.send_error(404)
                    return
                key = self.path[len("/event/"):]
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) if n else b"null"
                try:
                    payload = json.loads(raw)
                except ValueError:
                    self.send_error(400, "body must be JSON")
                    return
                provider.send_event(key, payload)
                self.send_response(200)
                self.end_headers()
                self.wfile.write(b"ok")

            def log_message(self, *a):  # quiet
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      Handler)
        self.address: Tuple[str, int] = self._httpd.server_address
        threading.Thread(target=self._httpd.serve_forever, daemon=True,
                         name="wf-events").start()

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def wait_for_event(key: str, provider: EventProvider,
                   timeout: Optional[float] = None):
    """A DAG node that blocks the workflow until the event for ``key``
    arrives, then checkpoints its payload as the node's durable result
    (reference: workflow.wait_for_event)."""
    from ray_tpu.workflow import EventNode

    return EventNode(key, provider, timeout)
