"""State API: observability over nodes, actors, tasks, and objects.

Reference: python/ray/util/state/api.py:781 (list_nodes/list_actors/
list_tasks/list_objects, summarize_*). Works against both cores: the
embedded runtime answers from its own tables; a cluster driver aggregates
the GCS node/actor tables plus per-node state RPCs.
"""

from __future__ import annotations

from typing import Any, Dict, List

from ray_tpu.core import runtime_context


def _core():
    return runtime_context.get_core()


def _is_cluster(core) -> bool:
    return hasattr(core, "_cluster_view")


def _node_summaries(core) -> List[dict]:
    from ray_tpu.core.cluster.rpc import RpcError

    out = []
    for n in core.nodes():
        addr = tuple(n["address"])
        entry = {"node_id": n["node_id"].hex(), "address": list(addr),
                 "state": n["state"], "resources": n["resources"],
                 "labels": n.get("topology", {})}
        try:
            entry["summary"] = core._nodes.get(addr).call(("state",))
        except RpcError:
            entry["summary"] = None  # unreachable node
        out.append(entry)
    return out


def _workers_from(summaries: List[dict]) -> List[dict]:
    out = []
    for n in summaries:
        if n["summary"]:
            for w in n["summary"]["workers"]:
                out.append({**w, "node_id": n["node_id"]})
    return out


def _tasks_from(summaries: List[dict]) -> Dict[str, int]:
    total = {"queued": 0, "running": 0}
    for n in summaries:
        if n["summary"]:
            total["queued"] += n["summary"]["tasks"]["queued"]
            total["running"] += n["summary"]["tasks"]["running"]
    return total


def _objects_from(summaries: List[dict]) -> Dict[str, Any]:
    agg = {"tracked": 0, "resolved": 0, "pinned": 0, "spilled_bytes": 0,
           "store_bytes_in_use": 0}
    for n in summaries:
        s = n["summary"]
        if s:
            for k in ("tracked", "resolved", "pinned", "spilled_bytes"):
                agg[k] += s["objects"][k]
            agg["store_bytes_in_use"] += s["store"]["bytes_in_use"]
    return agg


def _transfer_from(summaries: List[dict]) -> Dict[str, Any]:
    """Cluster-wide data-plane movement: cross-node pull throughput,
    pull-admission occupancy, and sender-side backpressure, aggregated
    from each node's ("state",) reply."""
    agg: Dict[str, Any] = {"fetch_bytes": 0, "fetch_seconds": 0.0,
                           "fetch_count": 0, "fetch_gbps": 0.0,
                           "push_waits": 0, "pulls": []}
    for n in summaries:
        s = n["summary"]
        if not s:
            continue
        f = s.get("fetch")
        if f:
            agg["fetch_bytes"] += f["bytes"]
            agg["fetch_seconds"] += f["seconds"]
            agg["fetch_count"] += f["count"]
        agg["push_waits"] += s.get("push_waits", 0)
        if s.get("pulls") is not None:
            agg["pulls"].append({"node_id": n["node_id"], **s["pulls"]})
    if agg["fetch_seconds"] > 0:
        agg["fetch_gbps"] = round(
            agg["fetch_bytes"] * 8 / agg["fetch_seconds"] / 1e9, 3)
    return agg


def summarize_transfers() -> Dict[str, Any]:
    """Object-movement stats: bytes pulled cross-node, effective fetch
    throughput, per-node pull-manager occupancy, push backpressure. The
    single-node runtime has no cross-node plane: returns zeros."""
    core = _core()
    if _is_cluster(core):
        return _transfer_from(_node_summaries(core))
    return {"fetch_bytes": 0, "fetch_seconds": 0.0, "fetch_count": 0,
            "fetch_gbps": 0.0, "push_waits": 0, "pulls": []}


def locality_stats() -> Dict[str, int]:
    """This driver's locality-scheduling counters: submissions that
    landed on the node holding the most argument bytes (hits) vs not
    (misses), cross-node transfer bytes avoided (bytes_local) vs still
    required (bytes_remote), and directory lookup efficiency
    (batched_lookups, cache_hits). All zeros on the single-node core,
    where every argument is always local."""
    core = _core()
    if _is_cluster(core):
        with core._lock:
            return dict(core.locality_stats)
    return {"hits": 0, "misses": 0, "bytes_local": 0, "bytes_remote": 0,
            "batched_lookups": 0, "cache_hits": 0}


def list_nodes() -> List[dict]:
    core = _core()
    if _is_cluster(core):
        return [{"node_id": n["node_id"].hex(),
                 "address": list(n["address"]), "state": n["state"],
                 "resources": n["resources"]} for n in core.nodes()]
    s = core.state_summary()
    return [{"node_id": s["node_id"], "address": ["local", 0],
             "state": "ALIVE", "resources": s["resources"]["total"]}]


def list_actors() -> List[dict]:
    core = _core()
    if _is_cluster(core):
        table = core.gcs.call(("list_actors",))
        return [{"actor_id": aid.hex(), **{k: v for k, v in info.items()
                                           if k != "opts"}}
                for aid, info in table.items()]
    return core.state_summary()["actors"]


def list_workers() -> List[dict]:
    core = _core()
    if _is_cluster(core):
        return _workers_from(_node_summaries(core))
    return core.state_summary()["workers"]


def stack_dump() -> Dict[str, str]:
    """Live stacks of every worker across the cluster — the py-spy-style
    profiling surface (reference: dashboard worker-stack endpoint).
    Returns {worker_id_hex (prefixed by node in cluster mode): text}."""
    from ray_tpu.core.cluster.rpc import RpcError

    core = _core()
    if _is_cluster(core):
        out: Dict[str, str] = {}
        for n in core.nodes():
            try:
                dumps = core._nodes.get(tuple(n["address"])).call(
                    ("stack_dump",))
            except RpcError:
                continue
            nid = n["node_id"].hex()[:8]
            out.update({f"{nid}:{wid}": text
                        for wid, text in dumps.items()})
        return out
    return core.stack_dump()


def summarize_tasks() -> Dict[str, Any]:
    core = _core()
    if _is_cluster(core):
        return _tasks_from(_node_summaries(core))
    return core.state_summary()["tasks"]


def summarize_objects() -> Dict[str, Any]:
    core = _core()
    if _is_cluster(core):
        return _objects_from(_node_summaries(core))
    s = core.state_summary()
    return {**s["objects"], "store_bytes_in_use": s["store"]["bytes_in_use"]}


def list_logs(node_id: str = None) -> List[dict]:
    """Session log files (name + size), per node in cluster mode
    (reference: ray.util.state.list_logs)."""
    from ray_tpu.core.cluster.rpc import RpcError

    core = _core()
    if _is_cluster(core):
        out = []
        for n in core.nodes():
            if node_id and n["node_id"].hex() != node_id:
                continue
            try:
                files = core._nodes.get(tuple(n["address"])).call(
                    ("list_logs",))
            except RpcError:  # unreachable node
                files = []
            out.extend({**f, "node_id": n["node_id"].hex()} for f in files)
        return out
    from ray_tpu.core.log_monitor import list_log_files

    return list_log_files(core.log_dir)


def get_log(filename: str, node_id: str = None,
            tail: int = 1000) -> str:
    """Tail of one session log file (reference: ray.util.state.get_log)."""
    from ray_tpu.core.cluster.rpc import RpcError

    core = _core()
    if _is_cluster(core):
        for n in core.nodes():
            if node_id and n["node_id"].hex() != node_id:
                continue
            try:
                return core._nodes.get(tuple(n["address"])).call(
                    ("get_log", filename, tail))
            except (RpcError, FileNotFoundError):
                # transport failure or absent on this node: try the next
                # one; bad requests (ValueError) propagate untouched
                if node_id:
                    raise
                continue
        raise FileNotFoundError(
            f"log {filename!r} not found on any reachable node")
    from ray_tpu.core.log_monitor import read_log_file

    return read_log_file(core.log_dir, filename, tail)


def cluster_resources() -> Dict[str, float]:
    core = _core()
    if _is_cluster(core):
        return core.cluster_resources()
    return core.state_summary()["resources"]["total"]


def available_resources() -> Dict[str, float]:
    core = _core()
    if _is_cluster(core):
        total: Dict[str, float] = {}
        for n in core.nodes():
            for k, v in n.get("avail", {}).items():
                total[k] = total.get(k, 0) + v
        return total
    return core.state_summary()["resources"]["available"]


def state_summary() -> Dict[str, Any]:
    """One-call overview (the dashboard-lite payload). In cluster mode the
    per-node fan-out happens exactly once, so the snapshot is internally
    consistent."""
    core = _core()
    if _is_cluster(core):
        summaries = _node_summaries(core)
        return {
            "nodes": list_nodes(),
            "actors": list_actors(),
            "tasks": _tasks_from(summaries),
            "objects": _objects_from(summaries),
            "transfers": _transfer_from(summaries),
            "scheduling": {"locality": locality_stats()},
            "cluster_resources": cluster_resources(),
            "available_resources": available_resources(),
        }
    return {
        "nodes": list_nodes(),
        "actors": list_actors(),
        "tasks": summarize_tasks(),
        "objects": summarize_objects(),
        "transfers": summarize_transfers(),
        "scheduling": {"locality": locality_stats()},
        "cluster_resources": cluster_resources(),
        "available_resources": available_resources(),
    }
