"""CLI: cluster lifecycle + observability + jobs.

Reference: python/ray/scripts/scripts.py:571 (`ray start/stop/status`),
the `ray job` and `ray list` command families. Invoked as
``python -m ray_tpu <command>``.

Session state (head pid/address) lives in /tmp/ray_tpu_session.json so
``stop``/``status`` find the cluster without arguments.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

SESSION_FILE = "/tmp/ray_tpu_session.json"


def _save_session(data: dict):
    with open(SESSION_FILE, "w") as f:
        json.dump(data, f)


def _load_session() -> dict:
    if not os.path.exists(SESSION_FILE):
        raise SystemExit(
            "no running session found (did you `ray_tpu start --head`?)")
    with open(SESSION_FILE) as f:
        return json.load(f)


def _ensure_authkey() -> str:
    key = os.environ.get("RTPU_CLUSTER_AUTHKEY")
    if not key:
        key = os.urandom(16).hex()
        os.environ["RTPU_CLUSTER_AUTHKEY"] = key
    return key


def cmd_start(args):
    env = dict(os.environ)
    if args.head:
        key = _ensure_authkey()
        env["RTPU_CLUSTER_AUTHKEY"] = key
        gcs_cmd = [sys.executable, "-m", "ray_tpu.core.cluster.gcs",
                   "--port", str(args.port)]
        if getattr(args, "gcs_persist_dir", None):
            gcs_cmd += ["--persist-dir", args.gcs_persist_dir]
        gcs = subprocess.Popen(
            gcs_cmd,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
            start_new_session=True)
        line = gcs.stdout.readline().decode()
        address = line.split()[-1]
        node = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.cluster.node_server",
             "--gcs", address, "--head",
             "--num-workers", str(args.num_workers)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
            start_new_session=True)
        node_line = node.stdout.readline().decode()
        _save_session({"address": address, "authkey": key,
                       "pids": [gcs.pid, node.pid]})
        print(f"ray_tpu head started.\n  GCS address: {address}\n"
              f"  node: {node_line.split()[-1]}\n"
              f"  connect: ray_tpu.init(address=\"{address}\")  "
              f"(RTPU_CLUSTER_AUTHKEY={key})")
    else:
        if not args.address:
            raise SystemExit("--address host:port required to join")
        node = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.cluster.node_server",
             "--gcs", args.address,
             "--num-workers", str(args.num_workers)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
            start_new_session=True)
        line = node.stdout.readline().decode()
        print(f"node started at {line.split()[-1]} "
              f"(joined {args.address})")
        try:
            sess = _load_session()
            sess.setdefault("pids", []).append(node.pid)
            _save_session(sess)
        except SystemExit:
            pass


def cmd_stop(args):
    sess = _load_session()
    for pid in sess.get("pids", []):
        try:
            os.killpg(pid, signal.SIGTERM)
        except OSError:
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                pass
    time.sleep(0.5)
    for pid in sess.get("pids", []):
        try:
            os.killpg(pid, signal.SIGKILL)
        except OSError:
            pass
    os.unlink(SESSION_FILE)
    print("stopped.")


def _connect():
    import ray_tpu

    sess = _load_session()
    os.environ.setdefault("RTPU_CLUSTER_AUTHKEY", sess["authkey"])
    ray_tpu.init(address=sess["address"])
    return sess


def cmd_status(args):
    _connect()
    from ray_tpu import state

    s = state.state_summary()
    print(f"nodes: {len(s['nodes'])}")
    for n in s["nodes"]:
        print(f"  {n['node_id'][:12]}  {n['address']}  {n['state']}  "
              f"{n['resources']}")
    print(f"tasks: {s['tasks']}")
    print(f"objects: {s['objects']}")
    print(f"resources: {s['cluster_resources']} "
          f"(available {s['available_resources']})")


def cmd_state(args):
    _connect()
    from ray_tpu import state

    fn = {"nodes": state.list_nodes, "actors": state.list_actors,
          "workers": state.list_workers, "tasks": state.summarize_tasks,
          "objects": state.summarize_objects,
          "summary": state.state_summary}[args.what]
    print(json.dumps(fn(), indent=2, default=str))


def cmd_serve(args):
    _connect()
    from ray_tpu import serve

    if args.action == "deploy":
        if not args.config:
            raise SystemExit("usage: ray_tpu serve deploy <config.yaml>")
        names = serve.deploy_config(args.config)
        print(json.dumps({"deployed": names}))
    elif args.action == "status":
        print(json.dumps(serve.status(), indent=2, default=str))
    elif args.action == "shutdown":
        serve.shutdown()


def cmd_stack(args):
    """Live worker stacks (py-spy-style profiling surface)."""
    del args
    _connect()
    from ray_tpu import state

    for wid, text in state.stack_dump().items():
        print(f"===== worker {wid} =====\n{text}")


def cmd_logs(args):
    _connect()
    from ray_tpu import state

    if args.file:
        print(state.get_log(args.file, node_id=args.node, tail=args.tail),
              end="")
    else:
        print(json.dumps(state.list_logs(node_id=args.node), indent=2))


def cmd_job(args):
    from ray_tpu.job import JobSubmissionClient

    sess = _load_session()
    os.environ.setdefault("RTPU_CLUSTER_AUTHKEY", sess["authkey"])
    client = JobSubmissionClient(sess["address"])
    if args.job_cmd == "submit":
        import shlex

        parts = [a for i, a in enumerate(args.entrypoint)
                 if not (i == 0 and a == "--")]
        job_id = client.submit_job(entrypoint=shlex.join(parts))
        print(job_id)
        if args.wait:
            status = client.wait_until_finished(job_id)
            print(status.value)
            print(client.get_job_logs(job_id), end="")
    elif args.job_cmd == "status":
        print(client.get_job_status(args.job_id).value)
    elif args.job_cmd == "logs":
        print(client.get_job_logs(args.job_id), end="")
    elif args.job_cmd == "stop":
        print("stopped" if client.stop_job(args.job_id) else "not running")
    elif args.job_cmd == "list":
        for j in client.list_jobs():
            print(f"{j['job_id']}  {j['status']}  {j['entrypoint']!r}")
    client.close()


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="ray_tpu", description="ray_tpu cluster CLI")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start", help="start a head node or join a cluster")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", default=None, help="GCS host:port to join")
    sp.add_argument("--port", type=int, default=0, help="GCS port (head)")
    sp.add_argument("--num-workers", type=int, default=2)
    sp.add_argument("--gcs-persist-dir", default=None,
                    help="persist GCS state here; a restarted head on the "
                         "same dir + port rehydrates the cluster")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("stop", help="stop the local session")
    sp.set_defaults(fn=cmd_stop)

    sp = sub.add_parser("status", help="cluster overview")
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser("state", help="state API queries")
    sp.add_argument("what", choices=["nodes", "actors", "workers", "tasks",
                                     "objects", "summary"])
    sp.set_defaults(fn=cmd_state)

    sp = sub.add_parser("stack", help="dump live worker stacks (profiling)")
    sp.set_defaults(fn=cmd_stack)

    sp = sub.add_parser("serve", help="declarative serve deploys")
    sp.add_argument("action", choices=["deploy", "status", "shutdown"])
    sp.add_argument("config", nargs="?", default=None,
                    help="config file (for deploy)")
    sp.set_defaults(fn=cmd_serve)

    sp = sub.add_parser("logs", help="list/tail session worker logs")
    sp.add_argument("file", nargs="?", default=None,
                    help="log filename (omit to list)")
    sp.add_argument("--node", default=None, help="node id filter")
    sp.add_argument("--tail", type=int, default=1000)
    sp.set_defaults(fn=cmd_logs)

    sp = sub.add_parser("job", help="job submission")
    jsub = sp.add_subparsers(dest="job_cmd", required=True)
    j = jsub.add_parser("submit")
    j.add_argument("--wait", action="store_true")
    j.add_argument("entrypoint", nargs=argparse.REMAINDER)
    j = jsub.add_parser("status")
    j.add_argument("job_id")
    j = jsub.add_parser("logs")
    j.add_argument("job_id")
    j = jsub.add_parser("stop")
    j.add_argument("job_id")
    jsub.add_parser("list")
    sp.set_defaults(fn=cmd_job)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
