"""Search-space primitives + variant generation.

Reference: python/ray/tune/search/sample.py (Domain/Float/Integer/
Categorical, grid_search) and search/basic_variant.py
(BasicVariantGenerator) — grid cross-products plus random sampling of
Domain leaves.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, Iterator, List, Optional


class Domain:
    """A sampleable hyperparameter domain."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False,
                 q: Optional[float] = None):
        self.lower = lower
        self.upper = upper
        self.log = log
        self.q = q

    def sample(self, rng: random.Random) -> float:
        if self.log:
            import math
            v = math.exp(rng.uniform(math.log(self.lower),
                                     math.log(self.upper)))
        else:
            v = rng.uniform(self.lower, self.upper)
        if self.q:
            v = round(v / self.q) * self.q
        return v


class Integer(Domain):
    def __init__(self, lower: int, upper: int, log: bool = False):
        self.lower = lower
        self.upper = upper
        self.log = log

    def sample(self, rng: random.Random) -> int:
        if self.log:
            import math
            v = math.exp(rng.uniform(math.log(self.lower),
                                     math.log(self.upper)))
            return max(self.lower, min(self.upper - 1, int(v)))
        return rng.randrange(self.lower, self.upper)


class Categorical(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(self.categories)


class Function(Domain):
    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn

    def sample(self, rng: random.Random) -> Any:
        return self.fn()


class GridSearch:
    def __init__(self, values: List[Any]):
        self.values = list(values)


# ---- public constructors (reference: tune.uniform/choice/... sample.py) ----

def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, q=q)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def lograndint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper, log=True)


def choice(categories: List[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable[[], Any]) -> Function:
    return Function(fn)


def grid_search(values: List[Any]) -> Dict[str, Any]:
    return {"grid_search": list(values)}


def _is_grid(v) -> bool:
    return (isinstance(v, dict) and set(v.keys()) == {"grid_search"}) or \
        isinstance(v, GridSearch)


def _grid_values(v) -> List[Any]:
    return v.values if isinstance(v, GridSearch) else v["grid_search"]


def generate_variants(space: Dict[str, Any], num_samples: int = 1,
                      seed: Optional[int] = None
                      ) -> Iterator[Dict[str, Any]]:
    """Yield resolved configs: the grid cross-product, repeated
    ``num_samples`` times, with Domain leaves re-sampled per repeat
    (reference: BasicVariantGenerator semantics — num_samples multiplies
    the grid)."""
    rng = random.Random(seed)

    grid_keys: List[List[str]] = []
    grid_vals: List[List[Any]] = []

    def walk(prefix: List[str], node: Any):
        if isinstance(node, dict) and not _is_grid(node):
            for k, v in node.items():
                walk(prefix + [k], v)
        elif _is_grid(node):
            grid_keys.append(list(prefix))
            grid_vals.append(_grid_values(node))

    walk([], space)

    def resolve(node: Any, assignment: Dict[tuple, Any],
                path: List[str]) -> Any:
        if _is_grid(node):
            return assignment[tuple(path)]
        if isinstance(node, dict):
            return {k: resolve(v, assignment, path + [k])
                    for k, v in node.items()}
        if isinstance(node, Domain):
            return node.sample(rng)
        return node

    combos = list(itertools.product(*grid_vals)) if grid_vals else [()]
    for _ in range(max(1, num_samples)):
        for combo in combos:
            assignment = {tuple(k): v
                          for k, v in zip(grid_keys, combo)}
            yield resolve(space, assignment, [])
