"""Trial schedulers: FIFO, ASHA, median stopping, PBT.

Reference: python/ray/tune/schedulers/ — async_hyperband.py
(ASHAScheduler), median_stopping_rule.py, pbt.py
(PopulationBasedTraining). The controller calls ``on_result`` for every
report and acts on the returned decision; PBT additionally mutates trial
configs via exploit/explore with checkpoint cloning.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def set_experiment(self, metric: str, mode: str):
        self.metric = metric
        self.mode = mode

    def _score(self, result: Dict[str, Any]) -> Optional[float]:
        v = result.get(self.metric)
        if v is None:
            return None
        return float(v) if self.mode == "max" else -float(v)

    def on_result(self, trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial):
        pass


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion (reference: FIFOScheduler)."""


class ASHAScheduler(TrialScheduler):
    """Asynchronous successive halving (reference:
    schedulers/async_hyperband.py AsyncHyperBandScheduler).

    Rungs at r, r*eta, r*eta^2, ... up to max_t; a trial reaching a rung
    is stopped unless its score is in the top 1/eta of scores recorded at
    that rung so far.
    """

    def __init__(self, *, time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4.0):
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace = grace_period
        self.eta = reduction_factor
        self._rungs: List[tuple] = []  # (milestone, {trial_id: score})
        m = max_t
        milestones = []
        while m > grace_period:
            milestones.append(m)
            m = int(m / self.eta)
        milestones.append(grace_period)
        # ascending milestones paired with recorded scores
        self._rungs = [(ms, {}) for ms in sorted(milestones)]

    def on_result(self, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, trial.iterations)
        score = self._score(result)
        if score is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        decision = CONTINUE
        for milestone, recorded in self._rungs:
            if t < milestone or trial.trial_id in recorded:
                continue
            recorded[trial.trial_id] = score
            if len(recorded) > 1:
                # Continue only in the top 1/eta of scores recorded at this
                # rung (newcomer included), as in the reference's
                # AsyncHyperBand cutoff (schedulers/async_hyperband.py).
                vals = sorted(recorded.values())
                q = (1.0 - 1.0 / self.eta)
                cutoff = vals[min(len(vals) - 1,
                                  int(math.floor(q * (len(vals) - 1))))]
                if score < cutoff:
                    decision = STOP
        return decision


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best score so far is below the median of other
    trials' running averages at the same step (reference:
    schedulers/median_stopping_rule.py)."""

    def __init__(self, *, time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._avgs: Dict[str, List[float]] = {}

    def on_result(self, trial, result: Dict[str, Any]) -> str:
        score = self._score(result)
        if score is None:
            return CONTINUE
        hist = self._avgs.setdefault(trial.trial_id, [])
        hist.append(score)
        t = result.get(self.time_attr, len(hist))
        if t < self.grace:
            return CONTINUE
        others = [sum(h) / len(h) for tid, h in self._avgs.items()
                  if tid != trial.trial_id and h]
        if len(others) < self.min_samples:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        best = max(hist)
        return STOP if best < median else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: schedulers/pbt.py): at each perturbation interval,
    bottom-quantile trials exploit a top-quantile trial — clone its latest
    checkpoint and config — then explore by perturbing hyperparameters.

    Exploitation here restarts the trial actor from the donor checkpoint
    (the reference's stop-and-restore path; in-place _exploit is an
    optimization it also only applies with reuse_actors).
    """

    def __init__(self, *, time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 perturbation_factors=(1.2, 0.8),
                 seed: Optional[int] = None):
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self.factors = perturbation_factors
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        self._scores: Dict[str, float] = {}
        # controller inspects this after on_result returns EXPLOIT
        self.pending_exploit: Optional[dict] = None

    EXPLOIT = "EXPLOIT"

    def on_result(self, trial, result: Dict[str, Any]) -> str:
        score = self._score(result)
        if score is not None:
            self._scores[trial.trial_id] = score
        t = result.get(self.time_attr, trial.iterations)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval or len(self._scores) < 2:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        ranked = sorted(self._scores.items(), key=lambda kv: kv[1])
        n = len(ranked)
        k = max(1, int(n * self.quantile))
        bottom = {tid for tid, _ in ranked[:k]}
        top = [tid for tid, _ in ranked[-k:]]
        if trial.trial_id not in bottom or trial.trial_id in top:
            return CONTINUE
        donor_id = self._rng.choice(top)
        self.pending_exploit = {
            "donor_id": donor_id,
            "explore": True,
        }
        return self.EXPLOIT

    def explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        """Perturb mutated hyperparameters (reference: pbt.py _explore)."""
        from ray_tpu.tune.search_space import Domain
        out = dict(config)
        for key, spec in self.mutations.items():
            if self._rng.random() < self.resample_prob or \
                    key not in out or not isinstance(out[key], (int, float)):
                if isinstance(spec, Domain):
                    out[key] = spec.sample(self._rng)
                elif isinstance(spec, list):
                    out[key] = self._rng.choice(spec)
                elif callable(spec):
                    out[key] = spec()
            else:
                factor = self._rng.choice(self.factors)
                out[key] = out[key] * factor
                if isinstance(spec, list):
                    # snap to nearest allowed value
                    out[key] = min(spec, key=lambda v: abs(v - out[key]))
        return out
