"""Trial schedulers: FIFO, ASHA, median stopping, PBT.

Reference: python/ray/tune/schedulers/ — async_hyperband.py
(ASHAScheduler), median_stopping_rule.py, pbt.py
(PopulationBasedTraining). The controller calls ``on_result`` for every
report and acts on the returned decision; PBT additionally mutates trial
configs via exploit/explore with checkpoint cloning.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def set_experiment(self, metric: str, mode: str):
        self.metric = metric
        self.mode = mode

    def _score(self, result: Dict[str, Any]) -> Optional[float]:
        v = result.get(self.metric)
        if v is None:
            return None
        return float(v) if self.mode == "max" else -float(v)

    def on_result(self, trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial):
        pass


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion (reference: FIFOScheduler)."""


class ASHAScheduler(TrialScheduler):
    """Asynchronous successive halving (reference:
    schedulers/async_hyperband.py AsyncHyperBandScheduler).

    Rungs at r, r*eta, r*eta^2, ... up to max_t; a trial reaching a rung
    is stopped unless its score is in the top 1/eta of scores recorded at
    that rung so far.
    """

    def __init__(self, *, time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4.0):
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace = grace_period
        self.eta = reduction_factor
        self._rungs: List[tuple] = []  # (milestone, {trial_id: score})
        m = max_t
        milestones = []
        while m > grace_period:
            milestones.append(m)
            m = int(m / self.eta)
        milestones.append(grace_period)
        # ascending milestones paired with recorded scores
        self._rungs = [(ms, {}) for ms in sorted(milestones)]

    def on_result(self, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, trial.iterations)
        score = self._score(result)
        if score is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        decision = CONTINUE
        for milestone, recorded in self._rungs:
            if t < milestone or trial.trial_id in recorded:
                continue
            recorded[trial.trial_id] = score
            if len(recorded) > 1:
                # Continue only in the top 1/eta of scores recorded at this
                # rung (newcomer included), as in the reference's
                # AsyncHyperBand cutoff (schedulers/async_hyperband.py).
                vals = sorted(recorded.values())
                q = (1.0 - 1.0 / self.eta)
                cutoff = vals[min(len(vals) - 1,
                                  int(math.floor(q * (len(vals) - 1))))]
                if score < cutoff:
                    decision = STOP
        return decision


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best score so far is below the median of other
    trials' running averages at the same step (reference:
    schedulers/median_stopping_rule.py)."""

    def __init__(self, *, time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._avgs: Dict[str, List[float]] = {}

    def on_result(self, trial, result: Dict[str, Any]) -> str:
        score = self._score(result)
        if score is None:
            return CONTINUE
        hist = self._avgs.setdefault(trial.trial_id, [])
        hist.append(score)
        t = result.get(self.time_attr, len(hist))
        if t < self.grace:
            return CONTINUE
        others = [sum(h) / len(h) for tid, h in self._avgs.items()
                  if tid != trial.trial_id and h]
        if len(others) < self.min_samples:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        best = max(hist)
        return STOP if best < median else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: schedulers/pbt.py): at each perturbation interval,
    bottom-quantile trials exploit a top-quantile trial — clone its latest
    checkpoint and config — then explore by perturbing hyperparameters.

    Exploitation here restarts the trial actor from the donor checkpoint
    (the reference's stop-and-restore path; in-place _exploit is an
    optimization it also only applies with reuse_actors).
    """

    def __init__(self, *, time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 perturbation_factors=(1.2, 0.8),
                 seed: Optional[int] = None):
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self.factors = perturbation_factors
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        self._scores: Dict[str, float] = {}
        # controller inspects this after on_result returns EXPLOIT
        self.pending_exploit: Optional[dict] = None

    EXPLOIT = "EXPLOIT"

    def on_result(self, trial, result: Dict[str, Any]) -> str:
        score = self._score(result)
        if score is not None:
            self._scores[trial.trial_id] = score
        t = result.get(self.time_attr, trial.iterations)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval or len(self._scores) < 2:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        ranked = sorted(self._scores.items(), key=lambda kv: kv[1])
        n = len(ranked)
        k = max(1, int(n * self.quantile))
        bottom = {tid for tid, _ in ranked[:k]}
        top = [tid for tid, _ in ranked[-k:]]
        if trial.trial_id not in bottom or trial.trial_id in top:
            return CONTINUE
        donor_id = self._rng.choice(top)
        self.pending_exploit = {
            "donor_id": donor_id,
            "explore": True,
        }
        return self.EXPLOIT

    def explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        """Perturb mutated hyperparameters (reference: pbt.py _explore)."""
        from ray_tpu.tune.search_space import Domain
        out = dict(config)
        for key, spec in self.mutations.items():
            if self._rng.random() < self.resample_prob or \
                    key not in out or not isinstance(out[key], (int, float)):
                if isinstance(spec, Domain):
                    out[key] = spec.sample(self._rng)
                elif isinstance(spec, list):
                    out[key] = self._rng.choice(spec)
                elif callable(spec):
                    out[key] = spec()
            else:
                factor = self._rng.choice(self.factors)
                out[key] = out[key] * factor
                if isinstance(spec, list):
                    # snap to nearest allowed value
                    out[key] = min(spec, key=lambda v: abs(v - out[key]))
        return out


PAUSE = "PAUSE"


class _Bracket:
    """One synchronous successive-halving bracket (reference:
    hyperband.py Bracket): n0 trials starting at r0 iterations; at each
    rung every live trial pauses until all have reported, then the top
    1/eta continue to the next rung and the rest stop."""

    def __init__(self, s: int, s_max: int, max_t: int, eta: float):
        self.s = s
        self.eta = eta
        self.max_t = max_t
        self.n0 = max(1, math.ceil((s_max + 1) / (s + 1) * eta ** s))
        self.r0 = max(1, int(max_t * eta ** -s))
        self.rung = 0
        self.members: set = set()       # alive trial ids
        self.recorded: Dict[str, float] = {}   # scores at current rung
        self.paused: set = set()

    @property
    def milestone(self) -> int:
        return min(self.max_t, int(self.r0 * self.eta ** self.rung))

    def has_capacity(self) -> bool:
        return len(self.members) < self.n0 and self.rung == 0

    def keep_count(self) -> int:
        return max(1, int(len(self.recorded) / self.eta))

    def promotion_ready(self) -> bool:
        return (self.members
                and all(tid in self.recorded for tid in self.members))


class HyperBandScheduler(TrialScheduler):
    """Synchronous HyperBand (reference: schedulers/hyperband.py
    HyperBandScheduler). Trials are assigned round-robin into brackets
    s = s_max..0; each bracket successively halves at shared milestones.
    Requires checkpointing trainables (paused trials resume from their
    latest checkpoint, like the reference's PAUSE decision)."""

    def __init__(self, *, time_attr: str = "training_iteration",
                 max_t: int = 81, reduction_factor: float = 3.0):
        self.time_attr = time_attr
        self.max_t = max_t
        self.eta = reduction_factor
        self.s_max = int(math.log(max_t) / math.log(self.eta))
        self._brackets: List[_Bracket] = []
        self._next_s = self.s_max
        self._of: Dict[str, _Bracket] = {}
        self._unpause: List[str] = []
        self._stop_parked: List[str] = []

    # -- controller hooks ---------------------------------------------------

    def on_trial_add(self, trial):
        for b in self._brackets:
            if b.has_capacity():
                b.members.add(trial.trial_id)
                self._of[trial.trial_id] = b
                return
        b = _Bracket(self._next_s, self.s_max, self.max_t, self.eta)
        self._next_s = self._next_s - 1 if self._next_s > 0 else self.s_max
        self._brackets.append(b)
        b.members.add(trial.trial_id)
        self._of[trial.trial_id] = b

    def pop_unpaused(self) -> List[str]:
        out, self._unpause = self._unpause, []
        return out

    def pop_parked_stops(self) -> List[str]:
        out, self._stop_parked = self._stop_parked, []
        return out

    # -- decisions ----------------------------------------------------------

    def on_result(self, trial, result: Dict[str, Any]) -> str:
        b = self._of.get(trial.trial_id)
        if b is None:
            self.on_trial_add(trial)
            b = self._of[trial.trial_id]
        t = result.get(self.time_attr, trial.iterations)
        score = self._score(result)
        if score is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        if t < b.milestone:
            return CONTINUE
        b.recorded[trial.trial_id] = score
        if not b.promotion_ready():
            return PAUSE  # wait for bracket peers at this rung
        return self._promote(b, trial.trial_id)

    def _promote(self, b: _Bracket, reporter_id: str) -> str:
        """All bracket members reached the rung: keep the top 1/eta."""
        ranked = sorted(b.recorded.items(), key=lambda kv: -kv[1])
        keep = {tid for tid, _ in ranked[:b.keep_count()]}
        for tid in list(b.members):
            if tid == reporter_id:
                continue
            if tid in keep:
                if tid in b.paused:
                    b.paused.discard(tid)
                    self._unpause.append(tid)
            else:
                b.members.discard(tid)
                self._of.pop(tid, None)
                if tid in b.paused:
                    b.paused.discard(tid)
                    self._stop_parked.append(tid)
        b.rung += 1
        b.recorded = {}
        if reporter_id in keep:
            return CONTINUE
        b.members.discard(reporter_id)
        self._of.pop(reporter_id, None)
        return STOP

    def on_trial_complete(self, trial):
        """A member left (finished/errored): don't deadlock its bracket."""
        b = self._of.pop(trial.trial_id, None)
        if b is None:
            return
        b.members.discard(trial.trial_id)
        b.recorded.pop(trial.trial_id, None)
        b.paused.discard(trial.trial_id)
        if b.promotion_ready():
            # promote on behalf of a phantom reporter
            self._promote(b, reporter_id="__gone__")

    def note_paused(self, trial_id: str):
        b = self._of.get(trial_id)
        if b is not None:
            b.paused.add(trial_id)


class HyperBandForBOHB(HyperBandScheduler):
    """HyperBand variant pairing with the BOHB searcher (reference:
    schedulers/hb_bohb.py HyperBandForBOHB): identical bracket mechanics;
    trials are filled into ONE bracket at a time (the reference processes
    brackets sequentially so the model-based searcher sees each budget's
    results before proposing the next batch)."""

    def on_trial_add(self, trial):
        if self._brackets and self._brackets[-1].has_capacity():
            b = self._brackets[-1]
            b.members.add(trial.trial_id)
            self._of[trial.trial_id] = b
            return
        super().on_trial_add(trial)


class PB2(PopulationBasedTraining):
    """Population-Based Bandits (reference: schedulers/pb2.py:256
    PB2): PBT where EXPLORE picks new hyperparameters with a Gaussian-
    process UCB bandit fit to observed (config, score-delta) data,
    instead of random perturbation — far more sample-efficient for small
    populations.

    ``hyperparam_bounds`` maps each tuned key to [low, high]."""

    def __init__(self, *, hyperparam_bounds: Dict[str, Any],
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 quantile_fraction: float = 0.25,
                 seed: Optional[int] = None):
        super().__init__(
            time_attr=time_attr,
            perturbation_interval=perturbation_interval,
            hyperparam_mutations={},
            quantile_fraction=quantile_fraction, seed=seed)
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        self._obs_x: List[List[float]] = []   # normalized configs
        self._obs_y: List[float] = []         # score deltas
        self._prev_score: Dict[str, float] = {}

    def on_result(self, trial, result: Dict[str, Any]) -> str:
        score = self._score(result)
        if score is not None:
            prev = self._prev_score.get(trial.trial_id)
            if prev is not None:
                self._record(trial.config, score - prev)
            self._prev_score[trial.trial_id] = score
        decision = super().on_result(trial, result)
        if decision == self.EXPLOIT:
            # the trial restarts from the DONOR's checkpoint: its next
            # score delta reflects the clone, not the explored config —
            # it must not be attributed to the new config
            self._prev_score.pop(trial.trial_id, None)
        return decision

    def on_trial_complete(self, trial):
        self._prev_score.pop(trial.trial_id, None)
        super().on_trial_complete(trial)

    # -- GP-UCB explore ------------------------------------------------------

    def _norm(self, config: Dict[str, Any]) -> List[float]:
        out = []
        for k, (lo, hi) in self.bounds.items():
            v = float(config.get(k, lo))
            out.append((v - lo) / (hi - lo) if hi > lo else 0.0)
        return out

    def _record(self, config: Dict[str, Any], dy: float):
        self._obs_x.append(self._norm(config))
        self._obs_y.append(dy)
        if len(self._obs_y) > 256:   # bounded fit cost
            self._obs_x = self._obs_x[-256:]
            self._obs_y = self._obs_y[-256:]

    def explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        import numpy as np

        out = dict(config)
        keys = list(self.bounds.keys())
        if len(self._obs_y) < 4:
            for k in keys:  # cold start: uniform sample
                lo, hi = self.bounds[k]
                out[k] = lo + (hi - lo) * self._rng.random()
            return out
        X = np.asarray(self._obs_x)
        y = np.asarray(self._obs_y)
        y = (y - y.mean()) / (y.std() + 1e-9)
        # RBF-kernel GP posterior (reference fits TV-SquaredExp; plain
        # RBF keeps the bandit while staying dependency-free)
        ls, noise = 0.2, 1e-2
        def k(a, b):
            d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
            return np.exp(-d2 / (2 * ls * ls))
        K = k(X, X) + noise * np.eye(len(X))
        Kinv = np.linalg.inv(K)
        cand = np.asarray([[self._rng.random() for _ in keys]
                           for _ in range(64)])
        Ks = k(cand, X)
        mu = Ks @ Kinv @ y
        var = np.clip(1.0 - np.einsum("ij,jk,ik->i", Ks, Kinv, Ks),
                      1e-9, None)
        beta = math.sqrt(2 * math.log(len(self._obs_y) + 1))
        best = cand[int(np.argmax(mu + beta * np.sqrt(var)))]
        for k_, u in zip(keys, best):
            lo, hi = self.bounds[k_]
            out[k_] = lo + (hi - lo) * float(u)
        return out


REALLOC = "REALLOC"


def evenly_distribute_cpus(total_cpus: float, num_running: int,
                           trial, base: Dict[str, Any]
                           ) -> Dict[str, Any]:
    """Default allocation policy (reference: the DistributeResources
    function in tune/schedulers/resource_changing_scheduler.py): spread
    the cluster's CPUs evenly over the trials still running, never below
    the trial's base request."""
    if num_running <= 0:
        return dict(base)
    share = max(float(base.get("num_cpus", 1)), total_cpus // num_running)
    out = dict(base)
    out["num_cpus"] = share
    return out


class ResourceChangingScheduler(TrialScheduler):
    """Reallocate trial resources mid-experiment (reference:
    tune/schedulers/resource_changing_scheduler.py:ResourceChangingScheduler).

    Wraps a base scheduler (default FIFO): every decision is the base
    scheduler's; after a CONTINUE, ``resources_allocation_function(
    total_cpus, running_trials, trial, base_resources)`` may return a new
    resource dict for the trial. A change checkpoints the trial, stops
    its actor, and requeues it so it restarts under the new allocation —
    the same restart path PBT exploitation uses.
    """

    def __init__(self, base_scheduler: Optional[TrialScheduler] = None,
                 resources_allocation_function=None):
        self.base = base_scheduler or FIFOScheduler()
        self._alloc = resources_allocation_function
        self._pending: Dict[str, Dict[str, Any]] = {}
        self._running_ids: set = set()
        self._running_count_fn = None
        self._realloc_count = 0

    def set_experiment(self, metric: str, mode: str):
        super().set_experiment(metric, mode)
        self.base.set_experiment(metric, mode)

    def __getattr__(self, name):
        # Delegate base-scheduler-specific surface the controller probes
        # for (on_trial_add, HyperBand's pause bookkeeping, PBT's
        # explore) so wrapping changes no behavior of the wrapped one.
        if name.startswith("_") or name == "base":
            raise AttributeError(name)
        return getattr(self.base, name)

    # PBT's exploit protocol: the controller both reads AND assigns
    # pending_exploit, so a plain __getattr__ forward is not enough —
    # the property keeps reads/writes on the wrapped scheduler.
    @property
    def pending_exploit(self):
        return getattr(self.base, "pending_exploit", None)

    @pending_exploit.setter
    def pending_exploit(self, value):
        self.base.pending_exploit = value

    def on_trial_complete(self, trial):
        self._running_ids.discard(trial.trial_id)
        self.base.on_trial_complete(trial)

    def pop_realloc(self, trial_id: str) -> Optional[Dict[str, Any]]:
        return self._pending.pop(trial_id, None)

    def set_cluster_view(self, total_cpus: float, base_resources: dict,
                         running_count_fn=None):
        """Called by the controller before the run loop starts.
        ``running_count_fn`` reports the live number of RUNNING trials
        (the controller knows; reported-once bookkeeping here would
        hand the first reporter the whole cluster)."""
        self._total_cpus = float(total_cpus)
        self._base_resources = dict(base_resources)
        self._running_count_fn = running_count_fn

    def _num_running(self) -> int:
        if self._running_count_fn is not None:
            try:
                return max(1, int(self._running_count_fn()))
            except Exception:  # noqa: BLE001
                pass
        return max(1, len(self._running_ids))

    def on_result(self, trial, result: Dict[str, Any]) -> str:
        self._running_ids.add(trial.trial_id)
        decision = self.base.on_result(trial, result)
        if decision != CONTINUE or self._alloc is None:
            if decision in (STOP, PAUSE):
                self._running_ids.discard(trial.trial_id)
            return decision
        base = dict(getattr(self, "_base_resources", {}) or
                    {"num_cpus": 1})
        want = self._alloc(getattr(self, "_total_cpus", 1.0),
                           self._num_running(), trial, base)
        # normalize both sides over the base keys: partial dicts from
        # the allocation function must not oscillate vs the stored state
        want = {**base, **(want or {})}
        have = {**base, **(trial.resources or {})}
        if want != have:
            self._pending[trial.trial_id] = want
            self._realloc_count += 1
            return REALLOC
        return decision
