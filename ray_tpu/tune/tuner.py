"""Tuner + TuneConfig + ResultGrid.

Reference: python/ray/tune/tuner.py:44 (Tuner.fit / Tuner.restore),
tune/tune_config.py, tune/result_grid.py. Trainables may be plain
functions ``fn(config)`` calling ``tune.report`` or DataParallelTrainer
instances (run per-trial with the trial's config merged into
train_loop_config).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.tune.schedulers import TrialScheduler
from ray_tpu.tune.trial import Trial, TrialStatus
from ray_tpu.tune.tune_controller import TuneController


@dataclass
class TuneConfig:
    """Reference: tune/tune_config.py."""

    metric: str = "score"
    mode: str = "max"
    num_samples: int = 1
    scheduler: Optional[TrialScheduler] = None
    search_alg: Optional[Any] = None  # a tune.search.Searcher
    max_concurrent_trials: Optional[int] = None
    seed: Optional[int] = None
    trial_resources: Dict[str, Any] = field(default_factory=dict)


class Result:
    def __init__(self, trial: Trial):
        self.metrics = trial.last_result
        self.config = trial.config
        self.error = trial.error
        self.checkpoint = None
        if trial.checkpoint_path:
            from ray_tpu.train.checkpoint import Checkpoint
            self.checkpoint = Checkpoint(trial.checkpoint_path)
        self.metrics_history = trial.metric_history
        self.trial_id = trial.trial_id
        self.terminated = trial.status == TrialStatus.TERMINATED

    def __repr__(self):
        return f"Result({self.trial_id}, metrics={self.metrics})"


class ResultGrid:
    """Reference: tune/result_grid.py."""

    def __init__(self, trials: List[Trial], metric: str, mode: str):
        self._trials = trials
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._trials)

    def __getitem__(self, i) -> Result:
        return Result(self._trials[i])

    @property
    def errors(self) -> List[str]:
        return [t.error for t in self._trials if t.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        scored = [t for t in self._trials
                  if t.last_result.get(metric) is not None]
        if not scored:
            raise RuntimeError("No trial reported metric "
                               f"{metric!r}; errors: {self.errors}")
        best = (max if mode == "max" else min)(
            scored, key=lambda t: t.last_result[metric])
        return Result(best)

    def get_dataframe(self):
        import pandas as pd
        rows = []
        for t in self._trials:
            row = dict(t.last_result)
            row.update({f"config/{k}": v for k, v in t.config.items()
                        if not isinstance(v, (dict, list))})
            row["trial_id"] = t.trial_id
            row["status"] = t.status.value
            rows.append(row)
        return pd.DataFrame(rows)


def _trainable_of(obj) -> Callable:
    """Normalize a Tuner target to fn(config)."""
    from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
    if isinstance(obj, DataParallelTrainer):
        trainer = obj

        def run_trainer(config):
            import copy
            from ray_tpu.tune.trial import report, get_trial_dir
            t = copy.copy(trainer)
            t.train_loop_config = {**trainer.train_loop_config, **config}
            rc = copy.copy(trainer.run_config)
            rc.storage_path = get_trial_dir()
            rc.name = "trainer"
            t.run_config = rc
            result = t.fit()
            if result.error is not None:
                raise result.error
            metrics = dict(result.metrics)
            ckpt = result.checkpoint.path if result.checkpoint else None
            report(metrics, checkpoint=ckpt)

        return run_trainer
    if callable(obj):
        return obj
    raise TypeError(f"Cannot use {type(obj)} as a trainable")


class Tuner:
    def __init__(self, trainable, *, param_space: Optional[Dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config=None,
                 _restore_path: Optional[str] = None):
        from ray_tpu.train.config import RunConfig
        self._trainable = trainable
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()
        self._restore_path = _restore_path

    def _experiment_dir(self) -> str:
        from ray_tpu.train.storage import StorageContext
        base = self._run_config.resolved_storage_path()
        name = self._run_config.name or "tune_experiment"
        return os.path.join(base, name)

    def fit(self) -> ResultGrid:
        cfg = self._tune_config
        fc = getattr(self._run_config, "failure_config", None)
        controller = TuneController(
            _trainable_of(self._trainable),
            param_space=self._param_space,
            metric=cfg.metric, mode=cfg.mode,
            num_samples=cfg.num_samples,
            scheduler=cfg.scheduler,
            search_alg=cfg.search_alg,
            max_concurrent_trials=cfg.max_concurrent_trials,
            max_failures=fc.max_failures if fc else 0,
            experiment_dir=self._experiment_dir(),
            trial_resources=cfg.trial_resources,
            stop=getattr(self._run_config, "stop", None),
            seed=cfg.seed)
        if self._restore_path:
            state_file = os.path.join(self._restore_path,
                                      "experiment_state.json")
            with open(state_file) as f:
                state = json.load(f)
            controller.restore_trials(state["trials"])
        trials = controller.run()
        return ResultGrid(trials, cfg.metric, cfg.mode)

    @classmethod
    def restore(cls, path: str, trainable, *,
                param_space: Optional[Dict] = None,
                tune_config: Optional[TuneConfig] = None,
                run_config=None) -> "Tuner":
        """Resume an interrupted experiment: finished trials keep their
        results, unfinished ones restart (from their latest checkpoint if
        they saved one). Reference: Tuner.restore (tuner.py)."""
        from ray_tpu.train.config import RunConfig
        state_file = os.path.join(path, "experiment_state.json")
        with open(state_file) as f:
            state = json.load(f)
        tc = tune_config or TuneConfig(metric=state["metric"],
                                       mode=state["mode"])
        rc = run_config or RunConfig(
            storage_path=os.path.dirname(path.rstrip("/")),
            name=os.path.basename(path.rstrip("/")))
        return cls(trainable, param_space=param_space or {},
                   tune_config=tc, run_config=rc, _restore_path=path)
