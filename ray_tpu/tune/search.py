"""Pluggable search algorithms (reference: python/ray/tune/search/ —
Searcher base searcher.py:40, BasicVariantGenerator basic_variant.py, and
the Optuna/HyperOpt integrations whose role the built-in TPE fills here,
since no external search library ships in this image).

A Searcher proposes configs one trial at a time and learns from completed
results, so proposals sharpen as the experiment progresses (vs the
variant generator's up-front sampling).
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.search_space import (Categorical, Domain, Float, Integer,
                                       _is_grid, generate_variants)


class Searcher:
    """Interface: suggest(trial_id) -> config | None (None = budget done);
    on_trial_complete(trial_id, result) feeds the metric back."""

    def set_experiment(self, space: Dict[str, Any], metric: str, mode: str,
                       num_samples: int, seed: Optional[int]):
        self._space = space
        self._metric = metric
        self._mode = mode
        self._num_samples = num_samples
        self._seed = seed

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]):
        pass

    def on_restore(self, num_existing: int):
        """Called after an experiment restore with the number of trials
        already created, so the suggestion budget accounts for them."""
        pass


class BasicVariantGenerator(Searcher):
    """Random/grid sampling behind the Searcher interface (reference:
    tune/search/basic_variant.py)."""

    def set_experiment(self, space, metric, mode, num_samples, seed):
        super().set_experiment(space, metric, mode, num_samples, seed)
        self._variants = generate_variants(space, num_samples, seed)

    def suggest(self, trial_id: str):
        try:
            return next(self._variants)
        except StopIteration:
            return None

    def on_restore(self, num_existing: int):
        for _ in range(num_existing):
            next(self._variants, None)


def _flatten(space: Dict[str, Any], prefix: Tuple[str, ...] = ()
             ) -> Dict[Tuple[str, ...], Any]:
    out: Dict[Tuple[str, ...], Any] = {}
    for k, v in space.items():
        if isinstance(v, dict) and not _is_grid(v):
            out.update(_flatten(v, prefix + (k,)))
        else:
            out[prefix + (k,)] = v
    return out


def _set_path(cfg: Dict[str, Any], path: Tuple[str, ...], value: Any):
    d = cfg
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator (independent per dimension).

    Completed trials are split into good/bad by the gamma quantile of the
    metric; numeric dimensions model each group with a Gaussian KDE and
    propose the candidate maximizing l(x)/g(x); categorical dimensions use
    smoothed count ratios. The first ``n_startup`` trials sample randomly.
    """

    def __init__(self, n_startup: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24):
        self._n_startup = n_startup
        self._gamma = gamma
        self._n_cand = n_candidates
        self._obs: List[Tuple[Dict[Tuple[str, ...], Any], float]] = []
        self._configs: Dict[str, Dict[str, Any]] = {}
        self._count = 0

    def set_experiment(self, space, metric, mode, num_samples, seed):
        super().set_experiment(space, metric, mode, num_samples, seed)
        self._rng = random.Random(seed)
        self._dims = _flatten(space)
        for path, dom in self._dims.items():
            if _is_grid(dom):
                raise ValueError(
                    f"TPESearcher does not support grid_search (at "
                    f"{'.'.join(path)}); use tune.choice() so the searcher "
                    f"can model the dimension")

    # ---- proposal -----------------------------------------------------------

    def suggest(self, trial_id: str):
        if self._count >= self._num_samples:
            return None
        self._count += 1
        cfg: Dict[str, Any] = {}
        use_tpe = len(self._obs) >= self._n_startup
        split = self._split() if use_tpe else None
        flat: Dict[Tuple[str, ...], Any] = {}
        for path, dom in self._dims.items():
            if isinstance(dom, (Float, Integer)) and use_tpe:
                value = self._suggest_numeric(path, dom, split)
            elif isinstance(dom, Categorical) and use_tpe:
                value = self._suggest_categorical(path, dom, split)
            elif isinstance(dom, Domain):
                value = dom.sample(self._rng)
            else:
                value = dom  # constant
            flat[path] = value
            _set_path(cfg, path, value)
        self._configs[trial_id] = flat
        return cfg

    def _split(self):
        ordered = sorted(self._obs, key=lambda o: o[1],
                         reverse=(self._mode == "max"))
        n_good = max(1, int(math.ceil(self._gamma * len(ordered))))
        return ordered[:n_good], ordered[n_good:]

    def _suggest_numeric(self, path, dom, split):
        good, bad = split
        log = getattr(dom, "log", False)

        def xform(v):
            return math.log(v) if log else float(v)

        lo, hi = xform(dom.lower), xform(dom.upper)
        if hi <= lo:  # degenerate domain: only one value exists
            return dom.sample(self._rng)
        gx = [xform(o[0][path]) for o in good if path in o[0]]
        bx = [xform(o[0][path]) for o in bad if path in o[0]]
        if not gx:
            return dom.sample(self._rng)
        # Scott-style bandwidth from the GOOD points' spread (floored):
        # the old (hi-lo)/sqrt(n) rule stayed range-wide for small n, and
        # clamping its out-of-range samples piled candidate mass on the
        # domain boundaries — an artificial attractor at lo/hi.
        if len(gx) > 1:
            mean = sum(gx) / len(gx)
            std = (sum((v - mean) ** 2 for v in gx) / len(gx)) ** 0.5
        else:
            std = 0.0
        bw = max(std * len(gx) ** -0.2, 0.05 * (hi - lo), 1e-12)

        def kde(xs, x):
            if not xs:
                return 1.0 / (hi - lo)
            s = sum(math.exp(-0.5 * ((x - xi) / bw) ** 2) for xi in xs)
            return s / (len(xs) * bw * math.sqrt(2 * math.pi)) + 1e-12

        best_x, best_score = None, -1.0
        for _ in range(self._n_cand):
            center = self._rng.choice(gx)
            # rejection sampling keeps the proposal INSIDE the domain
            # without boundary pile-up; fall back to clamp if unlucky
            for _try in range(8):
                x = self._rng.gauss(center, bw)
                if lo <= x <= hi:
                    break
            else:
                x = min(hi, max(lo, x))
            score = kde(gx, x) / kde(bx, x)
            if score > best_score:
                best_x, best_score = x, score
        v = math.exp(best_x) if log else best_x
        if isinstance(dom, Integer):
            return max(dom.lower, min(dom.upper - 1, int(round(v))))
        if getattr(dom, "q", None):
            v = round(v / dom.q) * dom.q
        return min(dom.upper, max(dom.lower, v))

    def _suggest_categorical(self, path, dom, split):
        good, bad = split
        cats = dom.categories

        def counts(obs):
            c = {repr(v): 1.0 for v in cats}  # +1 smoothing
            for o in obs:
                if path in o[0]:
                    c[repr(o[0][path])] = c.get(repr(o[0][path]), 1.0) + 1
            total = sum(c.values())
            return {k: v / total for k, v in c.items()}

    # pick the category maximizing p_good/p_bad
        pg, pb = counts(good), counts(bad)
        return max(cats, key=lambda v: pg[repr(v)] / pb[repr(v)])

    # ---- feedback -----------------------------------------------------------

    def on_trial_complete(self, trial_id, result):
        flat = self._configs.pop(trial_id, None)
        if flat is None or not result:
            return
        score = result.get(self._metric)
        if score is None:
            return
        self._obs.append((flat, float(score)))

    def observe(self, config: Dict[str, Any], score: float):
        """Feed an externally-known (config, score) pair — used when an
        interrupted experiment is restored."""
        self._obs.append((_flatten(config), float(score)))

    def register(self, trial_id: str, config: Dict[str, Any]):
        """Make an externally-created trial's config known so its eventual
        on_trial_complete lands in the model (restored in-flight trials)."""
        self._configs[trial_id] = _flatten(config)

    def on_restore(self, num_existing: int):
        self._count = max(self._count, num_existing)


class BOHBSearcher(TPESearcher):
    """Model-based half of BOHB (reference: the TuneBOHB searcher paired
    with schedulers/hb_bohb.py). BOHB fits its KDE model PER BUDGET and
    proposes from the largest budget with enough observations — a trial
    HyperBand stopped at a low rung reports a low score because of its
    short BUDGET, not its config, so mixing budgets in one model (plain
    TPE) poisons it. Observations are bucketed by training_iteration;
    ``suggest`` rebuilds the TPE observation set from the deepest bucket
    that has at least ``n_startup`` entries before proposing."""

    def __init__(self, n_startup: int = 6, gamma: float = 0.25,
                 n_candidates: int = 64):
        super().__init__(n_startup=n_startup, gamma=gamma,
                         n_candidates=n_candidates)
        self._by_budget: Dict[int, List[Tuple[dict, float]]] = {}

    def on_trial_complete(self, trial_id, result):
        flat = self._configs.pop(trial_id, None)
        if flat is None or not result:
            return
        score = result.get(self._metric)
        if score is None:
            return
        budget = int(result.get("training_iteration", 1))
        self._by_budget.setdefault(budget, []).append(
            (flat, float(score)))

    _RESTORED_BUDGET = 1 << 30  # restored trials ran to completion

    def observe(self, config, score):
        """Restored-experiment history (TuneController.restore_trials):
        completed trials count as deepest-budget observations so a
        restored BOHB search keeps its model instead of restarting
        random."""
        self._by_budget.setdefault(self._RESTORED_BUDGET, []).append(
            (_flatten(config), float(score)))

    def suggest(self, trial_id: str):
        # model on the deepest budget with enough data (reference: BOHB's
        # "use the KDE of the highest budget with sufficient points")
        self._obs = []
        for budget in sorted(self._by_budget, reverse=True):
            bucket = self._by_budget[budget]
            if len(bucket) >= max(4, self._n_startup // 2):
                self._obs = list(bucket)
                break
        else:
            # not enough at any single budget yet: pool the deepest few
            for budget in sorted(self._by_budget, reverse=True):
                self._obs.extend(self._by_budget[budget])
        return super().suggest(trial_id)


class GPSearcher(Searcher):
    """Gaussian-process Bayesian optimization with Expected Improvement
    (reference role: tune/search/bayesopt/bayesopt_search.py, which
    wraps the external ``bayesian-optimization`` package — this is the
    in-tree numpy implementation, closing the capability on merit since
    no external searcher library ships in this image).

    Model: zero-mean GP over the unit-cube encoding of the search space
    (numeric dims min-max scaled, log-aware; categoricals one-hot) with
    an RBF kernel and Cholesky-solved exact posterior; acquisition is
    Expected Improvement maximized over random candidates. Trials before
    ``n_startup`` sample randomly.
    """

    def __init__(self, n_startup: int = 6, n_candidates: int = 512,
                 length_scale: float = 0.25, noise: float = 1e-5,
                 xi: float = 0.01):
        self._n_startup = n_startup
        self._n_cand = n_candidates
        self._ls = float(length_scale)
        self._noise = float(noise)
        self._xi = float(xi)
        self._obs: List[Tuple[Dict[Tuple[str, ...], Any], float]] = []
        self._configs: Dict[str, Dict[Tuple[str, ...], Any]] = {}
        self._count = 0

    def set_experiment(self, space, metric, mode, num_samples, seed):
        super().set_experiment(space, metric, mode, num_samples, seed)
        self._rng = random.Random(seed)
        self._dims = _flatten(space)
        for path, dom in self._dims.items():
            if _is_grid(dom):
                raise ValueError(
                    f"GPSearcher does not support grid_search (at "
                    f"{'.'.join(path)}); use tune.choice() so the "
                    f"searcher can model the dimension")

    # ---- encoding -----------------------------------------------------------

    def _encode(self, flat: Dict[Tuple[str, ...], Any]) -> List[float]:
        x: List[float] = []
        for path, dom in sorted(self._dims.items()):
            v = flat.get(path)
            if isinstance(dom, (Float, Integer)):
                log = getattr(dom, "log", False)
                lo = math.log(dom.lower) if log else float(dom.lower)
                hi = math.log(dom.upper) if log else float(dom.upper)
                vv = math.log(v) if log else float(v)
                x.append((vv - lo) / (hi - lo) if hi > lo else 0.0)
            elif isinstance(dom, Categorical):
                for c in dom.categories:
                    x.append(1.0 if repr(v) == repr(c) else 0.0)
            # constants carry no information: skip
        return x

    # ---- proposal -----------------------------------------------------------

    def suggest(self, trial_id: str):
        if self._count >= self._num_samples:
            return None
        self._count += 1
        if len(self._obs) < self._n_startup:
            flat = {p: (d.sample(self._rng) if isinstance(d, Domain)
                        else d)
                    for p, d in self._dims.items()}
        else:
            flat = self._suggest_ei()
        cfg: Dict[str, Any] = {}
        for path, value in flat.items():
            _set_path(cfg, path, value)
        self._configs[trial_id] = flat
        return cfg

    def _suggest_ei(self) -> Dict[Tuple[str, ...], Any]:
        import numpy as np

        # internal convention: MINIMIZE standardized y
        ys = np.array([o[1] for o in self._obs], dtype=np.float64)
        if self._mode == "max":
            ys = -ys
        mu0, sd0 = float(ys.mean()), float(ys.std()) or 1.0
        ys = (ys - mu0) / sd0
        X = np.array([self._encode(o[0]) for o in self._obs],
                     dtype=np.float64)
        n, d = X.shape
        ls = self._ls * max(1.0, math.sqrt(d))

        def k(a, b):
            d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2 / (ls * ls))

        K = k(X, X) + self._noise * np.eye(n)
        L = np.linalg.cholesky(K)
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, ys))

        cands = [{p: (dom.sample(self._rng) if isinstance(dom, Domain)
                      else dom)
                  for p, dom in self._dims.items()}
                 for _ in range(self._n_cand)]
        Xc = np.array([self._encode(c) for c in cands], dtype=np.float64)
        Kc = k(Xc, X)                                  # [m, n]
        mu = Kc @ alpha
        v = np.linalg.solve(L, Kc.T)                   # [n, m]
        var = np.maximum(1.0 - (v * v).sum(0), 1e-12)
        s = np.sqrt(var)
        best = ys.min()
        z = (best - mu - self._xi) / s
        erf = np.vectorize(math.erf)
        cdf = 0.5 * (1.0 + erf(z / math.sqrt(2.0)))
        pdf = np.exp(-0.5 * z * z) / math.sqrt(2.0 * math.pi)
        ei = (best - mu - self._xi) * cdf + s * pdf
        return cands[int(np.argmax(ei))]

    # ---- feedback (same protocol as TPESearcher) ---------------------------

    def on_trial_complete(self, trial_id, result):
        flat = self._configs.pop(trial_id, None)
        if flat is None or not result:
            return
        score = result.get(self._metric)
        if score is None:
            return
        self._obs.append((flat, float(score)))

    def observe(self, config: Dict[str, Any], score: float):
        self._obs.append((_flatten(config), float(score)))

    def register(self, trial_id: str, config: Dict[str, Any]):
        self._configs[trial_id] = _flatten(config)

    def on_restore(self, num_existing: int):
        self._count = max(self._count, num_existing)
