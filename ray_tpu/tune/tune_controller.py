"""TuneController: the experiment event loop.

Reference: python/ray/tune/execution/tune_controller.py:68 — manages trials
as actors, polls results, applies scheduler decisions, persists experiment
state, and retries failed trials. One in-flight ``ack_and_next`` call per
running trial; ray_tpu.wait multiplexes across them.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.tune.schedulers import (CONTINUE, PAUSE, REALLOC, STOP,
                                     FIFOScheduler,
                                     PopulationBasedTraining, TrialScheduler)
from ray_tpu.tune.trial import Trial, TrialActor, TrialStatus


class TuneController:
    def __init__(self, trainable, *, param_space: Dict[str, Any],
                 metric: str = "score", mode: str = "max",
                 num_samples: int = 1,
                 scheduler: Optional[TrialScheduler] = None,
                 search_alg=None,
                 max_concurrent_trials: Optional[int] = None,
                 max_failures: int = 0,
                 experiment_dir: str = "",
                 trial_resources: Optional[dict] = None,
                 stop: Optional[Dict[str, Any]] = None,
                 seed: Optional[int] = None):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self._trainable = trainable
        self._metric = metric
        self._mode = mode
        self._scheduler = scheduler or FIFOScheduler()
        self._scheduler.set_experiment(metric, mode)
        self._max_concurrent = max_concurrent_trials or 4
        self._max_failures = max_failures
        self._experiment_dir = experiment_dir
        self._trial_resources = trial_resources or {}
        self._stop_criteria = stop or {}
        os.makedirs(experiment_dir, exist_ok=True)

        # With a search algorithm, trials are created LAZILY so each
        # suggestion can learn from completed results (reference:
        # tune/search/searcher.py); otherwise variants are pre-generated.
        self._searcher = search_alg
        self._next_trial_idx = 0
        if search_alg is not None:
            search_alg.set_experiment(param_space, metric, mode,
                                      num_samples, seed)
            self.trials: List[Trial] = []
        else:
            from ray_tpu.tune.search_space import generate_variants
            self.trials = [
                Trial(trial_id=f"trial_{i:05d}", config=cfg)
                for i, cfg in enumerate(
                    generate_variants(param_space, num_samples, seed))
            ]
            self._next_trial_idx = len(self.trials)

    def _maybe_suggest_trial(self) -> Optional[Trial]:
        if self._searcher is None:
            return None
        trial_id = f"trial_{self._next_trial_idx:05d}"
        cfg = self._searcher.suggest(trial_id)
        if cfg is None:
            return None
        self._next_trial_idx += 1
        trial = Trial(trial_id=trial_id, config=cfg)
        self.trials.append(trial)
        return trial

    # ------------------------------------------------------------- running

    def restore_trials(self, snapshots: List[dict]):
        if self._searcher is not None:
            # Searcher mode creates trials lazily, so restored trials are
            # reconstructed directly from their snapshots; completed ones
            # are fed back so the searcher resumes with full history.
            for s in snapshots:
                r = Trial.from_snapshot(s)
                if not r.is_finished:
                    r.status = TrialStatus.PENDING
                    register = getattr(self._searcher, "register", None)
                    if register is not None:
                        register(r.trial_id, r.config)
                self.trials.append(r)
                try:
                    idx = int(r.trial_id.rsplit("_", 1)[-1]) + 1
                    self._next_trial_idx = max(self._next_trial_idx, idx)
                except ValueError:
                    pass
                if r.is_finished and r.last_result:
                    score = r.last_result.get(self._metric)
                    observe = getattr(self._searcher, "observe", None)
                    if observe is not None and score is not None:
                        observe(r.config, score)
            on_restore = getattr(self._searcher, "on_restore", None)
            if on_restore is not None:
                on_restore(len(self.trials))
            return
        restored = {s["trial_id"]: s for s in snapshots}
        for t in self.trials:
            snap = restored.get(t.trial_id)
            if snap:
                r = Trial.from_snapshot(snap)
                # Keep the recorded config: fresh variant generation may
                # have re-sampled random leaves differently.
                t.config = r.config
                if r.is_finished:
                    t.status = r.status
                    t.last_result = r.last_result
                    t.error = r.error
                    t.iterations = r.iterations
                t.checkpoint_path = r.checkpoint_path
                t.resources = r.resources

    def run(self) -> List[Trial]:
        view = getattr(self._scheduler, "set_cluster_view", None)
        if view is not None:
            from ray_tpu import state
            try:
                total = state.cluster_resources().get("CPU", 1.0)
            except Exception:  # noqa: BLE001 — view is best-effort
                total = 1.0
            view(total, self._trial_resources or {"num_cpus": 1},
                 lambda: self._num_live)
        self._num_live = 0
        pending = [t for t in self.trials if not t.is_finished]
        for t in pending:
            self._notify_added(t)
        running: Dict[Any, Trial] = {}  # pending_result ref -> trial
        parked: Dict[str, Trial] = {}   # PAUSED, awaiting the scheduler
        exhausted = False
        try:
            while True:
                while len(pending) + len(running) < self._max_concurrent \
                        and not exhausted:
                    t = self._maybe_suggest_trial()
                    if t is None:
                        exhausted = True
                    else:
                        self._notify_added(t)
                        pending.append(t)
                self._drain_parked(parked, pending)
                if not (pending or running or parked):
                    if self._searcher is None or exhausted:
                        break
                while pending and len(running) < self._max_concurrent:
                    trial = pending.pop(0)
                    self._start_trial(trial)
                    running[trial.pending_result] = trial
                if not running:
                    if parked:
                        # nothing can progress and the scheduler released
                        # nobody (e.g. bracket peers all errored):
                        # fail-safe unpause everyone rather than hang
                        for t in parked.values():
                            t.status = TrialStatus.PENDING
                            pending.append(t)
                        parked.clear()
                        continue
                    break
                self._num_live = len(running)
                ready, _ = ray_tpu.wait(list(running.keys()),
                                        num_returns=1, timeout=5.0)
                for ref in ready:
                    trial = running.pop(ref)
                    requeue = self._process(trial)
                    if requeue == "requeue":
                        pending.append(trial)
                    elif requeue == "park":
                        parked[trial.trial_id] = trial
                    elif not trial.is_finished:
                        running[trial.pending_result] = trial
                self._checkpoint_experiment()
        finally:
            for trial in running.values():
                self._kill_actor(trial)
            self._checkpoint_experiment()
        return self.trials

    def _notify_added(self, trial: Trial):
        hook = getattr(self._scheduler, "on_trial_add", None)
        if hook is not None:
            hook(trial)

    def _drain_parked(self, parked: Dict[str, Trial],
                      pending: List[Trial]):
        """Apply the scheduler's verdicts for paused trials (HyperBand
        releases a bracket's survivors once all peers hit the rung)."""
        sched = self._scheduler
        for tid in (sched.pop_unpaused()
                    if hasattr(sched, "pop_unpaused") else []):
            t = parked.pop(tid, None)
            if t is not None:
                t.status = TrialStatus.PENDING
                pending.append(t)
        for tid in (sched.pop_parked_stops()
                    if hasattr(sched, "pop_parked_stops") else []):
            t = parked.pop(tid, None)
            if t is not None:
                t.status = TrialStatus.TERMINATED
                sched.on_trial_complete(t)
                if self._searcher is not None:
                    self._searcher.on_trial_complete(t.trial_id,
                                                     t.last_result)

    # ------------------------------------------------------------ internals

    def _start_trial(self, trial: Trial, action: str = "continue"):
        trial_dir = os.path.join(self._experiment_dir, trial.trial_id)
        opts = dict(self._trial_resources)
        opts.update(trial.resources or {})
        trial.actor = TrialActor.options(**opts).remote(
            self._trainable, trial.config, trial_dir,
            checkpoint_path=trial.checkpoint_path)
        ray_tpu.get(trial.actor.start.remote())
        trial.status = TrialStatus.RUNNING
        trial.pending_result = trial.actor.ack_and_next.remote()

    def _process(self, trial: Trial) -> Optional[str]:
        try:
            kind, metrics, ckpt = ray_tpu.get(trial.pending_result)
        except Exception as e:  # actor/worker death
            return self._on_error(trial, repr(e))
        if kind == "error":
            return self._on_error(trial, metrics.get("error", "unknown"),
                                  metrics.get("traceback"))
        if kind in ("done", "stopped"):
            trial.status = TrialStatus.TERMINATED
            self._scheduler.on_trial_complete(trial)
            if self._searcher is not None:
                self._searcher.on_trial_complete(trial.trial_id,
                                                 trial.last_result)
            self._kill_actor(trial)
            return None

        # kind == "result"
        trial.iterations += 1
        metrics.setdefault("training_iteration", trial.iterations)
        metrics["trial_id"] = trial.trial_id
        trial.last_result = metrics
        trial.metric_history.append(metrics)
        if ckpt:
            trial.checkpoint_path = ckpt

        decision = self._scheduler.on_result(trial, metrics)
        if self._should_stop_by_criteria(metrics):
            decision = STOP
        if decision == PopulationBasedTraining.EXPLOIT:
            return self._exploit(trial)
        if decision == REALLOC:
            return self._realloc(trial)
        if decision == PAUSE:
            # park at the latest checkpoint until the scheduler releases
            # the bracket (reference: HyperBand's PauseTrial)
            trial.pending_result = trial.actor.ack_and_next.remote("stop")
            try:
                ray_tpu.get(trial.pending_result, timeout=30)
            except Exception:  # noqa: BLE001
                pass
            self._kill_actor(trial)
            trial.status = TrialStatus.PAUSED
            note = getattr(self._scheduler, "note_paused", None)
            if note is not None:
                note(trial.trial_id)
            return "park"
        action = "stop" if decision == STOP else "continue"
        trial.pending_result = trial.actor.ack_and_next.remote(action)
        return None

    def _stop_and_requeue(self, trial: Trial) -> str:
        """Stop the trial's actor at its latest checkpoint and mark the
        trial PENDING for a restart (shared by PBT exploitation and
        resource reallocation)."""
        trial.pending_result = trial.actor.ack_and_next.remote("stop")
        try:
            ray_tpu.get(trial.pending_result, timeout=30)
        except Exception:  # noqa: BLE001
            pass
        self._kill_actor(trial)
        trial.status = TrialStatus.PENDING
        return "requeue"

    def _exploit(self, trial: Trial) -> str:
        """PBT exploit: stop this trial, clone donor checkpoint+config
        (perturbed), and requeue it to restart from there."""
        sched = self._scheduler
        info = sched.pending_exploit or {}
        sched.pending_exploit = None
        donor = next((t for t in self.trials
                      if t.trial_id == info.get("donor_id")), None)
        out = self._stop_and_requeue(trial)
        if donor is not None:
            trial.checkpoint_path = donor.checkpoint_path
            trial.config = sched.explore(dict(donor.config))
        return out

    def _realloc(self, trial: Trial) -> str:
        """ResourceChangingScheduler: restart the trial from its latest
        checkpoint under a new resource allocation (reference:
        resource_changing_scheduler.py — same stop/requeue path as PBT
        exploitation, config untouched)."""
        new_res = self._scheduler.pop_realloc(trial.trial_id)
        out = self._stop_and_requeue(trial)
        if new_res:
            trial.resources = new_res
        return out

    def _on_error(self, trial: Trial, err: str,
                  tb: Optional[str] = None) -> Optional[str]:
        trial.num_failures += 1
        self._kill_actor(trial)
        if trial.num_failures <= self._max_failures:
            trial.status = TrialStatus.PENDING
            return "requeue"
        trial.status = TrialStatus.ERROR
        trial.error = tb or err
        self._scheduler.on_trial_complete(trial)
        if self._searcher is not None:
            self._searcher.on_trial_complete(trial.trial_id, None)
        return None

    def _should_stop_by_criteria(self, metrics: Dict[str, Any]) -> bool:
        for key, bound in self._stop_criteria.items():
            v = metrics.get(key)
            if v is not None and v >= bound:
                return True
        return False

    def _kill_actor(self, trial: Trial):
        if trial.actor is not None:
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
        trial.pending_result = None

    def _checkpoint_experiment(self):
        """Persist trial states for Tuner.restore (reference:
        tune/execution/experiment_state.py)."""
        path = os.path.join(self._experiment_dir, "experiment_state.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({
                "timestamp": time.time(),
                "metric": self._metric,
                "mode": self._mode,
                "trials": [t.snapshot() for t in self.trials],
            }, f)
        os.replace(tmp, path)
