"""ray_tpu.tune: hyperparameter search over trial actors.

Reference: python/ray/tune/ — Tuner.fit (tuner.py:44), TuneController
(execution/tune_controller.py:68), search spaces (search/sample.py),
schedulers (schedulers/: ASHA, median stopping, PBT).
"""

from ray_tpu.tune.schedulers import (  # noqa: F401
    ASHAScheduler,
    FIFOScheduler,
    HyperBandForBOHB,
    HyperBandScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    ResourceChangingScheduler,
    TrialScheduler,
    evenly_distribute_cpus,
)
from ray_tpu.tune.search_space import (  # noqa: F401
    choice,
    grid_search,
    lograndint,
    loguniform,
    quniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.trial import (  # noqa: F401
    Trial,
    TrialStatus,
    get_checkpoint,
    get_trial_dir,
    report,
)
from ray_tpu.tune.search import (  # noqa: F401
    BasicVariantGenerator,
    BOHBSearcher,
    GPSearcher,
    Searcher,
    TPESearcher,
)
from ray_tpu.tune.tune_controller import TuneController  # noqa: F401
from ray_tpu.tune.tuner import Result, ResultGrid, TuneConfig, Tuner  # noqa: F401

__all__ = [
    "Tuner", "TuneConfig", "TuneController", "Result", "ResultGrid",
    "Trial", "TrialStatus",
    "report", "get_checkpoint", "get_trial_dir",
    "uniform", "loguniform", "quniform", "randint", "lograndint",
    "choice", "sample_from", "grid_search",
    "TrialScheduler", "FIFOScheduler", "ASHAScheduler",
    "HyperBandScheduler", "HyperBandForBOHB", "PB2",
    "MedianStoppingRule", "PopulationBasedTraining",
    "ResourceChangingScheduler", "evenly_distribute_cpus",
    "Searcher", "BasicVariantGenerator", "TPESearcher", "BOHBSearcher",
    "GPSearcher",
]
