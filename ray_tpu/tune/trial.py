"""Trial state + the trial actor hosting a function trainable.

Reference: python/ray/tune/experiment/trial.py (Trial FSM) and
tune/trainable/function_trainable.py — the user function runs on a thread
inside the trial actor; ``tune.report`` hands results over in lockstep
(the same pattern as the Train session, train/_internal/session.py:111).
"""

from __future__ import annotations

import enum
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import ray_tpu


class TrialStatus(str, enum.Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    PAUSED = "PAUSED"
    TERMINATED = "TERMINATED"
    ERROR = "ERROR"


@dataclass
class Trial:
    """Driver-side record of one trial (reference: experiment/trial.py)."""

    trial_id: str
    config: Dict[str, Any]
    status: TrialStatus = TrialStatus.PENDING
    last_result: Dict[str, Any] = field(default_factory=dict)
    metric_history: list = field(default_factory=list)
    error: Optional[str] = None
    checkpoint_path: Optional[str] = None
    num_failures: int = 0
    iterations: int = 0
    resources: Optional[Dict[str, Any]] = None  # per-trial override
    actor: Any = None           # ActorHandle while running
    pending_result: Any = None  # in-flight ObjectRef from next_result

    @property
    def is_finished(self) -> bool:
        return self.status in (TrialStatus.TERMINATED, TrialStatus.ERROR)

    def snapshot(self) -> dict:
        return {
            "trial_id": self.trial_id,
            "config": self.config,
            "status": self.status.value,
            "last_result": self.last_result,
            "error": self.error,
            "checkpoint_path": self.checkpoint_path,
            "iterations": self.iterations,
            "num_failures": self.num_failures,
            "resources": self.resources,
        }

    @staticmethod
    def from_snapshot(snap: dict) -> "Trial":
        t = Trial(snap["trial_id"], snap["config"])
        t.status = TrialStatus(snap["status"])
        t.last_result = snap.get("last_result", {})
        t.error = snap.get("error")
        t.checkpoint_path = snap.get("checkpoint_path")
        t.iterations = snap.get("iterations", 0)
        t.num_failures = snap.get("num_failures", 0)
        t.resources = snap.get("resources")
        return t


# ---------------------------------------------------------------- sessions

# One TrialActor per worker process and one runner thread per actor, so a
# plain module global suffices (threading.local is unpicklable, and actor
# classes ship to workers by value).
_active_session: Optional["_TuneSession"] = None


class _TuneSession:
    def __init__(self, checkpoint_path: Optional[str], trial_dir: str):
        self.result_q: "queue.Queue" = queue.Queue(maxsize=1)
        self.consumed = threading.Semaphore(0)
        self.checkpoint_path = checkpoint_path
        self.trial_dir = trial_dir
        self.should_stop = False

    def report(self, metrics: Dict[str, Any],
               checkpoint_path: Optional[str] = None):
        self.result_q.put(("result", dict(metrics), checkpoint_path))
        self.consumed.acquire()
        if self.should_stop:
            raise StopTrial()


class StopTrial(Exception):
    """Raised inside the user fn when the scheduler stops the trial early."""


def report(metrics: Dict[str, Any], *, checkpoint=None):
    """In-trial API (reference: ray.tune.report / train.report in trials)."""
    s = _active_session
    if s is None:
        raise RuntimeError("tune.report() called outside a Tune trial")
    path = None
    if checkpoint is not None:
        path = checkpoint if isinstance(checkpoint, str) else \
            getattr(checkpoint, "path", None)
    s.report(metrics, checkpoint_path=path)


def get_checkpoint():
    """Latest checkpoint to resume from (None on fresh start)."""
    s = _active_session
    if s is None or s.checkpoint_path is None:
        return None
    from ray_tpu.train.checkpoint import Checkpoint
    return Checkpoint(s.checkpoint_path)


def get_trial_dir() -> str:
    s = _active_session
    return s.trial_dir if s else ""


@ray_tpu.remote
class TrialActor:
    """Hosts one function trainable; the controller polls next_result()."""

    def __init__(self, fn, config: Dict[str, Any], trial_dir: str,
                 checkpoint_path: Optional[str] = None):
        os.makedirs(trial_dir, exist_ok=True)
        self._session = _TuneSession(checkpoint_path, trial_dir)
        self._fn = fn
        self._config = config
        self._thread = None
        self._unacked = False

    def start(self):
        session = self._session

        def runner():
            # The actor class ships to workers pickled by value, giving it
            # a synthetic globals dict; user code calls tune.report via the
            # canonically imported module. Set the session THERE.
            import ray_tpu.tune.trial as _trial_mod
            _trial_mod._active_session = session
            try:
                self._fn(self._config)
                session.result_q.put(("done", {}, None))
            except StopTrial:
                session.result_q.put(("stopped", {}, None))
            except BaseException as e:  # noqa: BLE001
                import traceback
                session.result_q.put(
                    ("error", {"error": repr(e),
                               "traceback": traceback.format_exc()}, None))

        self._thread = threading.Thread(target=runner, daemon=True,
                                        name="rtpu-tune-trial")
        self._thread.start()
        return True

    def ack_and_next(self, action: str = "continue"):
        """Acknowledge the previous result with ``action`` ('continue' |
        'stop'), then block for the next report.

        Actor calls execute serially, so stop cannot be a separate method —
        it would queue behind a blocked next_result. Instead the controller
        folds its scheduler decision into the next poll; when un-acked, the
        user fn is guaranteed parked inside report(), so flipping
        should_stop before releasing the semaphore is race-free.
        Returns (kind, metrics, ckpt_path)."""
        if self._unacked:
            if action == "stop":
                self._session.should_stop = True
            self._session.consumed.release()
            self._unacked = False
        kind, metrics, ckpt = self._session.result_q.get()
        self._unacked = kind == "result"
        return kind, metrics, ckpt
