"""User-facing exceptions (reference: python/ray/exceptions.py)."""

from __future__ import annotations

from ray_tpu.core.cluster.rpc import RpcError as _RpcError


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception during execution.

    Re-raised at ``get`` on the caller, wrapping the remote traceback
    (reference: RayTaskError in python/ray/exceptions.py).
    """

    def __init__(self, cause: BaseException, remote_traceback: str = ""):
        self.cause = cause
        self.remote_traceback = remote_traceback
        super().__init__(f"{type(cause).__name__}: {cause}\n{remote_traceback}")


class ActorError(RayTpuError):
    pass


class ActorDiedError(ActorError):
    """The actor is dead: it crashed, was killed, or exhausted restarts.

    When the runtime knows more than the bare fact, the structured
    fields carry it (and are appended to the message, so the context
    survives pickling across processes): ``cause`` is the terminal
    death reason, ``restarts_consumed`` how many of the actor's
    ``max_restarts`` budget were spent, and ``incarnation`` which
    incarnation (0 = the original process) failed.
    """

    def __init__(self, message: str = "", cause: str = "",
                 restarts_consumed=None, incarnation=None):
        self.cause = cause
        self.restarts_consumed = restarts_consumed
        self.incarnation = incarnation
        detail = []
        if cause:
            detail.append(f"cause: {cause}")
        if restarts_consumed is not None:
            detail.append(f"restarts consumed: {restarts_consumed}")
        if incarnation is not None:
            detail.append(f"failing incarnation: {incarnation}")
        if detail:
            message += " (" + "; ".join(detail) + ")"
        super().__init__(message)


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable: its worker died and a
    restart is underway, but the call could not be buffered — the
    RESTARTING queue is past ``actor_restart_buffer_max``, or the
    restart has been running longer than ``actor_restart_timeout_s``.
    Unlike ``ActorDiedError`` the actor may come back; callers may
    retry later."""


class GcsUnavailableError(RayTpuError, _RpcError):
    """The head node (GCS) is temporarily unreachable: it died or is
    mid-restart, and the call could not be buffered past the ride-through
    window — more than ``gcs_op_buffer_max`` calls are already parked, the
    outage outlasted ``gcs_reconnect_timeout_s``, or the op is not on the
    retry-after-apply whitelist and its reply was lost (blind replay could
    run the side effect twice). Unlike a node death this is usually
    transient: a restarted GCS recovers its state from snapshot+WAL and
    the cluster resyncs, so callers may retry later. Mirrors
    ``ActorUnavailableError`` semantics at the cluster level; subclasses
    the transport ``RpcError`` so existing best-effort handlers keep
    treating it as a connectivity failure."""


class StaleGcsEpochError(RayTpuError, _RpcError):
    """A write from a fenced (stale) GCS incarnation was rejected.

    Split-brain guard: every GCS incarnation carries a monotonic
    ``epoch_seq`` (persisted counter, stamped on heartbeat replies and
    ``gcs_info``), and nodes remember the highest value they have seen.
    A GCS-originated write (actor restart, reap) carrying a LOWER seq
    than the receiver has observed is the signature of a
    partitioned-but-alive old head still trying to mutate the cluster —
    the node rejects it with this error, and the stale head fences
    itself on seeing the rejection (stops restarts, death-marking, and
    table writes). Structured fields survive pickling via
    ``__reduce__``: ``stale_seq`` is the writer's epoch_seq,
    ``current_seq`` the newest the rejecting side has seen. Subclasses
    the transport ``RpcError`` so best-effort ``except RpcError``
    handlers treat a fenced head like an unreachable one.
    """

    def __init__(self, message: str = "", stale_seq: int = 0,
                 current_seq: int = 0):
        self.stale_seq = int(stale_seq)
        self.current_seq = int(current_seq)
        self._message = message or "stale GCS incarnation fenced"
        super().__init__(
            f"{self._message} (writer epoch_seq {self.stale_seq} < "
            f"newest seen {self.current_seq})")

    def __reduce__(self):
        # rebuild from the original fields: default exception pickling
        # would re-call __init__ with the composed message, doubling
        # the suffix and zeroing the structured fields
        return (type(self), (self._message, self.stale_seq,
                             self.current_seq))


class BackpressureError(RayTpuError):
    """The serving plane rejected (shed) the request under overload.

    Raised at ADMISSION by the router — before any replica work starts —
    when the deployment's queue depth exceeds the priority class's share
    of ``max_queue_depth``, or when the TTFT estimate says the request's
    deadline cannot be met; and mid-flight when a request's deadline
    expires (the stream is closed and the engine request cancelled).
    Structured fields survive pickling across processes via
    ``__reduce__``: ``deployment`` names the shedding deployment,
    ``queue_depth`` the router-local depth at rejection,
    ``estimated_wait_s`` the TTFT-EWMA-based wait estimate, and
    ``retry_after_s`` a client hint (the HTTP proxy maps this error to
    429 with a ``Retry-After`` header)."""

    def __init__(self, message: str = "", deployment: str = "",
                 queue_depth: int = 0, estimated_wait_s: float = 0.0,
                 retry_after_s: float = 1.0):
        self.deployment = deployment
        self.queue_depth = int(queue_depth)
        self.estimated_wait_s = float(estimated_wait_s)
        self.retry_after_s = float(retry_after_s)
        self._message = message
        detail = []
        if deployment:
            detail.append(f"deployment: {deployment!r}")
        detail.append(f"queue depth: {self.queue_depth}")
        detail.append(f"estimated wait: {self.estimated_wait_s:.3f}s")
        detail.append(f"retry after: {self.retry_after_s:.3f}s")
        super().__init__((message or "request shed under overload")
                         + " (" + "; ".join(detail) + ")")

    def __reduce__(self):
        # default exception pickling re-calls __init__ with the COMPOSED
        # message as args[0], doubling the detail suffix and zeroing the
        # structured fields — rebuild from the originals instead
        return (type(self), (self._message, self.deployment,
                             self.queue_depth, self.estimated_wait_s,
                             self.retry_after_s))


class ReplicaUnavailableError(RayTpuError):
    """No running replica could serve a deployment's request: none
    appeared within the router's wait window (``serve_replica_wait_s``
    — deleted, never deployed, or every replica down/restarting), or
    the request's replay budget ran out across replica deaths. Unlike
    ``BackpressureError`` this is not load-dependent — retrying sooner
    will not help until the control plane brings replicas back. The HTTP
    proxy maps it to 503.

    ``attempts`` counts the dispatch attempts the router spent before
    giving up (0 when no replica was ever picked) and ``last_cause``
    carries the final attempt's error (usually ActorDiedError), so
    callers can distinguish "never had a replica" from "replicas kept
    dying under the request"."""

    def __init__(self, message: str = "", deployment: str = "",
                 attempts: int = 0, last_cause=None):
        self.deployment = deployment
        self.attempts = int(attempts)
        self.last_cause = last_cause
        if not message:
            if self.attempts:
                message = (
                    f"request to deployment {deployment!r} failed after "
                    f"{self.attempts} attempt(s)")
                if last_cause is not None:
                    message += f"; last cause: {last_cause!r}"
            else:
                message = (
                    f"no running replicas for deployment {deployment!r}"
                    if deployment else "no running replicas")
        self._message = message
        super().__init__(message)

    def __reduce__(self):
        # rebuild from the original fields (not the composed message) so
        # a pickle round-trip neither doubles the suffix nor drops the
        # structured attempt count / cause
        return (type(self), (self._message, self.deployment,
                             self.attempts, self.last_cause))


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get`` did not complete within the requested timeout."""


class ObjectLostError(RayTpuError):
    """The object's value was lost and could not be reconstructed.

    Task returns are normally recomputed transparently from lineage (up
    to ``config.max_reconstructions`` attempts); this error surfaces only
    for unrecoverable objects — ``ray_tpu.put`` values, eagerly freed
    ids, lineage-evicted entries — or once the reconstruction budget is
    exhausted. When the producing task is known, ``task_id`` carries its
    hex id and ``attempts`` the reconstruction history (one string per
    attempt, e.g. why it was retried or why it stopped).
    """

    def __init__(self, message: str = "", task_id: str = "",
                 attempts=None):
        self.task_id = task_id
        self.attempts = list(attempts or [])
        if task_id:
            message += f" (producing task {task_id}"
            if self.attempts:
                message += ("; reconstruction attempts: "
                            + "; ".join(self.attempts))
            message += ")"
        super().__init__(message)


class WorkerCrashedError(RayTpuError):
    """The worker executing the task died unexpectedly."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled via ``ray_tpu.cancel()`` before completing
    (reference: TaskCancelledError in python/ray/exceptions.py)."""


class RuntimeNotInitializedError(RayTpuError):
    """An API call was made before ``ray_tpu.init()``."""


class ObjectStoreFullError(RayTpuError):
    """Allocation failed after eviction: the object store is out of memory."""


class ObjectTimeoutError(RayTpuError, TimeoutError):
    """A store-level blocking get did not complete in time."""


class PlacementGroupError(RayTpuError):
    pass


class OutOfMemoryError(TaskError):
    """A task's worker was killed by the node memory monitor and the
    task is out of OOM retries (reference: ray.exceptions.OutOfMemoryError
    raised by the worker-killing policy, memory_monitor.h:52)."""
