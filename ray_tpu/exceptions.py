"""User-facing exceptions (reference: python/ray/exceptions.py)."""

from __future__ import annotations

from ray_tpu.core.cluster.rpc import RpcError as _RpcError


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception during execution.

    Re-raised at ``get`` on the caller, wrapping the remote traceback
    (reference: RayTaskError in python/ray/exceptions.py).
    """

    def __init__(self, cause: BaseException, remote_traceback: str = ""):
        self.cause = cause
        self.remote_traceback = remote_traceback
        super().__init__(f"{type(cause).__name__}: {cause}\n{remote_traceback}")


class ActorError(RayTpuError):
    pass


class ActorDiedError(ActorError):
    """The actor is dead: it crashed, was killed, or exhausted restarts.

    When the runtime knows more than the bare fact, the structured
    fields carry it (and are appended to the message, so the context
    survives pickling across processes): ``cause`` is the terminal
    death reason, ``restarts_consumed`` how many of the actor's
    ``max_restarts`` budget were spent, and ``incarnation`` which
    incarnation (0 = the original process) failed.
    """

    def __init__(self, message: str = "", cause: str = "",
                 restarts_consumed=None, incarnation=None):
        self.cause = cause
        self.restarts_consumed = restarts_consumed
        self.incarnation = incarnation
        detail = []
        if cause:
            detail.append(f"cause: {cause}")
        if restarts_consumed is not None:
            detail.append(f"restarts consumed: {restarts_consumed}")
        if incarnation is not None:
            detail.append(f"failing incarnation: {incarnation}")
        if detail:
            message += " (" + "; ".join(detail) + ")"
        super().__init__(message)


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable: its worker died and a
    restart is underway, but the call could not be buffered — the
    RESTARTING queue is past ``actor_restart_buffer_max``, or the
    restart has been running longer than ``actor_restart_timeout_s``.
    Unlike ``ActorDiedError`` the actor may come back; callers may
    retry later."""


class GcsUnavailableError(RayTpuError, _RpcError):
    """The head node (GCS) is temporarily unreachable: it died or is
    mid-restart, and the call could not be buffered past the ride-through
    window — more than ``gcs_op_buffer_max`` calls are already parked, the
    outage outlasted ``gcs_reconnect_timeout_s``, or the op is not on the
    retry-after-apply whitelist and its reply was lost (blind replay could
    run the side effect twice). Unlike a node death this is usually
    transient: a restarted GCS recovers its state from snapshot+WAL and
    the cluster resyncs, so callers may retry later. Mirrors
    ``ActorUnavailableError`` semantics at the cluster level; subclasses
    the transport ``RpcError`` so existing best-effort handlers keep
    treating it as a connectivity failure."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get`` did not complete within the requested timeout."""


class ObjectLostError(RayTpuError):
    """The object's value was lost and could not be reconstructed.

    Task returns are normally recomputed transparently from lineage (up
    to ``config.max_reconstructions`` attempts); this error surfaces only
    for unrecoverable objects — ``ray_tpu.put`` values, eagerly freed
    ids, lineage-evicted entries — or once the reconstruction budget is
    exhausted. When the producing task is known, ``task_id`` carries its
    hex id and ``attempts`` the reconstruction history (one string per
    attempt, e.g. why it was retried or why it stopped).
    """

    def __init__(self, message: str = "", task_id: str = "",
                 attempts=None):
        self.task_id = task_id
        self.attempts = list(attempts or [])
        if task_id:
            message += f" (producing task {task_id}"
            if self.attempts:
                message += ("; reconstruction attempts: "
                            + "; ".join(self.attempts))
            message += ")"
        super().__init__(message)


class WorkerCrashedError(RayTpuError):
    """The worker executing the task died unexpectedly."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled via ``ray_tpu.cancel()`` before completing
    (reference: TaskCancelledError in python/ray/exceptions.py)."""


class RuntimeNotInitializedError(RayTpuError):
    """An API call was made before ``ray_tpu.init()``."""


class ObjectStoreFullError(RayTpuError):
    """Allocation failed after eviction: the object store is out of memory."""


class ObjectTimeoutError(RayTpuError, TimeoutError):
    """A store-level blocking get did not complete in time."""


class PlacementGroupError(RayTpuError):
    pass


class OutOfMemoryError(TaskError):
    """A task's worker was killed by the node memory monitor and the
    task is out of OOM retries (reference: ray.exceptions.OutOfMemoryError
    raised by the worker-killing policy, memory_monitor.h:52)."""
