"""Pipeline parallelism: GPipe scheduling as ONE jitted SPMD program.

TPU-native design (reference role: rllib/train pipeline stages run as
torch RPC/NCCL p2p across actor processes — e.g. the reference's
compiled-DAG PP inference path, python/ray/dag/compiled_dag_node.py; here
the pipeline is a collective program): the stacked layer dimension is
sharded over the mesh's ``pp`` axis (each stage holds L/P layers), and
one ``shard_map``-wrapped ``lax.scan`` runs the whole schedule — per
tick, every stage applies its layers to its current microbatch and
rotates activations to the next stage with ``lax.ppermute`` over ICI.
``jax.grad`` through the scan reverses the ppermutes automatically,
yielding the standard GPipe backward schedule with no hand-written
communication. Bubble fraction is (P-1)/(M+P-1) — pick
num_microbatches >> pp.

The generic primitive is ``pipeline_apply``; models expose thin wrappers
(models/llama.py: ``loss_fn_pp``).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any, microbatches: jax.Array,
                   axis_name: str = "pp") -> jax.Array:
    """Run ``microbatches [M, ...]`` through a P-stage pipeline.

    Call INSIDE shard_map over ``axis_name``: ``stage_params`` is this
    stage's layer slice, ``microbatches`` the full input set (replicated
    across pp; stage 0 injects them). Returns outputs [M, ...] valid on
    the LAST stage (zeros elsewhere — combine with a masked psum or read
    on the last stage). Differentiable end to end.
    """
    from ray_tpu.parallel.device_collectives import axis_size

    P = axis_size(axis_name)
    M = microbatches.shape[0]
    p = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % P) for i in range(P)]

    state0 = jnp.zeros_like(microbatches[0])
    out0 = jnp.zeros_like(microbatches)

    def tick(carry, t):
        state, outs = carry
        mb_idx = t - p                      # microbatch this stage sees
        active = (mb_idx >= 0) & (mb_idx < M)
        # stage 0 injects fresh microbatches; later stages consume the
        # rotated activations from their predecessor
        inject = microbatches[jnp.clip(mb_idx, 0, M - 1)]
        x = jnp.where(p == 0, inject, state)
        y = stage_fn(stage_params, x)
        # the LAST stage's result for an active tick is a finished
        # microbatch; bubble ticks write nowhere (scalar cond broadcasts)
        should_write = active & (p == P - 1)
        outs = jnp.where(should_write,
                         outs.at[jnp.clip(mb_idx, 0, M - 1)].set(y), outs)
        state = jax.lax.ppermute(y, axis_name, perm)
        return (state, outs), None

    (state, outs), _ = jax.lax.scan(
        tick, (state0, out0), jnp.arange(M + P - 1))
    return outs
