"""Logical-axis sharding rules → concrete NamedShardings.

Models annotate parameters and activations with *logical* dim names
("batch", "embed", "mlp", "heads", "kv", "vocab", "seq", "expert", "stage");
a rule table maps logical names to mesh axes. This is flax's logical
partitioning pattern, kept framework-agnostic so plain-jax models use it too.

The default rule table implements the standard megatron/ZeRO layout over the
ray_tpu axis conventions (mesh.py): batch over (dp, fsdp), embed sharded
over fsdp for ZeRO-3, matmul output dims over tp, sequence over sp, experts
over ep.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

# (logical dim name, mesh axis or tuple of axes or None)
DEFAULT_RULES: Tuple[Tuple[str, Any], ...] = (
    ("batch", ("dp", "fsdp")),
    ("seq", "sp"),
    ("embed", "fsdp"),       # ZeRO-3: params sharded over fsdp on the embed dim
    ("mlp", "tp"),
    ("heads", "tp"),
    ("kv", None),
    ("qkv", "tp"),
    ("vocab", "tp"),
    ("expert", "ep"),
    ("stage", "pp"),
    (None, None),
)


def resolve_axis(logical: Optional[str], mesh, rules=DEFAULT_RULES):
    """Map one logical dim to mesh axes present in `mesh` (else None)."""
    if logical is None:
        return None
    for name, target in rules:
        if name == logical:
            if target is None:
                return None
            if isinstance(target, str):
                return target if target in mesh.axis_names else None
            present = tuple(a for a in target if a in mesh.axis_names)
            return present if present else None
    return None


def logical_to_pspec(logical_axes: Sequence[Optional[str]], mesh,
                     rules=DEFAULT_RULES):
    """('batch','seq','embed') → PartitionSpec over the mesh's real axes."""
    from jax.sharding import PartitionSpec

    return PartitionSpec(
        *(resolve_axis(a, mesh, rules) for a in logical_axes)
    )


def named_sharding(mesh, *logical_axes, rules=DEFAULT_RULES):
    """NamedSharding for logical dims, e.g. named_sharding(mesh, 'batch', None, 'embed')."""
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, logical_to_pspec(logical_axes, mesh, rules))


def with_logical_constraint(x, logical_axes: Sequence[Optional[str]], mesh=None,
                            rules=DEFAULT_RULES):
    """Sharding constraint by logical names inside jitted code."""
    import jax

    if mesh is None:
        from jax.interpreters import pxla

        mesh = pxla.thread_resources.env.physical_mesh
        if mesh.empty:
            return x
    return jax.lax.with_sharding_constraint(
        x, named_sharding(mesh, *logical_axes, rules=rules)
    )


def shard_pytree_like(logical_tree, mesh, rules=DEFAULT_RULES):
    """Build a NamedSharding pytree from a pytree of logical-axis tuples
    (None entries → fully replicated)."""
    import jax

    def one(logical):
        if logical is None:
            return named_sharding(mesh)
        return named_sharding(mesh, *logical, rules=rules)

    return jax.tree_util.tree_map(
        one, logical_tree,
        is_leaf=lambda x: x is None or (isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x)),
    )


def device_put_sharded(tree, shardings):
    """jax.device_put a pytree with a matching shardings pytree."""
    import jax

    return jax.device_put(tree, shardings)


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh):
    """Sharding for a [global_batch, ...] array over the data axes."""
    return named_sharding(mesh, "batch")
