"""Device meshes: the ICI-native replacement for collective *groups*.

The reference expresses accelerator parallelism as eager NCCL collective
groups (python/ray/util/collective/collective.py) and process-group setup in
trainers (train/torch/config.py:66). On TPU the idiomatic equivalent is a
``jax.sharding.Mesh`` over the slice with named axes; collectives are XLA
programs over ICI, not runtime services. This module owns the axis
conventions and mesh construction.

Axis conventions (outer → inner, DCN-most to ICI-most):

    "dp"    pure data parallel (replicated params)
    "fsdp"  data parallel with sharded params/optimizer (ZeRO-3 style)
    "pp"    pipeline stages
    "sp"    sequence/context parallel (ring attention rides this axis)
    "tp"    tensor parallel (megatron-style, innermost = fastest ICI)
    "ep"    expert parallel (MoE; shares the tp neighborhood)

``build_mesh`` places later axes on faster (ICI-adjacent) device
neighborhoods via jax.experimental.mesh_utils.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

AXIS_ORDER = ("dp", "fsdp", "pp", "sp", "ep", "tp")

# Canonical groupings used by shardings and trainers.
DATA_AXES = ("dp", "fsdp")          # batch is sharded over these
MODEL_AXES = ("tp", "sp", "ep", "pp")
REPLICA_AXES = ("dp",)


@dataclass(frozen=True)
class MeshSpec:
    """A named, ordered parallelism layout.

    Example::

        spec = MeshSpec(axes={"fsdp": 2, "tp": 4})
        mesh = build_mesh(spec)          # uses all visible devices
    """

    axes: Dict[str, int] = field(default_factory=dict)

    def __post_init__(self):
        for name in self.axes:
            if name not in AXIS_ORDER:
                raise ValueError(
                    f"unknown mesh axis {name!r}; valid axes: {AXIS_ORDER}"
                )
        if any(s <= 0 for s in self.axes.values()):
            raise ValueError(f"axis sizes must be positive: {self.axes}")

    @property
    def ordered(self) -> List[Tuple[str, int]]:
        """Axes in canonical outer→inner order."""
        return [(a, self.axes[a]) for a in AXIS_ORDER if a in self.axes]

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(a for a, _ in self.ordered)

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(s for _, s in self.ordered)

    @property
    def size(self) -> int:
        n = 1
        for _, s in self.ordered:
            n *= s
        return n

    def with_axis(self, name: str, size: int) -> "MeshSpec":
        axes = dict(self.axes)
        axes[name] = size
        return MeshSpec(axes)

    @classmethod
    def data_parallel(cls, num_devices: int, sharded: bool = True) -> "MeshSpec":
        """All devices on one data axis (fsdp if sharded else dp)."""
        return cls({"fsdp" if sharded else "dp": num_devices})

    @classmethod
    def from_devices(cls, num_devices: int, tp: int = 1, pp: int = 1,
                     sp: int = 1, ep: int = 1, dp: int = 0) -> "MeshSpec":
        """Fill the data axis with whatever devices remain after model axes."""
        model = tp * pp * sp * ep
        if num_devices % model != 0:
            raise ValueError(
                f"{num_devices} devices not divisible by tp*pp*sp*ep={model}"
            )
        remaining = num_devices // model
        axes = {}
        if dp:
            if dp != remaining:
                raise ValueError(f"dp={dp} but only {remaining} devices remain")
        axes_map = {"dp": remaining, "pp": pp, "sp": sp, "ep": ep, "tp": tp}
        for k, v in axes_map.items():
            if v > 1 or (k == "dp" and v >= 1):
                axes[k] = v
        return cls(axes)


def build_mesh(spec: MeshSpec, devices: Optional[Sequence] = None):
    """Construct a ``jax.sharding.Mesh`` for the spec.

    Axis order maps outer axes to DCN/far links and inner axes (tp) to the
    tightest ICI neighborhoods, via mesh_utils.create_device_mesh's
    transposition logic ("How to Scale Your Model" mesh recipe).
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = 1
    for _, s in spec.ordered:
        n *= s
    if n != len(devices):
        raise ValueError(
            f"mesh spec {dict(spec.axes)} needs {n} devices, "
            f"got {len(devices)}"
        )
    if len(devices) == 1:
        import numpy as np

        dev_array = np.array(devices).reshape(spec.shape or (1,))
        return Mesh(dev_array, spec.axis_names or ("dp",))
    dev_mesh = mesh_utils.create_device_mesh(
        spec.shape, devices=list(devices)
    )
    return Mesh(dev_mesh, spec.axis_names)


def build_hybrid_mesh(ici: "MeshSpec | Dict[str, int]",
                      dcn: "MeshSpec | Dict[str, int]",
                      devices: Optional[Sequence] = None):
    """Multi-slice mesh: ``dcn`` axes span SLICES (data-center network),
    ``ici`` axes span chips WITHIN a slice (the scaling-book recipe: dp
    over DCN × fsdp/tp over ICI, so gradient all-reduces cross DCN once
    per step while the bandwidth-hungry param/activation collectives
    stay on ICI).

    An axis present in both specs gets total size dcn*ici with the DCN
    factor outermost. On real multi-slice TPU (devices carry
    ``slice_index``) placement delegates to
    ``mesh_utils.create_hybrid_device_mesh``; elsewhere (virtual CPU
    meshes, single-slice dry runs) devices are grouped into
    ``prod(dcn)`` contiguous pseudo-slices — topology-free but
    identical for numerics, which is what the multichip dry run checks.
    """
    import numpy as np

    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    ici = ici if isinstance(ici, MeshSpec) else MeshSpec(dict(ici))
    dcn = dcn if isinstance(dcn, MeshSpec) else MeshSpec(dict(dcn))
    if devices is None:
        devices = jax.devices()
    names = tuple(a for a in AXIS_ORDER
                  if a in ici.axes or a in dcn.axes)
    ici_shape = tuple(ici.axes.get(a, 1) for a in names)
    dcn_shape = tuple(dcn.axes.get(a, 1) for a in names)
    total = int(np.prod(ici_shape)) * int(np.prod(dcn_shape))
    if total != len(devices):
        raise ValueError(
            f"hybrid mesh ici={dict(ici.axes)} x dcn={dict(dcn.axes)} "
            f"needs {total} devices, got {len(devices)}")
    n_slices = int(np.prod(dcn_shape))
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    if len(slice_ids) == n_slices and None not in slice_ids \
            and n_slices > 1:
        dev_mesh = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=list(devices))
        return Mesh(dev_mesh, names)
    # pseudo-slice fallback: contiguous groups of prod(ici) devices act
    # as slices; interleave (dcn_0, ici_0, dcn_1, ici_1, ...) then merge
    arr = np.array(devices).reshape(dcn_shape + ici_shape)
    k = len(names)
    arr = arr.transpose([i // 2 if i % 2 == 0 else k + i // 2
                         for i in range(2 * k)])
    arr = arr.reshape(tuple(d * i for d, i in zip(dcn_shape, ici_shape)))
    return Mesh(arr, names)


def hybrid_mesh(dcn: Dict[str, int], **ici_axes):
    """Convenience: ``hybrid_mesh({"dp": 2}, fsdp=4)`` over all visible
    devices — 2 slices of data parallelism, fsdp=4 inside each."""
    return build_hybrid_mesh(MeshSpec(dict(ici_axes)), MeshSpec(dict(dcn)))


def local_mesh(tp: int = 0, **axes) -> "object":
    """Convenience: mesh over all local devices.

    ``local_mesh()`` → pure fsdp over every visible device;
    ``local_mesh(tp=4)`` → tp=4, data-parallel over the rest.
    """
    import jax

    n = len(jax.devices())
    if not axes and not tp:
        return build_mesh(MeshSpec.data_parallel(n))
    if tp:
        axes["tp"] = tp
    model = 1
    for v in axes.values():
        model *= v
    if n % model:
        raise ValueError(f"{n} devices not divisible by {axes}")
    if n // model > 1:
        axes = {"fsdp": n // model, **axes}
    return build_mesh(MeshSpec(axes))


def mesh_axis_names(mesh) -> Tuple[str, ...]:
    return tuple(mesh.axis_names)


def data_shard_axes(mesh) -> Tuple[str, ...]:
    """Mesh axes the global batch is sharded over."""
    return tuple(a for a in DATA_AXES if a in mesh.axis_names)
