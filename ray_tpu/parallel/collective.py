"""Host-level collective groups across actors (the out-of-band API).

Mirrors the reference's ``ray.util.collective`` surface
(collective.py:120 init_collective_group, :258 allreduce, :373 broadcast,
:423 allgather, :531/:594 send/recv) with TPU-native backends:

- ``backend="host"``: cross-process collectives through a named coordinator
  actor + the shared-memory object store — the GLOO/DCN-fallback path. The
  coordinator plays the role of the reference's ``Rendezvous`` actor
  (collective_group/nccl_collective_group.py:29), but since there is no NCCL
  to bootstrap it carries the data itself.
- ``backend="xla"``: an in-process group over local devices; collectives are
  jitted XLA programs over ICI via shard_map (see device_collectives for the
  in-program forms — the hot path for model math should use those directly).

Gang-step data-plane collectives in trainers do NOT go through this module;
they live inside the jitted train step (parallel/device_collectives.py).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.exceptions import RayTpuError

_COORD_PREFIX = "rtpu_collective::"
_groups: Dict[str, "CollectiveGroup"] = {}

REDUCE_OPS = ("sum", "prod", "min", "max")

# Sentinel the coordinator hands back from every rendezvous method once
# the group is aborted; members convert it into CollectiveAbortedError.
# A marker return (instead of raising inside the actor) keeps the abort
# indistinguishable from a normal reply on the wire — no reliance on
# exception pickling — and lets blocked pollers observe it on their very
# next 2 ms poll instead of waiting out the 120 s _sync_op timeout.
_ABORT = "__rtpu_collective_abort__"


class CollectiveAbortedError(RayTpuError):
    """An in-flight collective was aborted — typically because a gang
    peer died and the driver is resizing the group. The message names
    the reason (including the dead rank when known). Callers inside a
    train loop should let it propagate: the session/executor treat it
    as a resize signal, not an application error."""


class _Coordinator:
    """Named actor holding rendezvous + reduction state for one group.

    Methods are polled by members; per-operation state is keyed by a
    monotonically increasing per-member round counter so reuse is safe.
    Once ``abort`` is called every rendezvous method returns the abort
    marker forever — the group is dead and must be re-created (under a
    new generation) to be used again.
    """

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.rounds: Dict[str, dict] = {}
        self.mailbox: Dict[Tuple[int, int, int], Any] = {}
        self.aborted: Optional[str] = None

    def abort(self, reason: str):
        self.aborted = reason or "collective group aborted"
        self.rounds.clear()
        self.mailbox.clear()
        return True

    def contribute(self, key: str, rank: int, data, op: str):
        if self.aborted is not None:
            return (_ABORT, self.aborted)
        st = self.rounds.setdefault(key, {"parts": {}, "result": None, "op": op})
        st["parts"][rank] = data
        if len(st["parts"]) == self.world_size and st["result"] is None:
            parts = [st["parts"][r] for r in range(self.world_size)]
            st["result"] = self._combine(parts, op)
        return st["result"] is not None

    def fetch(self, key: str, rank: int):
        if self.aborted is not None:
            return (_ABORT, self.aborted)
        st = self.rounds.get(key)
        if st is None or st["result"] is None:
            return False, None
        st.setdefault("fetched", set()).add(rank)
        result = st["result"]
        if len(st["fetched"]) == self.world_size:
            del self.rounds[key]  # all members have it; free the round
        return True, result

    @staticmethod
    def _combine(parts: List[Any], op: str):
        if op == "gather":
            return parts
        if op == "barrier":
            return True
        arrs = [np.asarray(p) for p in parts]
        if op == "sum":
            out = arrs[0].copy()
            for a in arrs[1:]:
                out += a
            return out
        if op == "prod":
            out = arrs[0].copy()
            for a in arrs[1:]:
                out *= a
            return out
        if op == "min":
            return np.minimum.reduce(arrs)
        if op == "max":
            return np.maximum.reduce(arrs)
        if op.startswith("bcast:"):
            src = int(op.split(":", 1)[1])
            return parts[src]
        raise ValueError(f"unknown reduce op {op!r}")

    def post(self, src: int, dst: int, tag: int, data):
        if self.aborted is not None:
            return (_ABORT, self.aborted)
        self.mailbox[(src, dst, tag)] = data
        return None

    def take(self, src: int, dst: int, tag: int):
        if self.aborted is not None:
            return (_ABORT, self.aborted)
        if (src, dst, tag) in self.mailbox:
            return True, self.mailbox.pop((src, dst, tag))
        return False, None


class CollectiveGroup:
    """A member's view of one collective group."""

    def __init__(self, group_name: str, world_size: int, rank: int,
                 backend: str = "host", generation: int = 0):
        if backend not in ("host", "xla"):
            raise ValueError(f"backend must be 'host' or 'xla', got {backend!r}")
        self.name = group_name
        self.world_size = world_size
        self.rank = rank
        self.backend = backend
        self.generation = generation
        self._round = 0
        self._coord = None
        self._mesh = None
        if backend == "host":
            self._coord = _get_or_create_coordinator(
                group_name, world_size, generation)
        else:
            from ray_tpu.parallel.mesh import MeshSpec, build_mesh

            self._mesh = build_mesh(MeshSpec({"dp": world_size}))

    # ---- host backend primitives -------------------------------------------

    def abort(self, reason: str = "aborted"):
        """Poison the group: every member blocked in (or later entering)
        a collective gets CollectiveAbortedError on its next poll."""
        if self._coord is not None:
            import ray_tpu

            ray_tpu.get(self._coord.abort.remote(reason))

    def _check_abort(self, reply):
        """Raise if the coordinator replied with the abort marker."""
        if (isinstance(reply, tuple) and len(reply) == 2
                and reply[0] == _ABORT):
            raise CollectiveAbortedError(
                f"collective group {self.name!r} aborted "
                f"(rank {self.rank}/{self.world_size}): {reply[1]}")
        return reply

    def _sync_op(self, data, op: str, timeout: float = 120.0):
        import ray_tpu

        self._round += 1
        key = f"{op.split(':')[0]}:{self._round}"
        self._check_abort(ray_tpu.get(
            self._coord.contribute.remote(key, self.rank, data, op),
            timeout=timeout,
        ))
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            done, result = self._check_abort(ray_tpu.get(
                self._coord.fetch.remote(key, self.rank), timeout=timeout
            ))
            if done:
                return result
            time.sleep(0.002)
        raise TimeoutError(
            f"collective {op} timed out in group {self.name!r} "
            f"(rank {self.rank}/{self.world_size})"
        )

    # ---- ring allreduce ------------------------------------------------------

    # Above this size the host backend switches from the star (everything
    # through the coordinator) to a RING: chunks hop peer-to-peer as
    # ObjectRefs, the shm store is the data plane, and the coordinator
    # mailbox only rendezvouses refs — O(N) total movement per member and
    # O(refs) coordinator memory instead of O(world x N) payloads.
    RING_THRESHOLD_BYTES = 1 << 20

    def _ring_allreduce(self, arr: np.ndarray, op: str, timeout: float):
        import ray_tpu

        W, r = self.world_size, self.rank
        self._round += 1
        base = self._round * 10_000
        flat = arr.ravel()
        bounds = np.linspace(0, flat.size, W + 1).astype(int)
        own = [flat[bounds[i]: bounds[i + 1]].copy() for i in range(W)]

        def send_chunk(chunk, tag):
            ref = ray_tpu.put(np.ascontiguousarray(chunk))
            # nested (listed) refs pass through UNRESOLVED, so the
            # coordinator mailbox holds the ref, never the payload
            self._check_abort(ray_tpu.get(
                self._coord.post.remote(r, (r + 1) % W, tag, [ref])))

        def recv_chunk(tag):
            boxed = self.recv((r - 1) % W, tag=tag, timeout=timeout)
            return np.asarray(ray_tpu.get(boxed[0]))

        # phase 1: reduce-scatter around the ring
        for s in range(W - 1):
            send_chunk(own[(r - s) % W], base + s)
            idx = (r - s - 1) % W
            own[idx] = _reduce2(own[idx], recv_chunk(base + s), op)
        # phase 2: all-gather the reduced chunks
        for s in range(W - 1):
            send_chunk(own[(r + 1 - s) % W], base + 5000 + s)
            idx = (r - s) % W
            own[idx] = recv_chunk(base + 5000 + s)
        return np.concatenate(own).reshape(arr.shape)

    # ---- API ----------------------------------------------------------------

    def allreduce(self, tensor, op: str = "sum", timeout: float = 120.0):
        if self.backend == "xla":
            return _xla_allreduce(self._mesh, tensor, op)
        arr = np.asarray(tensor)
        if (self.world_size > 1 and op in REDUCE_OPS
                and arr.nbytes >= self.RING_THRESHOLD_BYTES):
            return self._ring_allreduce(arr, op, timeout)
        return self._sync_op(arr, op, timeout)

    def allgather(self, tensor, timeout: float = 120.0) -> List[Any]:
        return self._sync_op(np.asarray(tensor), "gather", timeout)

    def reducescatter(self, tensor, op: str = "sum", timeout: float = 120.0):
        full = self._sync_op(np.asarray(tensor), op, timeout)
        chunks = np.array_split(full, self.world_size, axis=0)
        return chunks[self.rank]

    def broadcast(self, tensor, src_rank: int = 0, timeout: float = 120.0):
        return self._sync_op(np.asarray(tensor), f"bcast:{src_rank}", timeout)

    def barrier(self, timeout: float = 120.0):
        self._sync_op(None, "barrier", timeout)

    def send(self, tensor, dst_rank: int, tag: int = 0):
        import ray_tpu

        self._check_abort(ray_tpu.get(
            self._coord.post.remote(self.rank, dst_rank, tag, np.asarray(tensor))
        ))

    def recv(self, src_rank: int, tag: int = 0, timeout: float = 120.0):
        import ray_tpu

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            ok, data = self._check_abort(ray_tpu.get(
                self._coord.take.remote(src_rank, self.rank, tag)
            ))
            if ok:
                return data
            time.sleep(0.002)
        raise TimeoutError(f"recv from rank {src_rank} timed out")


def _reduce2(a: np.ndarray, b: np.ndarray, op: str) -> np.ndarray:
    if op == "sum":
        return a + b
    if op == "prod":
        return a * b
    if op == "min":
        return np.minimum(a, b)
    return np.maximum(a, b)


def _xla_allreduce(mesh, tensor, op: str):
    import jax
    import jax.numpy as jnp
    try:
        from jax import shard_map
    except ImportError:  # jax < 0.5: public alias not exported yet
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    fns = {"sum": jax.lax.psum, "max": jax.lax.pmax, "min": jax.lax.pmin}
    if op not in fns:
        raise ValueError(f"xla backend supports {list(fns)}, got {op!r}")
    f = shard_map(
        lambda x: fns[op](x, "dp"), mesh=mesh,
        in_specs=P("dp"), out_specs=P(),
    )
    return jax.jit(f)(jnp.asarray(tensor))


def _coord_name(group_name: str, generation: int = 0) -> str:
    """Named-actor name for a group's coordinator. Generations let an
    elastic gang re-form the same logical group at a new world size
    without colliding with (or resurrecting the abort flag of) the
    previous incarnation's coordinator."""
    name = _COORD_PREFIX + group_name
    return name if generation == 0 else f"{name}@{generation}"


def _get_or_create_coordinator(group_name: str, world_size: int,
                               generation: int = 0):
    import ray_tpu

    name = _coord_name(group_name, generation)
    try:
        return ray_tpu.get_actor(name)
    except ValueError:
        pass
    try:
        coord_cls = ray_tpu.remote(_Coordinator)
        return coord_cls.options(name=name).remote(world_size)
    except ValueError:
        # lost the creation race; the winner's actor is registered
        return ray_tpu.get_actor(name)


def abort_group(group_name: str = "default", reason: str = "aborted",
                generation: int = 0) -> bool:
    """Driver-side: poison a group's coordinator so every member blocked
    in a collective fails over to CollectiveAbortedError within one poll
    interval (~ms), instead of stalling out the 120 s op timeout. Safe
    to call from a process that never joined the group. Returns False
    when no coordinator exists (nothing to abort)."""
    import ray_tpu

    try:
        coord = ray_tpu.get_actor(_coord_name(group_name, generation))
    except ValueError:
        return False
    ray_tpu.get(coord.abort.remote(reason))
    return True


def destroy_coordinator(group_name: str = "default",
                        generation: int = 0) -> bool:
    """Driver-side: kill a group's coordinator actor (after members have
    drained). A later init at the same name starts from fresh state."""
    import ray_tpu

    name = _coord_name(group_name, generation)
    try:
        coord = ray_tpu.get_actor(name)
    except ValueError:
        return False
    ray_tpu.kill(coord)
    # Wait until the name is actually deregistered: kill() is async, and
    # a fresh gang re-forming at the same name (cold restart after a
    # shrink below min_workers) must get-or-create a NEW coordinator, not
    # rendezvous with this dying one.
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        try:
            ray_tpu.get_actor(name)
        except ValueError:
            return True
        time.sleep(0.02)
    return True


def init_collective_group(world_size: int, rank: int,
                          backend: str = "host",
                          group_name: str = "default",
                          generation: int = 0) -> CollectiveGroup:
    """Join a collective group (call once per member)."""
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world_size {world_size}")
    group = CollectiveGroup(group_name, world_size, rank, backend, generation)
    _groups[group_name] = group
    return group


def get_group(group_name: str = "default") -> CollectiveGroup:
    if group_name not in _groups:
        raise ValueError(
            f"collective group {group_name!r} not initialized in this process"
        )
    return _groups[group_name]


def destroy_collective_group(group_name: str = "default"):
    _groups.pop(group_name, None)


def allreduce(tensor, group_name: str = "default", op: str = "sum"):
    return get_group(group_name).allreduce(tensor, op)


def allgather(tensor, group_name: str = "default"):
    return get_group(group_name).allgather(tensor)


def reducescatter(tensor, group_name: str = "default", op: str = "sum"):
    return get_group(group_name).reducescatter(tensor, op)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return get_group(group_name).broadcast(tensor, src_rank)


def barrier(group_name: str = "default"):
    get_group(group_name).barrier()


def send(tensor, dst_rank: int, group_name: str = "default", tag: int = 0):
    get_group(group_name).send(tensor, dst_rank, tag)


def recv(src_rank: int, group_name: str = "default", tag: int = 0):
    return get_group(group_name).recv(src_rank, tag)
