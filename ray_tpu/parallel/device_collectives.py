"""In-program collectives: XLA ops over ICI, the TPU data plane.

These are thin named wrappers around ``jax.lax`` collectives for use inside
``shard_map``/``pjit`` programs over a ray_tpu mesh. They replace the
reference's eager NCCL calls (util/collective/collective.py:258 allreduce,
:423 allgather, :472 reducescatter, :531/:594 send/recv): on TPU the
collective IS part of the compiled program and XLA schedules it onto ICI
links (scaling-book recipe), rather than a runtime service call.

Ring primitives (`ring_permute`, `ring_slice_exchange`) are the substrate
ring attention and pipeline microbatching build on.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

AxisName = Union[str, Sequence[str]]


def psum(x, axis: AxisName):
    import jax

    return jax.lax.psum(x, axis)


def pmean(x, axis: AxisName):
    import jax

    return jax.lax.pmean(x, axis)


def pmax(x, axis: AxisName):
    import jax

    return jax.lax.pmax(x, axis)


def pmin(x, axis: AxisName):
    import jax

    return jax.lax.pmin(x, axis)


def all_gather(x, axis: AxisName, *, gather_axis: int = 0, tiled: bool = True):
    """Gather shards along ``gather_axis`` across the mesh axis."""
    import jax

    return jax.lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x, axis: AxisName, *, scatter_axis: int = 0):
    """Sum-reduce then scatter along ``scatter_axis`` (ZeRO gradient path)."""
    import jax

    return jax.lax.psum_scatter(x, axis, scatter_dimension=scatter_axis,
                                tiled=True)


def all_to_all(x, axis: AxisName, *, split_axis: int, concat_axis: int):
    """All-to-all (the Ulysses/DeepSpeed sequence-parallel primitive)."""
    import jax

    return jax.lax.all_to_all(x, axis, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def axis_index(axis: AxisName):
    import jax

    return jax.lax.axis_index(axis)


def axis_size(axis: str):
    import jax

    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis)
    # jax < 0.5: psum of a literal folds to the static axis size
    return jax.lax.psum(1, axis)


def ring_permute(x, axis: str, shift: int = 1):
    """Send this shard to the neighbor ``shift`` steps around the ring and
    receive from the opposite neighbor — one hop of a ring collective
    (ppermute over ICI; the building block of ring attention)."""
    import jax

    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis, perm)


def ring_slice_exchange(kv, axis: str):
    """One ring-attention step: pass the current KV block to the next rank.

    Returns the block received from the previous rank. Used in a
    ``lax.fori_loop`` of ``axis_size`` steps so every rank sees every block
    while only ever holding 1/n of the sequence.
    """
    return ring_permute(kv, axis, shift=1)


def pbroadcast(x, axis: str, src: int = 0):
    """Broadcast src rank's value across the axis."""
    import jax
    import jax.numpy as jnp

    idx = jax.lax.axis_index(axis)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axis)
