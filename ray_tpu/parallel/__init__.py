"""Parallelism layer: meshes, shardings, and collectives.

- mesh: MeshSpec + build_mesh — named-axis device meshes (dp/fsdp/pp/sp/ep/tp)
- sharding: logical-axis rules → NamedShardings
- device_collectives: in-program XLA collectives over ICI (psum, all_gather,
  reduce_scatter, all_to_all, ring_permute)
- collective: host-level out-of-band collective groups across actors

Import cost note: jax is imported lazily inside functions; importing
ray_tpu.parallel does not pull jax.
"""

from ray_tpu.parallel.mesh import (  # noqa: F401
    AXIS_ORDER,
    DATA_AXES,
    MODEL_AXES,
    MeshSpec,
    build_hybrid_mesh,
    build_mesh,
    data_shard_axes,
    hybrid_mesh,
    local_mesh,
)
from ray_tpu.parallel.sharding import (  # noqa: F401
    DEFAULT_RULES,
    batch_sharding,
    logical_to_pspec,
    named_sharding,
    replicated,
    shard_pytree_like,
    with_logical_constraint,
)
from ray_tpu.parallel import collective  # noqa: F401
from ray_tpu.parallel import device_collectives  # noqa: F401
