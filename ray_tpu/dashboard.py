"""Dashboard-lite: a single-page cluster overview over the state API.

Reference: the Ray dashboard (python/ray/dashboard/) — here a stdlib HTTP
server with two routes: ``/`` renders an auto-refreshing HTML overview and
``/api/state`` returns the raw state_summary JSON (also the programmatic
endpoint the CLI's `status` could target remotely).
"""

from __future__ import annotations

import html
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<meta http-equiv="refresh" content="2">
<style>
 body {{ font-family: monospace; margin: 2em; background: #111;
        color: #ddd; }}
 h1 {{ color: #7fd4ff; }} h2 {{ color: #9f9; margin-bottom: 4px; }}
 table {{ border-collapse: collapse; }}
 td, th {{ border: 1px solid #444; padding: 3px 10px; text-align: left; }}
 .dead {{ color: #f77; }}
</style></head><body>
<h1>ray_tpu</h1>
<h2>resources</h2><pre>{resources}</pre>
<h2>tasks</h2><pre>{tasks}</pre>
<h2>objects</h2><pre>{objects}</pre>
<h2>nodes ({n_nodes})</h2><table><tr><th>id</th><th>address</th>
<th>state</th><th>resources</th></tr>{node_rows}</table>
<h2>actors ({n_actors})</h2><table><tr><th>id</th><th>name</th>
<th>state</th></tr>{actor_rows}</table>
</body></html>"""


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_GET(self):
        from ray_tpu import state

        try:
            s = state.state_summary()
        except Exception as e:  # noqa: BLE001
            self._reply(500, f"state unavailable: {e!r}".encode(),
                        "text/plain")
            return
        if self.path.startswith("/api"):
            self._reply(200, json.dumps(s, default=str).encode(),
                        "application/json")
            return
        node_rows = "".join(
            f"<tr><td>{n['node_id'][:12]}</td>"
            f"<td>{html.escape(str(n['address']))}</td>"
            f"<td class={'dead' if n['state'] != 'ALIVE' else 'ok'}>"
            f"{n['state']}</td>"
            f"<td>{html.escape(str(n['resources']))}</td></tr>"
            for n in s["nodes"])
        actor_rows = "".join(
            f"<tr><td>{a.get('actor_id', '')[:12]}</td>"
            f"<td>{html.escape(str(a.get('name') or ''))}</td>"
            f"<td>{a.get('state', '')}</td></tr>"
            for a in s["actors"])
        page = _PAGE.format(
            resources=html.escape(
                f"total: {s['cluster_resources']}\n"
                f"avail: {s['available_resources']}"),
            tasks=html.escape(str(s["tasks"])),
            objects=html.escape(str(s["objects"])),
            n_nodes=len(s["nodes"]), node_rows=node_rows,
            n_actors=len(s["actors"]), actor_rows=actor_rows)
        self._reply(200, page.encode(), "text/html")

    def _reply(self, code: int, body: bytes, ctype: str):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


_server: Optional[ThreadingHTTPServer] = None


def start_dashboard(host: str = "127.0.0.1", port: int = 0):
    global _server
    if _server is None:
        _server = ThreadingHTTPServer((host, port), _Handler)
        threading.Thread(target=_server.serve_forever, daemon=True,
                         name="dashboard-http").start()
    return _server.server_address


def stop_dashboard():
    global _server
    if _server is not None:
        _server.shutdown()
        _server.server_close()  # release the listening socket now
        _server = None
