"""Dashboard-lite: a single-page cluster overview over the state API.

Reference: the Ray dashboard (python/ray/dashboard/) — here a stdlib HTTP
server with these routes: ``/`` renders an auto-refreshing HTML overview
(including inline-SVG TIME-SERIES sparklines of cluster metrics — the
role of the reference's embedded Grafana panels, dependency-free),
``/api/state`` returns the raw state_summary JSON, and
``/api/metrics/history`` the sampled series.
"""

from __future__ import annotations

import html
import json
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional


class _History:
    """Ring-buffered samples of cluster gauges for the sparkline view
    (reference role: dashboard/modules/metrics time-series panels)."""

    MAXLEN = 300

    def __init__(self, period_s: float = 2.0):
        self.period_s = period_s
        self._lock = threading.Lock()
        self._t: deque = deque(maxlen=self.MAXLEN)
        self._series: Dict[str, deque] = {}
        self._stop = False
        threading.Thread(target=self._loop, daemon=True,
                         name="dash-sampler").start()

    def _loop(self):
        while not self._stop:
            time.sleep(self.period_s)
            try:
                self._sample()
            except Exception:  # noqa: BLE001 — sampling must not die
                pass

    def _sample(self):
        from ray_tpu import state

        s = state.state_summary()
        tasks = s.get("tasks") or {}
        objs = s.get("objects") or {}
        now = time.time()
        vals = {
            "nodes_alive": sum(1 for n in s.get("nodes", [])
                               if n.get("state") == "ALIVE"),
            "actors": len(s.get("actors", [])),
            "tasks_queued": float(tasks.get("queued", 0) or 0),
            "tasks_running": float(tasks.get("running", 0) or 0),
            "objects_tracked": float(objs.get("tracked", 0) or 0),
            "store_bytes": float(
                objs.get("store_bytes_in_use",
                         objs.get("spilled_bytes", 0)) or 0),
        }
        with self._lock:
            self._t.append(now)
            for k, v in vals.items():
                self._series.setdefault(
                    k, deque(maxlen=self.MAXLEN)).append(float(v))

    def snapshot(self) -> dict:
        with self._lock:
            return {"t": list(self._t),
                    "series": {k: list(v)
                               for k, v in self._series.items()}}

    def sparklines_html(self) -> str:
        snap = self.snapshot()
        out = []
        for name, ys in sorted(snap["series"].items()):
            if len(ys) < 2:
                continue
            lo, hi = min(ys), max(ys)
            span = (hi - lo) or 1.0
            w, h = 240, 36
            n = len(ys)
            pts = " ".join(
                f"{i * w / (n - 1):.1f},"
                f"{h - 3 - (y - lo) / span * (h - 6):.1f}"
                for i, y in enumerate(ys))
            out.append(
                f"<div class=spark><span>{html.escape(name)}: "
                f"{ys[-1]:g}</span><svg width={w} height={h}>"
                f"<polyline points='{pts}' fill='none' "
                f"stroke='#7fd4ff' stroke-width='1.5'/></svg></div>")
        return "".join(out) or "<i>collecting…</i>"


_history: Optional[_History] = None

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<meta http-equiv="refresh" content="2">
<style>
 body {{ font-family: monospace; margin: 2em; background: #111;
        color: #ddd; }}
 h1 {{ color: #7fd4ff; }} h2 {{ color: #9f9; margin-bottom: 4px; }}
 table {{ border-collapse: collapse; }}
 td, th {{ border: 1px solid #444; padding: 3px 10px; text-align: left; }}
 .dead {{ color: #f77; }}
 .spark {{ display: inline-block; margin: 0 14px 8px 0; }}
 .spark span {{ display: block; color: #9f9; font-size: 12px; }}
 .spark svg {{ background: #181818; border: 1px solid #333; }}
</style></head><body>
<h1>ray_tpu</h1>
<h2>metrics</h2><div>{sparklines}</div>
<h2>resources</h2><pre>{resources}</pre>
<h2>tasks</h2><pre>{tasks}</pre>
<h2>objects</h2><pre>{objects}</pre>
<h2>nodes ({n_nodes})</h2><table><tr><th>id</th><th>address</th>
<th>state</th><th>resources</th></tr>{node_rows}</table>
<h2>actors ({n_actors})</h2><table><tr><th>id</th><th>name</th>
<th>state</th></tr>{actor_rows}</table>
</body></html>"""


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_GET(self):
        from ray_tpu import state

        hist = _history  # read once: stop_dashboard() may null the global
        if self.path.startswith("/api/metrics/history"):
            snap = hist.snapshot() if hist else {}
            self._reply(200, json.dumps(snap).encode(),
                        "application/json")
            return
        try:
            s = state.state_summary()
        except Exception as e:  # noqa: BLE001
            self._reply(500, f"state unavailable: {e!r}".encode(),
                        "text/plain")
            return
        if self.path.startswith("/api"):
            self._reply(200, json.dumps(s, default=str).encode(),
                        "application/json")
            return
        node_rows = "".join(
            f"<tr><td>{n['node_id'][:12]}</td>"
            f"<td>{html.escape(str(n['address']))}</td>"
            f"<td class={'dead' if n['state'] != 'ALIVE' else 'ok'}>"
            f"{n['state']}</td>"
            f"<td>{html.escape(str(n['resources']))}</td></tr>"
            for n in s["nodes"])
        actor_rows = "".join(
            f"<tr><td>{a.get('actor_id', '')[:12]}</td>"
            f"<td>{html.escape(str(a.get('name') or ''))}</td>"
            f"<td>{a.get('state', '')}</td></tr>"
            for a in s["actors"])
        page = _PAGE.format(
            sparklines=(hist.sparklines_html() if hist
                        else "<i>sampler off</i>"),
            resources=html.escape(
                f"total: {s['cluster_resources']}\n"
                f"avail: {s['available_resources']}"),
            tasks=html.escape(str(s["tasks"])),
            objects=html.escape(str(s["objects"])),
            n_nodes=len(s["nodes"]), node_rows=node_rows,
            n_actors=len(s["actors"]), actor_rows=actor_rows)
        self._reply(200, page.encode(), "text/html")

    def _reply(self, code: int, body: bytes, ctype: str):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


_server: Optional[ThreadingHTTPServer] = None


def start_dashboard(host: str = "127.0.0.1", port: int = 0):
    global _server, _history
    if _server is None:
        _server = ThreadingHTTPServer((host, port), _Handler)
        threading.Thread(target=_server.serve_forever, daemon=True,
                         name="dashboard-http").start()
        _history = _History()
    return _server.server_address


def stop_dashboard():
    global _server, _history
    if _server is not None:
        _server.shutdown()
        _server.server_close()  # release the listening socket now
        _server = None
    if _history is not None:
        _history._stop = True
        _history = None
