"""Dashboard-lite: a single-page cluster overview over the state API.

Reference: the Ray dashboard (python/ray/dashboard/) — here a stdlib HTTP
server with these routes: ``/`` serves a client-rendered single-file
app — tabs (overview/nodes/actors/tasks/objects), canvas TIME-SERIES
charts of the sampled cluster metrics (the role of the reference's
embedded Grafana panels), a 2s fetch loop, zero dependencies and no
build step: the analogue of the reference's React dashboard/client
build; ``/api/state`` returns the raw state_summary JSON and
``/api/metrics/history`` the sampled series.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional


class _History:
    """Ring-buffered samples of cluster gauges for the sparkline view
    (reference role: dashboard/modules/metrics time-series panels)."""

    MAXLEN = 300

    def __init__(self, period_s: float = 2.0):
        self.period_s = period_s
        self._lock = threading.Lock()
        self._t: deque = deque(maxlen=self.MAXLEN)
        self._series: Dict[str, deque] = {}
        self._stop = False
        threading.Thread(target=self._loop, daemon=True,
                         name="dash-sampler").start()

    def _loop(self):
        while not self._stop:
            time.sleep(self.period_s)
            try:
                self._sample()
            except Exception:  # noqa: BLE001 — sampling must not die
                pass

    def _sample(self):
        from ray_tpu import state

        s = state.state_summary()
        tasks = s.get("tasks") or {}
        objs = s.get("objects") or {}
        now = time.time()
        vals = {
            "nodes_alive": sum(1 for n in s.get("nodes", [])
                               if n.get("state") == "ALIVE"),
            "actors": len(s.get("actors", [])),
            "tasks_queued": float(tasks.get("queued", 0) or 0),
            "tasks_running": float(tasks.get("running", 0) or 0),
            "objects_tracked": float(objs.get("tracked", 0) or 0),
            "store_bytes": float(
                objs.get("store_bytes_in_use",
                         objs.get("spilled_bytes", 0)) or 0),
        }
        with self._lock:
            self._t.append(now)
            for k, v in vals.items():
                self._series.setdefault(
                    k, deque(maxlen=self.MAXLEN)).append(float(v))

    def snapshot(self) -> dict:
        with self._lock:
            return {"t": list(self._t),
                    "series": {k: list(v)
                               for k, v in self._series.items()}}

_APP = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<meta charset="utf-8">
<style>
 :root { --bg:#101216; --panel:#181b21; --line:#2a2f38; --fg:#d7dce3;
         --accent:#7fd4ff; --good:#8fe08f; --bad:#f08f8f; --dim:#8b93a1; }
 body { font-family: ui-monospace, monospace; margin:0; background:var(--bg);
        color:var(--fg); }
 header { display:flex; align-items:baseline; gap:18px; padding:14px 22px;
          border-bottom:1px solid var(--line); flex-wrap:wrap; }
 header h1 { margin:0; font-size:18px; color:var(--accent); }
 .chip { background:var(--panel); border:1px solid var(--line);
         border-radius:6px; padding:4px 10px; font-size:12px; }
 .chip b { color:var(--good); }
 nav { display:flex; gap:4px; padding:10px 22px 0; }
 nav button { background:var(--panel); color:var(--dim); border:1px solid
   var(--line); border-bottom:none; border-radius:6px 6px 0 0;
   padding:6px 16px; cursor:pointer; font:inherit; }
 nav button.on { color:var(--fg); background:var(--bg);
   border-color:var(--accent); }
 main { padding:16px 22px; }
 table { border-collapse:collapse; width:100%; font-size:13px; }
 td,th { border:1px solid var(--line); padding:4px 10px; text-align:left; }
 th { color:var(--accent); background:var(--panel); }
 .dead { color:var(--bad); } .alive { color:var(--good); }
 .charts { display:grid; grid-template-columns:repeat(auto-fill,
   minmax(270px,1fr)); gap:14px; margin-top:10px; }
 .chart { background:var(--panel); border:1px solid var(--line);
   border-radius:6px; padding:8px; }
 .chart .t { font-size:12px; color:var(--good); margin-bottom:4px; }
 .chart .v { float:right; color:var(--dim); }
 canvas { width:100%; height:64px; display:block; }
 h2 { color:var(--good); font-size:14px; margin:18px 0 6px; }
 pre { background:var(--panel); border:1px solid var(--line); padding:8px;
   border-radius:6px; overflow:auto; }
 #err { color:var(--bad); padding:4px 22px; }
</style></head><body>
<header><h1>ray_tpu</h1><div id=chips></div></header>
<div id=err></div>
<nav id=tabs></nav>
<main id=main></main>
<script>
"use strict";
const TABS = ["overview", "nodes", "actors", "tasks", "objects"];
let tab = "overview", S = null, H = null;

const esc = s => String(s).replace(/[&<>"]/g,
  c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;"}[c]));

function chips() {
  if (!S) return "";
  const nodes = S.nodes || [];
  const alive = nodes.filter(n => n.state === "ALIVE").length;
  const cr = S.cluster_resources || {}, ar = S.available_resources || {};
  const t = S.tasks || {}, o = S.objects || {};
  return [
    `nodes <b>${alive}</b>/${nodes.length}`,
    `CPU <b>${ar.CPU ?? "?"}</b>/${cr.CPU ?? "?"} free`,
    `tasks ${Object.entries(t).map(([k, v]) => `${esc(k)} <b>${v}</b>`)
        .join(" ") || "-"}`,
    `objects <b>${o.tracked ?? "-"}</b>` +
      (o.store_bytes_in_use != null ?
        ` (${(o.store_bytes_in_use / 1048576).toFixed(1)} MB)` : ""),
    `actors <b>${(S.actors || []).length}</b>`,
  ].map(c => `<span class=chip>${c}</span>`).join(" ");
}

function kvTable(obj) {
  if (obj == null) return "<i>none</i>";
  if (typeof obj !== "object") return `<pre>${esc(obj)}</pre>`;
  const rows = Object.entries(obj).map(([k, v]) =>
    `<tr><td>${esc(k)}</td><td>${esc(
      typeof v === "object" ? JSON.stringify(v) : v)}</td></tr>`);
  return `<table><tr><th>key</th><th>value</th></tr>${rows.join("")}</table>`;
}

function listTable(rows, cols) {
  if (!rows || !rows.length) return "<i>none</i>";
  const head = cols.map(c => `<th>${esc(c)}</th>`).join("");
  const body = rows.map(r => "<tr>" + cols.map(c => {
    let v = r[c]; if (v == null) v = "";
    if (typeof v === "object") v = JSON.stringify(v);
    v = String(v);
    const cls = c === "state" ?
      (v === "ALIVE" || v === "RUNNING" ? "alive" : "dead") : "";
    return `<td class="${cls}">${esc(v.length > 90 ?
      v.slice(0, 90) + "…" : v)}</td>`;
  }).join("") + "</tr>").join("");
  return `<table><tr>${head}</tr>${body}</table>`;
}

function drawChart(cv, xs) {
  const dpr = window.devicePixelRatio || 1;
  const w = cv.clientWidth * dpr, h = cv.clientHeight * dpr;
  cv.width = w; cv.height = h;
  const g = cv.getContext("2d");
  g.clearRect(0, 0, w, h);
  if (xs.length < 2) return;
  const lo = Math.min(...xs), hi = Math.max(...xs), span = (hi - lo) || 1;
  g.strokeStyle = "#7fd4ff"; g.lineWidth = 1.5 * dpr; g.beginPath();
  xs.forEach((v, i) => {
    const x = i / (xs.length - 1) * (w - 4) + 2;
    const y = h - 3 - (v - lo) / span * (h - 6);
    i ? g.lineTo(x, y) : g.moveTo(x, y);
  });
  g.stroke();
}

function render() {
  document.getElementById("chips").innerHTML = chips();
  document.getElementById("tabs").innerHTML = TABS.map(t =>
    `<button class="${t === tab ? "on" : ""}"
      onclick="setTab('${t}')">${t}</button>`).join("");
  const m = document.getElementById("main");
  if (!S) { m.innerHTML = "<i>loading…</i>"; return; }
  if (tab === "overview") {
    const series = H && H.series ? Object.entries(H.series) : [];
    m.innerHTML = `
      <div class=charts>${series.map(([name, xs], i) => `
        <div class=chart><div class=t>${esc(name)}
          <span class=v>${xs.length ? esc(
            (+xs[xs.length - 1]).toPrecision(4)) : ""}</span></div>
        <canvas id=c${i}></canvas></div>`).join("") ||
        "<i>sampler warming up…</i>"}</div>
      <h2>resources</h2>${kvTable({total: S.cluster_resources,
                                   available: S.available_resources})}`;
    series.forEach(([_, xs], i) =>
      drawChart(document.getElementById("c" + i), xs.map(Number)));
  } else if (tab === "nodes") {
    m.innerHTML = listTable(S.nodes, ["node_id", "address", "state",
                                      "resources"]);
  } else if (tab === "actors") {
    m.innerHTML = listTable(S.actors, ["actor_id", "name", "state"]);
  } else if (tab === "tasks") {
    m.innerHTML = kvTable(S.tasks);
  } else if (tab === "objects") {
    m.innerHTML = kvTable(S.objects);
  }
}
window.setTab = t => { tab = t; render(); };

async function tick() {
  try {
    const [s, h] = await Promise.all([
      fetch("/api/state").then(r => r.json()),
      fetch("/api/metrics/history").then(r => r.json())]);
    S = s; H = h;
    document.getElementById("err").textContent = "";
  } catch (e) {
    document.getElementById("err").textContent =
      "state unavailable: " + e;
  }
  render();
}
tick();
setInterval(tick, 2000);
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_GET(self):
        hist = _history  # read once: stop_dashboard() may null the global
        if self.path.startswith("/api/metrics/history"):
            snap = hist.snapshot() if hist else {}
            self._reply(200, json.dumps(snap).encode(),
                        "application/json")
            return
        if self.path.startswith("/api"):
            from ray_tpu import state

            try:
                s = state.state_summary()
            except Exception as e:  # noqa: BLE001
                self._reply(500, f"state unavailable: {e!r}".encode(),
                            "text/plain")
                return
            self._reply(200, json.dumps(s, default=str).encode(),
                        "application/json")
            return
        # client-rendered single-file app (the reference ships a React
        # build, dashboard/client/; this is the no-build-step analogue:
        # fetch /api/state + /api/metrics/history every 2s, render tabs
        # and canvas time-series without page reloads)
        self._reply(200, _APP.encode(), "text/html")

    def _reply(self, code: int, body: bytes, ctype: str):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


_server: Optional[ThreadingHTTPServer] = None


def start_dashboard(host: str = "127.0.0.1", port: int = 0):
    global _server, _history
    if _server is None:
        _server = ThreadingHTTPServer((host, port), _Handler)
        threading.Thread(target=_server.serve_forever, daemon=True,
                         name="dashboard-http").start()
        _history = _History()
    return _server.server_address


def stop_dashboard():
    global _server, _history
    if _server is not None:
        _server.shutdown()
        _server.server_close()  # release the listening socket now
        _server = None
    if _history is not None:
        _history._stop = True
        _history = None
