"""Top-level public API: init/shutdown/remote/get/put/wait/kill.

Mirrors the reference's core API surface (python/ray/_private/worker.py —
ray.init :1227, ray.get :2578, ray.put :2693, ray.wait :2758, ray.kill :2939)
on the TPU-native runtime.
"""

from __future__ import annotations

import atexit
import os
from typing import Any, List, Optional, Sequence, Tuple, Union

from ray_tpu.core import runtime_context
from ray_tpu.core.actor import ActorClass, ActorHandle, get_actor  # noqa: F401
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.remote_function import RemoteFunction

_runtime = None


def init(num_workers: Optional[int] = None,
         object_store_memory: Optional[int] = None,
         ignore_reinit_error: bool = True,
         address: Optional[str] = None,
         log_to_driver: Optional[bool] = None,
         **kwargs):
    """Start the local runtime (worker pool + shm object store), or connect
    to a running cluster when ``address="host:port"`` names its GCS
    (reference: ray.init(address=...), python/ray/_private/worker.py:1227).

    Returns the runtime context. Safe to call twice with
    ``ignore_reinit_error`` (the default).
    """
    global _runtime
    if runtime_context.is_initialized():
        if ignore_reinit_error:
            return runtime_context.get_runtime_context()
        raise RuntimeError("ray_tpu.init() called twice")
    if address is None:
        # submitted jobs inherit the cluster address from the job agent
        address = os.environ.get("RTPU_ADDRESS")
    if address and address.startswith("ray://"):
        # thin-client mode through the multi-tenant proxy (reference:
        # ray.init("ray://...") -> util/client; see ray_tpu/client.py)
        from ray_tpu.client import ProxyCore

        host, _, port = address[len("ray://"):].rpartition(":")
        _runtime = ProxyCore((host, int(port)))
    elif address:
        from ray_tpu.core.cluster.cluster_core import ClusterCore

        host, _, port = address.rpartition(":")
        _runtime = ClusterCore((host, int(port)))
    else:
        from ray_tpu.core.runtime import Runtime

        _runtime = Runtime(num_workers=num_workers,
                           object_store_memory=object_store_memory,
                           log_to_driver=log_to_driver)
    runtime_context.set_core(_runtime)
    atexit.register(shutdown)
    return runtime_context.get_runtime_context()


def is_initialized() -> bool:
    return runtime_context.is_initialized()


def shutdown():
    global _runtime
    if _runtime is not None:
        _runtime.shutdown()
        _runtime = None
    if runtime_context.get_core_or_none() is not None:
        runtime_context.set_core(None)


def remote(*args, **options):
    """Decorator converting a function into a RemoteFunction or a class into
    an ActorClass (reference: python/ray/_private/worker.py ray.remote)."""

    def decorate(obj):
        if isinstance(obj, type):
            return ActorClass(obj, options)
        if callable(obj):
            return RemoteFunction(obj, options)
        raise TypeError("@remote requires a function or class")

    if len(args) == 1 and not options and (callable(args[0]) or isinstance(args[0], type)):
        return decorate(args[0])
    if args:
        raise TypeError("@remote takes keyword options only, e.g. @remote(num_cpus=2)")
    return decorate


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        timeout: Optional[float] = None) -> Any:
    """Block until object(s) are available and return the value(s)."""
    core = runtime_context.get_core()
    if isinstance(refs, ObjectRef):
        return core.get_objects([refs], timeout=timeout)[0]
    refs = list(refs)
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() expects ObjectRefs, got {type(r).__name__}")
    if not refs:
        return []
    return core.get_objects(refs, timeout=timeout)


def put(value: Any) -> ObjectRef:
    """Store a value in the object store and return a ref."""
    core = runtime_context.get_core()
    return core.put_object(value)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None
         ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
    """Wait until ``num_returns`` of ``refs`` are ready."""
    core = runtime_context.get_core()
    refs = list(refs)
    return core.wait(refs, num_returns=num_returns, timeout=timeout)


def kill(actor: ActorHandle, *, no_restart: bool = True):
    """Forcibly terminate an actor (reference: ray.kill, worker.py:2939).

    With ``no_restart=True`` (the default) the death is terminal: pending
    and future calls fail with ``ActorDiedError`` and the restart spec is
    dropped so nothing resurrects the actor. With ``no_restart=False``
    the kill behaves exactly like a worker crash: it consumes one unit of
    the actor's ``max_restarts`` budget and, if budget remains, the actor
    restarts — in-flight calls with ``max_task_retries`` left replay
    against the new incarnation and calls submitted meanwhile buffer
    through the RESTARTING window."""
    core = runtime_context.get_core()
    core.kill_actor(actor.actor_id, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    """Cancel the task that produces ``ref`` (reference: ray.cancel,
    worker.py:2970). Queued tasks are dropped; executing tasks are
    interrupted (force=False) or their worker killed (force=True). The
    caller sees TaskCancelledError at ``get``. Accepts an
    ``ObjectRefGenerator`` to cancel a ``num_returns="streaming"`` task
    mid-stream (the consumer's next ref raises, then the stream ends).
    ``recursive`` is accepted for API parity; child-task cancellation
    follows worker death."""
    del recursive
    core = runtime_context.get_core()
    from ray_tpu.core.ids import ObjectID
    from ray_tpu.core.object_ref import ObjectRefGenerator

    if isinstance(ref, ObjectRefGenerator):
        ref = ObjectRef(ObjectID(ref.seed), core=core)
    core.cancel_task(ref, force=force)


def free(refs, *, local_only: bool = False) -> int:
    """Eagerly delete objects from the store (reference:
    ray._private.internal_api.free). Complements the pin+spill lifetime
    model when the caller knows an object is dead: storage (shm or spill
    file) is reclaimed immediately, the id's lineage entry is
    invalidated, and subsequent ``get``s raise ObjectLostError — free
    means dead, reconstruction is never attempted for a freed id.

    That makes ``free`` the exception to the recovery rule: objects a
    TASK produced that are lost to LRU eviction, a missing/corrupt spill
    file, or worker death are otherwise transparently recomputed from
    recorded lineage (resubmitting the producing task, recursively
    rebuilding lost upstream deps) up to ``max_reconstructions``
    attempts per object. Not recoverable — ``get`` raises
    ObjectLostError naming the producing task and the attempt history
    where one exists: ``ray_tpu.put`` objects (no producing task),
    freed ids, and ids whose lineage was evicted past the
    ``lineage_max_bytes`` budget. Deterministic loss for tests is
    injected via ``ray_tpu.core.fault_injection`` (``RTPU_FAULT_<SITE>``
    env vars or the ``fault_injection`` config flag).

    Returns the number of objects actually freed. ``local_only`` is
    accepted for API parity (deletion always covers the owning core)."""
    del local_only
    if isinstance(refs, ObjectRef):
        refs = [refs]
    core = runtime_context.get_core()
    return core.free_objects([r.binary() for r in refs])


def timeline(filename: Optional[str] = None):
    """Export recorded task events as a chrome://tracing trace (reference:
    ray.timeline, python/ray/_private/worker.py). Requires the
    RTPU_TASK_EVENTS_ENABLED=1 flag; returns the event list when no
    filename is given."""
    import json

    core = runtime_context.get_core()
    events = getattr(core, "_events", None)
    if events is None and hasattr(core, "_cluster_view"):
        # cluster driver: aggregate every node's flag-gated event log
        # (reference: ray.timeline merges per-raylet task events)
        from ray_tpu.core.cluster.rpc import RpcClient, RpcError

        events = None
        for idx, n in enumerate(core._cluster_view(force=True)["nodes"]):
            # dedicated short-timeout client: a freshly-dead node must
            # cost ~2s, not the pooled client's full 10s connect retry
            client = RpcClient(tuple(n["address"]), core._authkey,
                               connect_timeout=2.0)
            try:
                node_events = client.call(("task_events",))
            except RpcError:
                continue
            finally:
                client.close()
            if node_events is None:
                continue  # recording disabled on that node
            events = events if events is not None else []
            nid = n["node_id"].hex()[:6] if hasattr(
                n["node_id"], "hex") else str(n["node_id"])[:6]
            for e in node_events:
                # composite pid: same OS pid on different hosts must not
                # merge into one chrome-trace process row
                events.append({**e, "worker": f"{nid}:{e['worker']}",
                               "pid": idx * (1 << 23) + int(e["pid"] or 0)})
    if events is None:
        raise RuntimeError(
            "task events are disabled; set RTPU_TASK_EVENTS_ENABLED=1 "
            "before init()")
    trace = [{
        "name": e["fn"],
        "cat": "actor_task" if e["actor"] else "task",
        "ph": "X",
        "ts": e["dispatched"] * 1e6,
        "dur": max(0.0, (e["done"] - e["dispatched"]) * 1e6),
        "pid": e["pid"],
        "tid": e["worker"],
        "args": {"task_id": e["task_id"],
                 "parent_task_id": e.get("parent_task_id"),
                 "queued_ms": round(max(
                     0.0, (e["dispatched"] - e.get("submitted",
                                                   e["dispatched"]))
                 ) * 1e3, 3)},
    } for e in events]
    if filename is None:
        return trace
    with open(filename, "w") as f:
        json.dump(trace, f)
    return filename


def method(**opts):
    """Decorator for actor methods to set options (num_returns)."""

    def wrap(fn):
        fn.__rtpu_method_opts__ = opts
        return fn

    return wrap
