"""rtpu-race: deterministic interleaving fuzzer for thread schedules.

See :mod:`ray_tpu.tools.race.interleave`.
"""

from ray_tpu.tools.race.interleave import (arm, arm_from_env, disarm,
                                           parse_env, schedule, sweep)

__all__ = ["arm", "arm_from_env", "disarm", "parse_env", "schedule",
           "sweep"]
