"""Deterministic interleaving fuzzer: seeded preemption injection.

The static rules (L5/L7) prove lock DISCIPLINE; this tool attacks lock
OMISSION dynamically. It installs a ``sys.settrace`` line tracer in
threads started while armed and, at deterministically-chosen line
events, forces a context switch (a tiny ``time.sleep`` releases the
GIL, letting any runnable sibling thread interleave). Races that hide
behind the GIL's coarse default switch interval — read-modify-write on
a shared field, check-then-act windows — surface orders of magnitude
faster under this schedule perturbation, and the same seed replays the
same per-thread preemption schedule.

Determinism model
-----------------
Each traced thread draws from its OWN ``random.Random`` seeded with
``(seed, thread name)``, so whether thread T preempts at its k-th
traced line event is a pure function of the seed and T's name — never
of wall-clock timing or sibling threads. The recorded per-thread
schedule (the sequence of ``(file, line)`` preemption points) is
therefore identical across runs of the same seeded workload, which is
asserted in the fuzzer's own tests. Name your threads.

Protocol
--------
``RTPU_INTERLEAVE=<seed>`` arms one deterministic schedule (replay);
``RTPU_INTERLEAVE=<seed>:<n>`` denotes the bounded sweep ``seed ..
seed+n-1`` (``parse_env``/:func:`sweep` consume it). On an assertion
failure or :class:`~ray_tpu.util.debug_lock.LockOrderError` inside
``sweep``, the failing seed is printed — export it back through
``RTPU_INTERLEAVE`` to replay that exact schedule under a debugger.

Relation to ``RTPU_SANITIZE``: the sanitizer detects lock-ORDER bugs
on schedules that happen; the interleaver manufactures adversarial
schedules. Armed together (the chaos suites do), the fuzzer drives the
program into orderings where the sanitizer — and plain asserts — can
see the bug.

Only threads STARTED while armed are traced (``threading.settrace``),
plus the arming thread itself; instrumentation is restricted to module
paths matching ``modules`` substrings, and each thread stops preempting
after ``max_preemptions`` so an armed long-lived suite degrades to
native speed instead of timing out.
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

ENV = "RTPU_INTERLEAVE"

#: default module-path substrings to instrument: the concurrency surface
DEFAULT_MODULES = (
    f"ray_tpu{os.sep}core{os.sep}",
    f"ray_tpu{os.sep}dag{os.sep}",
    f"ray_tpu{os.sep}serve{os.sep}",
    f"ray_tpu{os.sep}train{os.sep}",
)

#: preemption sleep: long enough to release the GIL and let any runnable
#: sibling run, short enough that hundreds of preemptions stay cheap
_PREEMPT_SLEEP_S = 0.0002


class _State:
    """One armed session (module-global singleton under ``_STATE``)."""

    def __init__(self, seed: int, modules: Tuple[str, ...],
                 preempt_prob: float, max_preemptions: int):
        self.seed = seed
        self.modules = modules
        self.preempt_prob = preempt_prob
        self.max_preemptions = max_preemptions
        self.local = threading.local()
        #: thread name -> ordered preemption points (file, line)
        self.schedule: Dict[str, List[Tuple[str, int]]] = {}
        self.schedule_lock = threading.Lock()


_STATE: Optional[_State] = None


def _thread_slot(st: _State):
    """Per-thread (rng, budget box, schedule list), created on first
    traced event in the thread. Seeded (seed, thread name): the
    preemption decisions of a thread depend only on the seed and its
    own deterministic sequence of traced line events."""
    slot = getattr(st.local, "slot", None)
    if slot is None:
        name = threading.current_thread().name
        rng = random.Random(f"{st.seed}\x00{name}")
        sched: List[Tuple[str, int]] = []
        with st.schedule_lock:
            # re-used thread names share one recorded lane, appended in
            # per-thread deterministic order
            sched = st.schedule.setdefault(name, sched)
        slot = (rng, [st.max_preemptions], sched)
        st.local.slot = slot
    return slot


def _local_trace(frame, event, arg):
    st = _STATE
    if st is None:
        return None
    if event == "line":
        rng, budget, sched = _thread_slot(st)
        if budget[0] > 0 and rng.random() < st.preempt_prob:
            budget[0] -= 1
            sched.append((os.path.basename(frame.f_code.co_filename),
                          frame.f_lineno))
            time.sleep(_PREEMPT_SLEEP_S)
    return _local_trace


def _global_trace(frame, event, arg):
    st = _STATE
    if st is None:
        return None
    if event != "call":
        return None
    fname = frame.f_code.co_filename
    for frag in st.modules:
        if frag in fname:
            return _local_trace
    return None  # foreign module: do not trace this frame's lines


def arm(seed: int, modules: Iterable[str] = DEFAULT_MODULES,
        preempt_prob: float = 0.05, max_preemptions: int = 500,
        trace_current: bool = True) -> None:
    """Start injecting preemptions. Affects threads started from now on
    (``threading.settrace``) and — with ``trace_current`` — the calling
    thread too. Re-arming replaces the previous session."""
    global _STATE
    _STATE = _State(int(seed), tuple(modules), float(preempt_prob),
                    int(max_preemptions))
    threading.settrace(_global_trace)
    if trace_current:
        sys.settrace(_global_trace)


def disarm() -> None:
    """Stop injecting. Threads already running keep their (now inert)
    tracer until they next hit it — ``_STATE is None`` short-circuits,
    so the residual cost is one attribute load per event."""
    global _STATE
    _STATE = None
    threading.settrace(None)  # type: ignore[arg-type]
    if sys.gettrace() is _global_trace:
        sys.settrace(None)


def schedule() -> Dict[str, List[Tuple[str, int]]]:
    """The armed session's recorded preemption points, per thread name.
    Deterministic for a fixed seed and seeded workload."""
    st = _STATE
    if st is None:
        return {}
    with st.schedule_lock:
        return {k: list(v) for k, v in st.schedule.items()}


def parse_env(value: Optional[str] = None
              ) -> Optional[Tuple[int, int]]:
    """Parse ``RTPU_INTERLEAVE`` into ``(seed, n_seeds)``; ``None`` when
    unset/empty/malformed. ``"7"`` -> ``(7, 1)``; ``"7:20"`` ->
    ``(7, 20)``."""
    raw = os.environ.get(ENV, "") if value is None else value
    raw = raw.strip()
    if not raw:
        return None
    head, _, tail = raw.partition(":")
    try:
        seed = int(head)
        n = int(tail) if tail else 1
    except ValueError:
        return None
    return (seed, max(1, n))


def arm_from_env(**kwargs) -> Optional[int]:
    """Arm from ``RTPU_INTERLEAVE`` (first seed of a ``seed:n`` range);
    no-op returning None when the variable is unset. Returns the armed
    seed for logging."""
    parsed = parse_env()
    if parsed is None:
        return None
    seed, _ = parsed
    arm(seed, **kwargs)
    return seed


def sweep(fn: Callable[[], None], seeds: Iterable[int],
          modules: Iterable[str] = DEFAULT_MODULES,
          preempt_prob: float = 0.05, max_preemptions: int = 500
          ) -> int:
    """Run ``fn`` once per seed under that seed's schedule. On an
    assertion or lock-order failure the FAILING SEED is printed (replay:
    ``RTPU_INTERLEAVE=<seed>``) and the error re-raised. Returns the
    number of seeds that passed."""
    from ray_tpu.util.debug_lock import LockOrderError

    passed = 0
    for seed in seeds:
        arm(seed, modules=modules, preempt_prob=preempt_prob,
            max_preemptions=max_preemptions)
        try:
            fn()
        except (AssertionError, LockOrderError) as e:
            print(f"rtpu-race: seed {seed} FAILED ({type(e).__name__}); "
                  f"replay with {ENV}={seed}", file=sys.stderr)
            raise
        finally:
            disarm()
        passed += 1
    return passed
