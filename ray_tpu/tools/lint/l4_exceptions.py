"""L4 — exception discipline in ``ray_tpu/core/``, ``ray_tpu/train/``,
and ``ray_tpu/parallel/`` (the recovery-contract surface).

These shapes are flagged:

1. Swallowing handlers: a bare ``except:`` anywhere, or an ``except
   Exception:``/``except BaseException:`` whose body does nothing (only
   ``pass``/``...``/``continue``). Broad catches are sometimes right
   (best-effort cleanup of already-dead resources), but each one must
   either narrow its type, do something observable (log, count,
   convert), or carry an explicit ``# rtpu-lint: disable=L4`` waiver
   with a justification.

2. Dropped ``ObjectLostError``: a handler that catches
   ``ObjectLostError`` must re-raise it, raise a converted error, or
   call into reconstruction — PR 1's recovery contract routes every
   lost-object signal to lineage resubmission, and a handler that
   swallows the signal silently disables recovery for that path.

3. Dropped ``ActorDiedError``: same contract for the actor plane — a
   handler that catches ``ActorDiedError`` must re-raise it, convert it,
   or route into the restart/retry machinery (restart, retry, resubmit,
   replay, re-resolve). Swallowing the death signal silently turns a
   restartable actor's failure into a hang or a lost call.

4. Dropped ``TrainingWorkerError`` / ``CollectiveAbortedError``: the
   elastic-training contract routes both signals into gang resize or
   gang restart — a handler that swallows either silently converts a
   recoverable preemption into a hang (peers stuck in collectives) or a
   lost run. Same handling test as ActorDiedError, with the resize verbs
   (resize, shrink, grow, abort, interrupt, drain) also counting as
   routing.

5. Dropped ``BackpressureError`` / ``ReplicaUnavailableError`` (checked
   in ``ray_tpu/serve/`` too — via ``analyze``'s ``signal_files``
   argument, which applies ONLY the typed-signal checks, not the broad
   catch/swallow rules: serve is full of legitimate best-effort
   cleanup): the overload contract routes every shed to the caller as a
   typed error — a handler that swallows one turns a deliberate 429/503
   into a silent hang or a dropped request. The routing/shedding verbs
   (shed, reject, admit, requeue, set_exception, backpressure) count as
   handling alongside the restart verbs.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ray_tpu.tools.lint.base import Finding, SourceFile, \
    enclosing_function_name

_BROAD = {"Exception", "BaseException"}
_RECONSTRUCT_HINTS = ("reconstruct", "resubmit", "recover")
_RESTART_HINTS = ("restart", "retry", "resubmit", "replay", "resolve",
                  "convert")
_RESIZE_HINTS = _RESTART_HINTS + ("resize", "shrink", "grow", "abort",
                                  "interrupt", "drain")
_QOS_HINTS = _RESTART_HINTS + ("shed", "reject", "admit", "requeue",
                               "set_exception", "backpressure")


def _exc_names(type_node: Optional[ast.AST]) -> List[str]:
    """Exception class names a handler catches."""
    if type_node is None:
        return []
    names: List[str] = []
    for node in ast.walk(type_node):
        if isinstance(node, ast.Name):
            names.append(node.id)
        elif isinstance(node, ast.Attribute):
            names.append(node.attr)
    return names


def _trivial_body(body: List[ast.stmt]) -> bool:
    """True when the handler body observably does nothing."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue  # docstring / ellipsis
        return False
    return True


def _handles_lost_object(handler: ast.ExceptHandler) -> bool:
    """Does the handler body re-raise, raise a conversion, or call into
    reconstruction machinery?"""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = ""
            if isinstance(node.func, ast.Name):
                name = node.func.id
            elif isinstance(node.func, ast.Attribute):
                name = node.func.attr
            if any(h in name.lower() for h in _RECONSTRUCT_HINTS):
                return True
        if isinstance(node, ast.Return) and node.value is not None:
            # returning a value derived from the handler is a conversion
            # decision made by the caller's contract; treat an explicit
            # non-None return as handling
            return True
    return False


def _handles_signal(handler: ast.ExceptHandler, hints) -> bool:
    """Does the handler re-raise, convert (raise / non-None return), or
    route into the recovery machinery named by ``hints``?"""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = ""
            if isinstance(node.func, ast.Name):
                name = node.func.id
            elif isinstance(node.func, ast.Attribute):
                name = node.func.attr
            if any(h in name.lower() for h in hints):
                return True
        if isinstance(node, ast.Return) and node.value is not None:
            return True
    return False


def _handles_actor_death(handler: ast.ExceptHandler) -> bool:
    return _handles_signal(handler, _RESTART_HINTS)


def _signal_findings(sf: SourceFile, node: ast.ExceptHandler,
                     names: List[str], fn: Optional[str]
                     ) -> List[Finding]:
    """The typed-overload-signal checks, shared between the full
    recovery-surface pass and the serve/ signal-only pass."""
    findings: List[Finding] = []
    for sig in ("BackpressureError", "ReplicaUnavailableError"):
        if sig in names and not _handles_signal(node, _QOS_HINTS):
            if fn is None:
                fn = enclosing_function_name(sf.tree, node)
            findings.append(Finding(
                "L4", sf.relpath, node.lineno,
                f"{fn}: catches {sig} without re-raising, converting, "
                f"or routing it to the caller (shed/reject/"
                f"set_exception) — swallowing a typed shed turns a "
                f"deliberate rejection into a silent drop"))
    return findings


def analyze_file(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        fn = None  # resolved lazily; enclosing lookup is O(tree)
        names = _exc_names(node.type)
        if node.type is None:
            fn = enclosing_function_name(sf.tree, node)
            findings.append(Finding(
                "L4", sf.relpath, node.lineno,
                f"{fn}: bare 'except:' — catch a typed exception "
                f"(bare catches eat KeyboardInterrupt/SystemExit)"))
        elif set(names) & _BROAD and _trivial_body(node.body):
            fn = enclosing_function_name(sf.tree, node)
            caught = "/".join(n for n in names if n in _BROAD)
            findings.append(Finding(
                "L4", sf.relpath, node.lineno,
                f"{fn}: 'except {caught}: pass' swallows every error — "
                f"narrow the type, log it, or waive with a "
                f"justification"))
        if "ObjectLostError" in names and not _handles_lost_object(node):
            if fn is None:
                fn = enclosing_function_name(sf.tree, node)
            findings.append(Finding(
                "L4", sf.relpath, node.lineno,
                f"{fn}: catches ObjectLostError without re-raising, "
                f"converting, or reconstructing — this silently "
                f"disables lineage recovery"))
        if "ActorDiedError" in names and not _handles_actor_death(node):
            if fn is None:
                fn = enclosing_function_name(sf.tree, node)
            findings.append(Finding(
                "L4", sf.relpath, node.lineno,
                f"{fn}: catches ActorDiedError without re-raising, "
                f"converting, or routing into restart/retry — dropping "
                f"the death signal loses calls silently"))
        for sig in ("TrainingWorkerError", "CollectiveAbortedError"):
            if sig in names and not _handles_signal(node, _RESIZE_HINTS):
                if fn is None:
                    fn = enclosing_function_name(sf.tree, node)
                findings.append(Finding(
                    "L4", sf.relpath, node.lineno,
                    f"{fn}: catches {sig} without re-raising, converting, "
                    f"or routing into gang resize/restart — swallowing "
                    f"the signal strands the surviving ranks"))
        findings.extend(_signal_findings(sf, node, names, fn))
    return findings


def analyze_signals_file(sf: SourceFile) -> List[Finding]:
    """Signal-only pass for ``ray_tpu/serve/``: flag dropped
    BackpressureError/ReplicaUnavailableError handlers without imposing
    the recovery surface's broad-catch rules on serve's best-effort
    cleanup idiom."""
    findings: List[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        findings.extend(
            _signal_findings(sf, node, _exc_names(node.type), None))
    return findings


def analyze(files: List[SourceFile],
            signal_files: Optional[List[SourceFile]] = None
            ) -> List[Finding]:
    out: List[Finding] = []
    for sf in files:
        out.extend(analyze_file(sf))
    for sf in signal_files or []:
        out.extend(analyze_signals_file(sf))
    return out
