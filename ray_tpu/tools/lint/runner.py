"""Orchestrates the four analyzers over a source tree and applies
suppression comments and baselines.

Scopes (mirroring where each invariant lives):

- L1 runs over ``core/protocol.py`` plus the three dispatcher files;
- L2 runs over ``ray_tpu/core/`` (the event-loop/lock surface);
- L4 runs over ``ray_tpu/core/``, ``ray_tpu/train/``, and
  ``ray_tpu/parallel/`` (the recovery-contract surface — elastic
  training extends the contract to TrainingWorkerError and
  CollectiveAbortedError);
- L3 runs over the whole ``ray_tpu/`` package (flags are read
  everywhere).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

from ray_tpu.tools.lint import l1_protocol, l2_locks, l3_config, \
    l4_exceptions
from ray_tpu.tools.lint.base import Finding, SourceFile, iter_py_files, \
    load_file

PROTOCOL_PATH = "ray_tpu/core/protocol.py"
CONFIG_PATH = "ray_tpu/core/config.py"
FAULT_PATH = "ray_tpu/core/fault_injection.py"

BASELINE_VERSION = 1


def default_root() -> str:
    """The repo root: parent of the installed ray_tpu package."""
    import ray_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(
        ray_tpu.__file__)))


def collect_findings(root: Optional[str] = None,
                     rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the selected analyzers; suppressed findings are dropped."""
    root = root or default_root()
    rules = {r.upper() for r in rules} if rules else {"L1", "L2", "L3",
                                                      "L4"}
    by_rel: Dict[str, SourceFile] = {}

    def get(rel: str) -> Optional[SourceFile]:
        if rel not in by_rel:
            path = os.path.join(root, rel)
            if not os.path.exists(path):
                return None
            sf = load_file(path, root)
            if sf is None:
                return None
            by_rel[rel] = sf
        return by_rel.get(rel)

    core_files: List[SourceFile] = []
    recovery_files: List[SourceFile] = []  # L4 scope
    all_files: List[SourceFile] = []
    for path in iter_py_files(root, "ray_tpu"):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        sf = get(rel)
        if sf is None:
            continue
        all_files.append(sf)
        if rel.startswith("ray_tpu/core/"):
            core_files.append(sf)
        if rel.startswith(("ray_tpu/core/", "ray_tpu/train/",
                           "ray_tpu/parallel/")):
            recovery_files.append(sf)

    findings: List[Finding] = []
    if "L1" in rules:
        protocol_sf = get(PROTOCOL_PATH)
        if protocol_sf is not None:
            dispatchers = {rel: sf for rel in l1_protocol.DISPATCHER_FILES
                           if (sf := get(rel)) is not None}
            findings.extend(l1_protocol.analyze(protocol_sf, dispatchers))
    if "L2" in rules:
        findings.extend(l2_locks.analyze(core_files))
    if "L3" in rules:
        config_sf = get(CONFIG_PATH)
        if config_sf is not None:
            findings.extend(l3_config.analyze(
                config_sf, get(FAULT_PATH), all_files))
    if "L4" in rules:
        findings.extend(l4_exceptions.analyze(recovery_files))

    out = []
    for f in findings:
        sf = by_rel.get(f.path)
        if sf is not None and sf.suppressed(f.line, f.rule):
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return out


def load_baseline(path: str) -> set:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version {data.get('version')!r}")
    return set(data.get("keys", []))


def write_baseline(path: str, findings: List[Finding]) -> None:
    data = {"version": BASELINE_VERSION,
            "keys": sorted({f.key for f in findings})}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1)
        f.write("\n")


def apply_baseline(findings: List[Finding], baseline: set) -> List[Finding]:
    """Keep only findings NOT present in the baseline (new violations)."""
    return [f for f in findings if f.key not in baseline]
