"""Orchestrates the analyzers over a source tree and applies
suppression comments and baselines.

Scopes (mirroring where each invariant lives):

- L1 runs over ``core/protocol.py`` plus the three dispatcher files;
- L2 runs over ``ray_tpu/core/`` and ``ray_tpu/dag/`` (the
  event-loop/lock surface; the DAG driver holds its writer/reader
  locks across channel ops);
- L4 runs over ``ray_tpu/core/``, ``ray_tpu/train/``,
  ``ray_tpu/parallel/``, and ``ray_tpu/job/`` (the recovery-contract
  surface — elastic training extends the contract to
  TrainingWorkerError and CollectiveAbortedError; the job agent's
  supervision loop is recovery machinery too), plus ``ray_tpu/serve/``
  for the typed-overload-signal checks ONLY (dropped BackpressureError /
  ReplicaUnavailableError handlers — serve's best-effort cleanup idiom
  is exempt from the broad-catch rules);
- L3 runs over the whole ``ray_tpu/`` package (flags are read
  everywhere) plus ``tests/`` for the fault-site coverage check;
- L5 runs over ``ray_tpu/core/`` (including ``core/cluster/``),
  ``ray_tpu/train/``, and ``ray_tpu/dag/`` — the multi-threaded lock
  surface (the CompiledDag wlock/rlock pairing is exactly the shape
  L5 guards);
- L6 runs over L5's scope plus ``ray_tpu/serve/`` and ``ray_tpu/dag/``
  (the async request paths the sync-in-async check guards);
- L7 and L8 run over L6's scope plus ``ray_tpu/job/`` — every class
  with a lock-guarded field and every manual acquire/release pair
  lives there (the job agent holds subprocess + fd lifecycles).

Rules run as independent thunks so the CLI can fan them out across a
thread pool (``--jobs``); each thunk's wall time is reported in the
``--json`` output (``rule_wall_ms``) so a rule that goes quadratic on
a growing tree is visible before it hurts.
"""

from __future__ import annotations

import json
import os
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu.tools.lint import l1_protocol, l2_locks, l3_config, \
    l4_exceptions, l5_lock_order, l6_thread_context, l7_guarded_fields, \
    l8_lifecycle, l9_wire_contract, l10_durability
from ray_tpu.tools.lint.base import Finding, RULES, SourceFile, \
    iter_py_files, load_file

PROTOCOL_PATH = "ray_tpu/core/protocol.py"
CONFIG_PATH = "ray_tpu/core/config.py"
FAULT_PATH = "ray_tpu/core/fault_injection.py"
NETEM_PATH = "ray_tpu/core/netem.py"
PROTOCOL_META_PATH = "ray_tpu/core/cluster/protocol_meta.py"
GCS_PATH = "ray_tpu/core/cluster/gcs.py"
HA_PATH = "ray_tpu/core/cluster/ha.py"
NODE_SERVER_PATH = "ray_tpu/core/cluster/node_server.py"

#: dispatcher files whose _op_* arms L9 holds to the contract table
L9_DISPATCHER_FILES = (GCS_PATH, NODE_SERVER_PATH)

ALL_RULES = ("L1", "L2", "L3", "L4", "L5", "L6", "L7", "L8", "L9",
             "L10")

BASELINE_VERSION = 1


class RuleCrash(Exception):
    """A rule raised mid-analysis. Carries the rule id and (when a
    SourceFile was in scope in the crashing frame) the file it was
    chewing on, so the CLI can name both and exit 2 instead of leaking
    a traceback."""

    def __init__(self, rule: str, file: Optional[str],
                 cause: BaseException):
        self.rule = rule
        self.file = file
        self.cause = cause
        where = f" analyzing {file}" if file else ""
        super().__init__(f"rule {rule} crashed{where}: {cause!r}")


def _crash_file(exc: BaseException) -> Optional[str]:
    """Deepest SourceFile local on the crash's traceback — the file the
    rule was analyzing when it died."""
    found: Optional[str] = None
    tb = exc.__traceback__
    while tb is not None:
        for v in tb.tb_frame.f_locals.values():
            if isinstance(v, SourceFile):
                found = v.relpath
        tb = tb.tb_next
    return found


def default_root() -> str:
    """The repo root: parent of the installed ray_tpu package."""
    import ray_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(
        ray_tpu.__file__)))


def _rule_thunks(root: str, rules: set) -> Tuple[
        Dict[str, Callable[[], List[Finding]]], Dict[str, SourceFile],
        float]:
    """Load + parse the tree ONCE (every rule receives the same
    SourceFile objects, hence the same parsed AST), return one zero-arg
    thunk per selected rule, the relpath -> SourceFile map (for
    suppression filtering), and the shared load/parse wall time in ms
    (reported as ``_parse`` next to the per-rule timings — the cost no
    rule pays again)."""
    t_load = time.perf_counter()
    by_rel: Dict[str, SourceFile] = {}

    def get(rel: str) -> Optional[SourceFile]:
        if rel not in by_rel:
            path = os.path.join(root, rel)
            if not os.path.exists(path):
                return None
            sf = load_file(path, root)
            if sf is None:
                return None
            by_rel[rel] = sf
        return by_rel.get(rel)

    core_files: List[SourceFile] = []    # L2 scope
    recovery_files: List[SourceFile] = []   # L4 scope (full rules)
    serve_files: List[SourceFile] = []      # L4 scope (signal-only)
    lock_files: List[SourceFile] = []       # L5 scope
    thread_files: List[SourceFile] = []     # L6 scope
    job_files: List[SourceFile] = []        # extends L4 + L7/L8
    all_files: List[SourceFile] = []
    for path in iter_py_files(root, "ray_tpu"):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        sf = get(rel)
        if sf is None:
            continue
        all_files.append(sf)
        if rel.startswith(("ray_tpu/core/", "ray_tpu/dag/")):
            core_files.append(sf)
        if rel.startswith(("ray_tpu/core/", "ray_tpu/train/",
                           "ray_tpu/parallel/")):
            recovery_files.append(sf)
        if rel.startswith("ray_tpu/serve/"):
            serve_files.append(sf)
        if rel.startswith("ray_tpu/job/"):
            job_files.append(sf)
        if rel.startswith(("ray_tpu/core/", "ray_tpu/train/",
                           "ray_tpu/dag/")):
            lock_files.append(sf)
        if rel.startswith(("ray_tpu/core/", "ray_tpu/train/",
                           "ray_tpu/serve/", "ray_tpu/dag/")):
            thread_files.append(sf)
    # the job agent's supervision loop is recovery machinery (L4) and
    # holds subprocess/fd lifecycles (L8)
    recovery_files = recovery_files + job_files
    # L7/L8 share the widest concurrency scope: everything multi-
    # threaded plus the serve request paths (thread_files covers
    # core/ incl. cluster/, train/, serve/, dag/) plus job/
    guard_files = thread_files + job_files

    test_files: List[SourceFile] = []
    if "L3" in rules:
        for path in iter_py_files(root, "tests"):
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            sf = get(rel)  # through the shared cache: parse once
            if sf is not None:
                test_files.append(sf)

    thunks: Dict[str, Callable[[], List[Finding]]] = {}
    if "L1" in rules:
        protocol_sf = get(PROTOCOL_PATH)
        if protocol_sf is not None:
            dispatchers = {rel: sf for rel in l1_protocol.DISPATCHER_FILES
                           if (sf := get(rel)) is not None}
            thunks["L1"] = lambda: l1_protocol.analyze(protocol_sf,
                                                       dispatchers)
    if "L2" in rules:
        thunks["L2"] = lambda: l2_locks.analyze(core_files)
    if "L3" in rules:
        config_sf = get(CONFIG_PATH)
        fault_sf = get(FAULT_PATH)
        netem_sf = get(NETEM_PATH)
        if config_sf is not None:
            thunks["L3"] = lambda: (
                l3_config.analyze(config_sf, fault_sf, all_files)
                + l3_config.fault_site_coverage(fault_sf, test_files)
                + l3_config.netem_policy_coverage(netem_sf, test_files))
    if "L4" in rules:
        thunks["L4"] = lambda: l4_exceptions.analyze(
            recovery_files, signal_files=serve_files)
    if "L5" in rules:
        thunks["L5"] = lambda: l5_lock_order.analyze(lock_files)
    if "L6" in rules:
        thunks["L6"] = lambda: l6_thread_context.analyze(thread_files)
    if "L7" in rules:
        thunks["L7"] = lambda: l7_guarded_fields.analyze(guard_files)
    if "L8" in rules:
        thunks["L8"] = lambda: l8_lifecycle.analyze(guard_files)
    if "L9" in rules:
        meta_sf = get(PROTOCOL_META_PATH)
        proto_sf = get(PROTOCOL_PATH)
        if meta_sf is not None and proto_sf is not None:
            l9_dispatchers = {rel: sf for rel in L9_DISPATCHER_FILES
                              if (sf := get(rel)) is not None}
            # the wire's client side: the cluster plane + the job agent
            l9_clients = [sf for sf in all_files
                          if sf.relpath.startswith(
                              ("ray_tpu/core/cluster/", "ray_tpu/job/"))]
            thunks["L9"] = lambda: l9_wire_contract.analyze(
                meta_sf, proto_sf, l9_dispatchers, l9_clients)
    if "L10" in rules:
        l10_meta = get(PROTOCOL_META_PATH)
        gcs_sf = get(GCS_PATH)
        ha_sf = get(HA_PATH)
        ns_sf = get(NODE_SERVER_PATH)
        if None not in (l10_meta, gcs_sf, ha_sf, ns_sf):
            thunks["L10"] = lambda: l10_durability.analyze(
                l10_meta, gcs_sf, ha_sf, ns_sf)
    parse_ms = (time.perf_counter() - t_load) * 1000.0
    return thunks, by_rel, parse_ms


def changed_files(root: str, ref: str) -> set:
    """Repo-relative .py paths changed vs ``ref`` (committed diff plus
    the working tree). Raises RuntimeError when git cannot answer."""
    import subprocess

    changed: set = set()
    for extra in ([ref], []):
        proc = subprocess.run(
            ["git", "-C", root, "diff", "--name-only", *extra],
            capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"git diff {' '.join(extra)} failed: "
                f"{proc.stderr.strip() or proc.stdout.strip()}")
        changed |= {ln.strip() for ln in proc.stdout.splitlines()
                    if ln.strip().endswith(".py")}
    return changed


def collect_findings_timed(
        root: Optional[str] = None,
        rules: Optional[Sequence[str]] = None,
        jobs: int = 1,
        changed_only: Optional[set] = None,
        include_suppressed: bool = False
        ) -> Tuple[List[Finding], Dict[str, float]]:
    """Run the selected analyzers (``jobs`` > 1 fans rules out across a
    thread pool); suppressed findings are dropped — or, with
    ``include_suppressed``, kept with ``.suppressed = True`` so output
    modes that annotate waivers (--sarif) can surface them. Returns the
    sorted findings and per-rule wall time in milliseconds (plus the
    shared ``_parse`` entry: the one-time load+parse cost every rule
    reuses). A rule that raises surfaces as :class:`RuleCrash` naming
    the rule and the file under analysis.

    ``changed_only`` (a set of repo-relative paths) filters the
    REPORTED findings to those files; whole-program rules still load
    and analyze the full tree, so cross-file context (lock-order
    graphs, guard inference, call resolution) is never truncated."""
    root = root or default_root()
    selected = {r.upper() for r in rules} if rules else set(ALL_RULES)
    thunks, by_rel, parse_ms = _rule_thunks(root, selected)

    findings: List[Finding] = []
    wall_ms: Dict[str, float] = {"_parse": round(parse_ms, 3)}

    def run(rule: str) -> Tuple[str, List[Finding], float]:
        t0 = time.perf_counter()
        try:
            result = thunks[rule]()
        except RuleCrash:
            raise
        except Exception as e:  # noqa: BLE001 — any analyzer bug lands
            # here; fold it into the typed crash the CLI reports
            raise RuleCrash(rule, _crash_file(e), e) from e
        return rule, result, (time.perf_counter() - t0) * 1000.0

    # findings are re-sorted below and timings keyed by rule, so pool
    # completion order cannot leak into the output: --jobs N is
    # deterministic by construction
    order = [r for r in ALL_RULES if r in thunks]
    if jobs > 1 and len(order) > 1:
        with ThreadPoolExecutor(max_workers=min(jobs, len(order))) as ex:
            results = list(ex.map(run, order))
    else:
        results = [run(r) for r in order]
    for rule, result, ms in results:
        findings.extend(result)
        wall_ms[rule] = round(ms, 3)

    out = []
    for f in findings:
        sf = by_rel.get(f.path)
        if sf is not None and sf.suppressed(f.line, f.rule):
            if not include_suppressed:
                continue
            f.suppressed = True
        if changed_only is not None and f.path not in changed_only:
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return out, wall_ms


def collect_findings(root: Optional[str] = None,
                     rules: Optional[Sequence[str]] = None,
                     jobs: int = 1,
                     changed_only: Optional[set] = None) -> List[Finding]:
    """Run the selected analyzers; suppressed findings are dropped."""
    return collect_findings_timed(root=root, rules=rules, jobs=jobs,
                                  changed_only=changed_only)[0]


def load_baseline(path: str) -> set:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version {data.get('version')!r}")
    return set(data.get("keys", []))


def write_baseline(path: str, findings: List[Finding]) -> None:
    data = {"version": BASELINE_VERSION,
            "keys": sorted({f.key for f in findings})}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1)
        f.write("\n")


def apply_baseline(findings: List[Finding], baseline: set) -> List[Finding]:
    """Keep only findings NOT present in the baseline (new violations)."""
    return [f for f in findings if f.key not in baseline]


def to_sarif(findings: List[Finding]) -> dict:
    """SARIF 2.1.0 log for ``findings`` (include suppressed ones —
    collected with ``include_suppressed=True`` — to have waived sites
    show up annotated rather than vanish: a waived finding carries
    ``suppressions: [{"kind": "inSource"}]``, which SARIF viewers and
    code-scanning UIs render as 'suppressed in source' instead of an
    open result)."""
    rule_ids = sorted({f.rule for f in findings} | set(RULES),
                      key=lambda r: (len(r), r))
    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": f.line},
                },
            }],
            "partialFingerprints": {"rtpuLintKey/v1": f.key},
        }
        if f.suppressed:
            result["suppressions"] = [{"kind": "inSource"}]
        results.append(result)
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "rtpu-lint",
                "rules": [{"id": r,
                           "shortDescription":
                               {"text": RULES.get(r, r)}}
                          for r in rule_ids],
            }},
            "columnKind": "utf16CodeUnits",
            "results": results,
        }],
    }
