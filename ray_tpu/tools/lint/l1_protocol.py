"""L1 — protocol exhaustiveness.

The reference runtime's C++ dispatch switches over RPC message enums are
exhaustive at compile time; our Python dispatchers are ``if tag ==
protocol.X`` chains that silently drop unknown opcodes. This analyzer
recovers the compile-time guarantee:

1. Parse the ``MSG_*``/``REQ_*`` constant table out of
   ``core/protocol.py`` (the same regex ``protocol.schema()`` uses),
   tracking each constant's direction section from the module's
   ``# driver -> worker`` / ``# worker -> driver`` comment headers.
2. Require a dispatch arm (a comparison against ``protocol.<NAME>``) for
   every opcode in the dispatcher that must handle it:

   - driver->worker ``MSG_*``  -> ``core/worker_proc.py``  (run_loop)
   - worker->driver ``MSG_*``  -> ``core/runtime.py``      (recv loop)
   - ``REQ_*`` (data conn)     -> ``core/runtime.py``      (_handle_data_request)

   ``core/cluster/node_server.py`` intercepts a subset and delegates the
   rest to ``Runtime``, so it is not required to be exhaustive.
3. Opcode-drift: inside any function in a dispatcher file, once a
   subject expression (``tag``, ``msg[0]``, ...) has been compared
   against a ``protocol.`` constant, comparing the same subject against
   a string literal that is NOT a declared opcode tag is an error — it
   is either a typo'd opcode or an undeclared protocol extension.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Set, Tuple

from ray_tpu.tools.lint.base import Finding, SourceFile

CONST_RE = re.compile(
    r'^((?:MSG|REQ)_\w+)\s*=\s*"([^"]+)"', re.M)
D2W_RE = re.compile(r"driver\s*-+>\s*worker")
W2D_RE = re.compile(r"worker\s*-+>\s*driver")

#: requirement targets; keys are direction labels produced by
#: parse_protocol_table, values are the dispatcher that must be
#: exhaustive for constants in that direction.
DISPATCH_TARGETS = {
    ("MSG", "d2w"): "ray_tpu/core/worker_proc.py",
    ("MSG", "w2d"): "ray_tpu/core/runtime.py",
    ("REQ", "d2w"): "ray_tpu/core/runtime.py",
    ("REQ", "w2d"): "ray_tpu/core/runtime.py",
}

#: dispatcher files whose string-literal comparisons are held to the
#: declared-opcode rule
DISPATCHER_FILES = (
    "ray_tpu/core/worker_proc.py",
    "ray_tpu/core/runtime.py",
    "ray_tpu/core/cluster/node_server.py",
)


def parse_protocol_table(
        protocol_sf: SourceFile) -> Dict[str, Tuple[str, str, int]]:
    """name -> (tag, direction, line). Direction is "d2w"/"w2d",
    carried forward from the most recent section comment."""
    table: Dict[str, Tuple[str, str, int]] = {}
    direction = ""
    for lineno, line in enumerate(protocol_sf.lines, start=1):
        if line.lstrip().startswith("#"):
            if D2W_RE.search(line):
                direction = "d2w"
            elif W2D_RE.search(line):
                direction = "w2d"
            continue
        m = CONST_RE.match(line)
        if m:
            table[m.group(1)] = (m.group(2), direction, lineno)
    return table


def _protocol_aliases(tree: ast.AST) -> Set[str]:
    """Local names bound to the protocol module in this file."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.endswith("protocol"):
                    aliases.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "protocol":
                    aliases.add(a.asname or "protocol")
    return aliases


def _const_names_in(expr: ast.AST, aliases: Set[str]) -> Iterable[str]:
    """protocol.<NAME> references inside expr (tuples included)."""
    for node in ast.walk(expr):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id in aliases):
            yield node.attr


def handled_constants(sf: SourceFile) -> Set[str]:
    """Constant names this file compares a subject against (Eq or
    membership) — its set of dispatch arms."""
    aliases = _protocol_aliases(sf.tree)
    handled: Set[str] = set()
    if not aliases:
        return handled
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Compare):
            continue
        if not any(isinstance(op, (ast.Eq, ast.In)) for op in node.ops):
            continue
        for side in [node.left] + node.comparators:
            handled.update(_const_names_in(side, aliases))
    return handled


def check_exhaustive(protocol_sf: SourceFile,
                     dispatchers: Dict[str, SourceFile]) -> List[Finding]:
    """Every opcode must have an arm in its required dispatcher.

    ``dispatchers`` maps repo-relative dispatcher path -> SourceFile.
    """
    findings: List[Finding] = []
    table = parse_protocol_table(protocol_sf)
    handled = {path: handled_constants(sf)
               for path, sf in dispatchers.items()}
    for name, (tag, direction, lineno) in sorted(table.items()):
        if not direction:
            findings.append(Finding(
                "L1", protocol_sf.relpath, lineno,
                f"opcode {name} is declared outside any "
                f"'driver -> worker' / 'worker -> driver' section; "
                f"L1 cannot assign it a dispatcher"))
            continue
        target = DISPATCH_TARGETS[(name.split("_")[0], direction)]
        if target not in handled:
            continue  # dispatcher not part of this lint run
        if name not in handled[target]:
            findings.append(Finding(
                "L1", protocol_sf.relpath, lineno,
                f"opcode {name} ({tag!r}) has no dispatch arm in "
                f"{target}"))
    return findings


def check_literal_drift(sf: SourceFile,
                        declared_tags: Set[str]) -> List[Finding]:
    """In functions that dispatch on protocol constants, flag
    comparisons of the same subject against undeclared string
    literals."""
    findings: List[Finding] = []
    aliases = _protocol_aliases(sf.tree)
    if not aliases:
        return findings
    for fn in ast.walk(sf.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        compares: List[ast.Compare] = [
            n for n in ast.walk(fn) if isinstance(n, ast.Compare)
            and any(isinstance(op, (ast.Eq, ast.In)) for op in n.ops)]
        # subjects (by dump key) compared at least once to protocol.X
        subjects: Set[str] = set()
        for node in compares:
            sides = [node.left] + node.comparators
            if any(True for s in sides
                   for _ in _const_names_in(s, aliases)):
                for s in sides:
                    if not list(_const_names_in(s, aliases)) and \
                            not _is_str_literalish(s):
                        subjects.add(ast.dump(s))
        if not subjects:
            continue
        for node in compares:
            sides = [node.left] + node.comparators
            if not any(ast.dump(s) in subjects for s in sides):
                continue
            for s in sides:
                for lit, lineno in _str_literals(s):
                    if lit not in declared_tags:
                        findings.append(Finding(
                            "L1", sf.relpath, lineno,
                            f"{fn.name}: dispatch subject compared "
                            f"against {lit!r}, which is not an opcode "
                            f"declared in core/protocol.py"))
    return findings


def _is_str_literalish(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return True
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return all(_is_str_literalish(e) for e in node.elts)
    return False


def _str_literals(node: ast.AST) -> Iterable[Tuple[str, int]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node.value, node.lineno
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for e in node.elts:
            yield from _str_literals(e)


def analyze(protocol_sf: SourceFile,
            dispatchers: Dict[str, SourceFile]) -> List[Finding]:
    findings = check_exhaustive(protocol_sf, dispatchers)
    declared = {tag for tag, _, _ in
                parse_protocol_table(protocol_sf).values()}
    for sf in dispatchers.values():
        findings.extend(check_literal_drift(sf, declared))
    return findings
