"""rtpu-lint: AST-based invariant checker for ray_tpu.

Four analyzers enforce invariants the reference runtime gets from its
C++ toolchain and that otherwise live only in reviewers' heads:

====  ==============================================================
L1    protocol exhaustiveness — every ``MSG_*``/``REQ_*`` opcode in
      ``core/protocol.py`` has a dispatch arm in the dispatcher that
      must handle it, and dispatchers never compare the message tag
      against undeclared string literals (silent opcode drift)
L2    lock discipline — no indefinitely-blocking call (sleep,
      conn recv/send, subprocess, queue get, future result, untimed
      join) lexically inside a ``with <lock>:`` block in ``core/``
L3    config/env hygiene — ``config.<attr>`` reads resolve to
      declared ``Flag`` rows, no dead flags, and every literal
      ``RTPU_*`` env read maps to a flag env var, a fault-injection
      site, or ``config.WIRING_ENV_VARS``
L4    exception discipline — no bare ``except:`` or do-nothing
      ``except Exception:`` in ``core/``, and no handler drops an
      ``ObjectLostError`` without re-raising/converting/reconstructing
L5    lock order — whole-program acquisition-order graph has no ABBA
      cycles, no function chain re-acquires a non-reentrant lock the
      caller holds (the PR 5 ``_enqueue`` deadlock shape), and no
      foreign callable (stored callback, callable argument, resolver)
      is invoked while any lock is held
L6    thread context — ``signal.signal``/``setitimer`` only from
      main-thread-guaranteed contexts (the PR 7 actor-pool bug), no
      ``os.fork``/subprocess spawn under a held lock, no blocking
      sync calls inside ``async def`` bodies
====  ==============================================================

L3 additionally checks fault-site coverage: every site in
``fault_injection.SITES`` must be armed by at least one test.

Run it::

    python -m ray_tpu.tools.lint              # human-readable, exit 1 on findings
    python -m ray_tpu.tools.lint --json       # machine-readable (+ per-rule wall time)
    python -m ray_tpu.tools.lint --jobs 4     # rules in parallel
    python -m ray_tpu.tools.lint --baseline lint_baseline.json
    python -m ray_tpu.tools.lint --write-baseline lint_baseline.json

Suppress a deliberate violation at its site (justify it in the same
comment)::

    conn.send(msg)  # rtpu-lint: disable=L2 — send lock exists to serialize this send

``tests/test_lint.py`` runs the checker over the tree in tier-1, so a
new violation fails CI unless fixed or explicitly waived.
"""

from ray_tpu.tools.lint.base import Finding, RULES, SourceFile
from ray_tpu.tools.lint.runner import (apply_baseline, collect_findings,
                                       collect_findings_timed,
                                       load_baseline, write_baseline)

__all__ = ["Finding", "RULES", "SourceFile", "collect_findings",
           "collect_findings_timed", "apply_baseline", "load_baseline",
           "write_baseline"]
