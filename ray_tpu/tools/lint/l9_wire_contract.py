"""L9 — wire-contract totality and retry-path conformance.

``WIRE_CONTRACT`` (core/cluster/protocol_meta.py) classifies every wire
op as idempotent / retry_after_apply / dedup_keyed(<key>) /
non_retryable, and the transport retry weave derives its whitelist from
it. This rule makes the table load-bearing in four directions:

(a) **Totality** — every ``_op_*`` dispatch arm in the GCS and node
    server, every ``MSG_*``/``REQ_*`` tag in core/protocol.py, and (for
    ``kv``) every sub-op literal compared inside ``_op_kv`` must have a
    classification; conversely a table entry matching no arm and no tag
    is drift and is flagged.
(b) **Retry paths** — a client-side send (``.call``/``.try_call``)
    whose message resolves to an op NOT classified retry-safe, sitting
    on a retry path (inside a loop with an RPC-error handler, or inside
    an RPC-error handler as a fallback re-send), can run a side effect
    twice. Functions that consult the contract (``maybe_applied`` /
    ``_retry_safe_after_apply`` / ``retry_safe``) are trusted; in an
    unguarded function a retry path re-sending an *unresolvable*
    message is flagged too — the rule cannot prove it safe.
(c) **Dedup claims** — ``dedup_keyed(<key>)`` promises a server-side
    dedup structure: the ``_op_<name>`` handler must take a ``<key>``
    parameter and route through ``self._dedup(<key>, ...)`` in a class
    that maintains ``self._applied``. A claim with no such handler is
    exactly-once theater.
(d) **Swallowed maybe_applied** — sending a non-retry-safe op through
    ``.try_call`` (which flattens every RpcError to None), or through
    ``.call`` inside a ``try`` whose handler absorbs RpcError without
    re-raising or consulting ``maybe_applied``, silently discards the
    "may have been applied once" signal the transport went to the
    trouble of raising.

Approximations (deliberate): messages are resolved only from tuple
literals at the send site or a same-function single assignment; loops
over *peers* with a per-peer error swallow look like retry loops (the
static view cannot distinguish fan-out from re-send) — waive genuine
best-effort fan-outs per site with justification.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.tools.lint.base import Finding, SourceFile
from ray_tpu.tools.lint.l1_protocol import CONST_RE

#: exception names whose handlers count as absorbing transport errors.
#: Deliberately NOT plain OSError/ConnectionError: the transport wraps
#: those into RpcError before they escape, so an ``except OSError``
#: around os.killpg / file IO is not RPC error handling (an OSError
#: caught in a TUPLE with RpcError still matches via the RpcError name).
ERRORISH = ("RpcError", "NetemFault", "GcsUnavailableError",
            "ActorUnavailableError", "Exception", "BaseException")

#: names whose presence in a function marks it contract-aware (guarded)
GUARD_NAMES = ("_retry_safe_after_apply", "retry_safe", "RETRY_SAFE_OPS",
               "maybe_applied")

SEND_ATTRS = ("call", "try_call")


# --------------------------------------------------------- contract load

class Contract:
    def __init__(self) -> None:
        self.ops: Dict[str, str] = {}
        self.kv_subops: Dict[str, str] = {}
        self.line: Dict[str, int] = {}

    def classify(self, op: str, subop: Optional[str]) -> Optional[str]:
        c = self.ops.get(op)
        if c == "per_subop":
            if subop is None:
                return None  # unresolvable sub-op: caller decides
            return self.kv_subops.get(subop)
        return c

    def retry_safe(self, c: Optional[str]) -> bool:
        return c in ("idempotent", "retry_after_apply") or (
            c is not None and c.startswith("dedup_keyed:"))


def load_contract(meta_sf: SourceFile) -> Contract:
    """Evaluate WIRE_CONTRACT / KV_SUBOP_CONTRACT from the module AST
    (constant names resolved through module-level ``X = "str"``
    assigns; ``dedup_keyed("k")`` calls folded to ``dedup_keyed:k``)."""
    consts: Dict[str, str] = {}
    dicts: Dict[str, ast.Dict] = {}
    for node in meta_sf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.value is not None:
            name, value = node.target.id, node.value
        else:
            continue
        if isinstance(value, ast.Constant) and isinstance(
                value.value, str):
            consts[name] = value.value
        elif isinstance(value, ast.Dict):
            dicts[name] = value

    def fold(v: ast.AST) -> Optional[str]:
        if isinstance(v, ast.Name):
            return consts.get(v.id)
        if (isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
                and v.func.id == "dedup_keyed" and v.args
                and isinstance(v.args[0], ast.Constant)):
            return "dedup_keyed:" + str(v.args[0].value)
        return None

    ct = Contract()
    for table, out in (("WIRE_CONTRACT", ct.ops),
                       ("KV_SUBOP_CONTRACT", ct.kv_subops)):
        d = dicts.get(table)
        if d is None:
            continue
        for k, v in zip(d.keys, d.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                c = fold(v)
                if c is not None:
                    out[k.value] = c
                    ct.line[k.value] = k.lineno
    return ct


# ------------------------------------------------------------ (a) totality

def _op_defs(sf: SourceFile) -> Dict[str, int]:
    """op wire-string -> first def line, from ``_op_<name>`` defs."""
    out: Dict[str, int] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name.startswith("_op_"):
            out.setdefault(node.name[4:], node.lineno)
    return out


def _protocol_tags(protocol_sf: SourceFile) -> Dict[str, Tuple[str, int]]:
    """tag -> (constant name, line)."""
    out: Dict[str, Tuple[str, int]] = {}
    for lineno, line in enumerate(protocol_sf.lines, start=1):
        m = CONST_RE.match(line)
        if m:
            out.setdefault(m.group(2), (m.group(1), lineno))
    return out


def _kv_subop_literals(gcs_sf: SourceFile) -> Dict[str, int]:
    """String literals compared (Eq/In) inside gcs ``_op_kv``."""
    out: Dict[str, int] = {}
    for node in ast.walk(gcs_sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "_op_kv":
            for cmp_ in ast.walk(node):
                if not isinstance(cmp_, ast.Compare):
                    continue
                if not any(isinstance(o, (ast.Eq, ast.In))
                           for o in cmp_.ops):
                    continue
                for side in [cmp_.left] + cmp_.comparators:
                    for sub in ast.walk(side):
                        if isinstance(sub, ast.Constant) and isinstance(
                                sub.value, str):
                            out.setdefault(sub.value, sub.lineno)
    return out


def check_totality(ct: Contract, meta_sf: SourceFile,
                   protocol_sf: SourceFile,
                   dispatchers: Dict[str, SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    seen_ops: Set[str] = set()
    for path, sf in sorted(dispatchers.items()):
        for op, lineno in sorted(_op_defs(sf).items()):
            seen_ops.add(op)
            if op not in ct.ops:
                findings.append(Finding(
                    "L9", path, lineno,
                    f"dispatch arm _op_{op} has no WIRE_CONTRACT entry "
                    f"for {op!r} — classify it in protocol_meta.py"))
    tags = _protocol_tags(protocol_sf)
    for tag, (name, lineno) in sorted(tags.items()):
        seen_ops.add(tag)
        if tag not in ct.ops:
            findings.append(Finding(
                "L9", protocol_sf.relpath, lineno,
                f"protocol tag {name} ({tag!r}) has no WIRE_CONTRACT "
                f"entry — classify it in protocol_meta.py"))
    for op in sorted(ct.ops):
        if op not in seen_ops:
            findings.append(Finding(
                "L9", meta_sf.relpath, ct.line.get(op, 1),
                f"WIRE_CONTRACT entry {op!r} matches no _op_ dispatch "
                f"arm and no protocol tag — stale entry"))
    gcs_sf = next((sf for p, sf in dispatchers.items()
                   if p.endswith("gcs.py")), None)
    if gcs_sf is not None:
        lits = _kv_subop_literals(gcs_sf)
        for sub, lineno in sorted(lits.items()):
            if sub not in ct.kv_subops:
                findings.append(Finding(
                    "L9", gcs_sf.relpath, lineno,
                    f"kv sub-op {sub!r} dispatched in _op_kv has no "
                    f"KV_SUBOP_CONTRACT entry"))
        for sub in sorted(ct.kv_subops):
            if lits and sub not in lits:
                findings.append(Finding(
                    "L9", meta_sf.relpath, ct.line.get(sub, 1),
                    f"KV_SUBOP_CONTRACT entry {sub!r} matches no "
                    f"comparison in _op_kv — stale entry"))
    return findings


# ---------------------------------------------------- (c) dedup structure

def check_dedup_claims(ct: Contract, meta_sf: SourceFile,
                       dispatchers: Dict[str, SourceFile]
                       ) -> List[Finding]:
    findings: List[Finding] = []
    claims = sorted((op, c.split(":", 1)[1])
                    for op, c in ct.ops.items()
                    if c.startswith("dedup_keyed:"))
    for op, key in claims:
        ok = False
        witness: Optional[Tuple[str, int, str]] = None
        for path, sf in sorted(dispatchers.items()):
            for node in ast.walk(sf.tree):
                if not (isinstance(node, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                        and node.name == f"_op_{op}"):
                    continue
                args = [a.arg for a in node.args.args] + \
                    [a.arg for a in node.args.kwonlyargs]
                has_key = key in args
                routes = any(
                    isinstance(c, ast.Call)
                    and isinstance(c.func, ast.Attribute)
                    and c.func.attr == "_dedup"
                    and c.args and isinstance(c.args[0], ast.Name)
                    and c.args[0].id == key
                    for c in ast.walk(node))
                table = any(
                    isinstance(n, ast.Attribute) and n.attr == "_applied"
                    for n in ast.walk(sf.tree))
                if has_key and routes and table:
                    ok = True
                elif witness is None:
                    why = ("missing a %r parameter" % key if not has_key
                           else "never calls self._dedup(%s, ...)" % key
                           if not routes else
                           "file maintains no self._applied dedup table")
                    witness = (path, node.lineno, why)
        if ok:
            continue
        if witness is not None:
            path, lineno, why = witness
            findings.append(Finding(
                "L9", path, lineno,
                f"op {op!r} is classified dedup_keyed({key!r}) but "
                f"_op_{op} {why} — the exactly-once claim is "
                f"unenforced"))
        else:
            findings.append(Finding(
                "L9", meta_sf.relpath, ct.line.get(op, 1),
                f"op {op!r} is classified dedup_keyed({key!r}) but no "
                f"dispatcher defines _op_{op} — nothing implements the "
                f"dedup"))
    return findings


# ------------------------------------------- (b)+(d) client-side sends

class _Send:
    __slots__ = ("node", "attr", "op", "subop", "line")

    def __init__(self, node: ast.Call, attr: str, op: Optional[str],
                 subop: Optional[str], line: int):
        self.node = node
        self.attr = attr
        self.op = op
        self.subop = subop
        self.line = line


def _own_walk(fn: ast.AST):
    """ast.walk over a function body that does NOT descend into nested
    function/lambda bodies (those are analyzed as their own scope)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _tuple_op(expr: ast.AST) -> Tuple[Optional[str], Optional[str]]:
    """(op, subop) from a tuple/list literal message, else (None, None)."""
    if isinstance(expr, (ast.Tuple, ast.List)) and expr.elts:
        first = expr.elts[0]
        if isinstance(first, ast.Constant) and isinstance(first.value,
                                                          str):
            sub = None
            if len(expr.elts) > 1:
                second = expr.elts[1]
                if isinstance(second, ast.Constant) and isinstance(
                        second.value, str):
                    sub = second.value
            return first.value, sub
    return None, None


def _resolve_msg(fn: ast.AST, arg: ast.AST
                 ) -> Tuple[Optional[str], Optional[str]]:
    op, sub = _tuple_op(arg)
    if op is not None:
        return op, sub
    if isinstance(arg, ast.Name):
        resolved: Set[Tuple[Optional[str], Optional[str]]] = set()
        for node in _own_walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == arg.id:
                        resolved.add(_tuple_op(node.value))
        if len(resolved) == 1:
            return resolved.pop()
    return None, None


def _sends_in(fn: ast.AST, scope: ast.AST) -> List[_Send]:
    out = []
    for node in _own_walk(scope):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in SEND_ATTRS and node.args):
            op, sub = _resolve_msg(fn, node.args[0])
            out.append(_Send(node, node.func.attr, op, sub, node.lineno))
    return out


def _handler_errorish(h: ast.ExceptHandler) -> bool:
    if h.type is None:
        return True  # bare except absorbs everything
    names = [n.id for n in ast.walk(h.type) if isinstance(n, ast.Name)]
    names += [n.attr for n in ast.walk(h.type)
              if isinstance(n, ast.Attribute)]
    return any(any(e in name for e in ERRORISH) for name in names)


def _handler_swallows(h: ast.ExceptHandler) -> bool:
    """True unless the handler's sole job is to re-raise."""
    return not (len(h.body) == 1 and isinstance(h.body[0], ast.Raise))


def _handler_reraises_or_consults(h: ast.ExceptHandler) -> bool:
    for node in ast.walk(h):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Attribute) and node.attr == \
                "maybe_applied":
            return True
    return False


def _guarded(fn: ast.AST) -> bool:
    for node in _own_walk(fn):
        if isinstance(node, ast.Name) and node.id in GUARD_NAMES:
            return True
        if isinstance(node, ast.Attribute) and node.attr in GUARD_NAMES:
            return True
        if isinstance(node, ast.Constant) and node.value in GUARD_NAMES:
            return True  # getattr(e, "maybe_applied", False)
    return False


def check_client_sends(ct: Contract,
                       clients: List[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for sf in clients:
        for fn in ast.walk(sf.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            findings.extend(_check_fn(ct, sf, fn))
    return findings


def _check_fn(ct: Contract, sf: SourceFile,
              fn: ast.AST) -> List[Finding]:
    out: List[Finding] = []
    guarded = _guarded(fn)
    flagged: Set[Tuple[int, str]] = set()

    def flag(send: _Send, msg: str) -> None:
        key = (send.line, send.op or "?")
        if key not in flagged:
            flagged.add(key)
            out.append(Finding("L9", sf.relpath, send.line,
                               f"{fn.name}: {msg}"))

    def unsafe(send: _Send) -> Tuple[bool, str]:
        """(definitely-not-retry-safe, classification label)."""
        if send.op is None:
            return False, "?"
        c = ct.classify(send.op, send.subop)
        if send.op in ct.ops and c is None:
            # per_subop with unresolvable sub-op: conservatively unsafe
            return True, "per_subop(unresolved sub-op)"
        if c is None:
            return False, "?"  # unclassified op: totality check owns it
        return not ct.retry_safe(c), c

    # (b) retry loops: a loop body holding both a send and an
    # error-absorbing handler re-sends on failure
    for loop in _own_walk(fn):
        if not isinstance(loop, (ast.While, ast.For, ast.AsyncFor)):
            continue
        handlers = [h for t in _own_walk(loop)
                    if isinstance(t, ast.Try) for h in t.handlers
                    if _handler_errorish(h) and _handler_swallows(h)]
        if not handlers:
            continue
        for send in _sends_in(fn, loop):
            bad, c = unsafe(send)
            if bad:
                flag(send, f"retry path re-sends {send.op!r} "
                           f"(classified {c}) — a lost reply means the "
                           f"side effect can run twice; WIRE_CONTRACT "
                           f"does not mark it retry-safe")
            elif send.op is None and not guarded:
                flag(send, "retry path re-sends an unresolvable message "
                           "in a function that never consults the wire "
                           "contract (maybe_applied / "
                           "_retry_safe_after_apply)")
    # (b) fallback re-send from inside an error handler
    for t in _own_walk(fn):
        if not isinstance(t, ast.Try):
            continue
        for h in t.handlers:
            if not _handler_errorish(h):
                continue
            for send in _sends_in(fn, h):
                bad, c = unsafe(send)
                if bad:
                    flag(send, f"error-handler fallback re-sends "
                               f"{send.op!r} (classified {c}) after a "
                               f"possible apply — not retry-safe per "
                               f"WIRE_CONTRACT")
    # (d) swallowed maybe_applied
    for send in _sends_in(fn, fn):
        bad, c = unsafe(send)
        if not bad:
            continue
        if send.attr == "try_call":
            flag(send, f"try_call of {send.op!r} (classified {c}) "
                       f"flattens RpcError.maybe_applied to None — the "
                       f"caller cannot tell a lost reply from a "
                       f"never-sent request")
    for t in _own_walk(fn):
        if not isinstance(t, ast.Try):
            continue
        swallowing = [h for h in t.handlers
                      if _handler_errorish(h)
                      and not _handler_reraises_or_consults(h)]
        if not swallowing:
            continue
        for stmt in t.body:
            for send in _sends_in(fn, stmt):
                bad, c = unsafe(send)
                if bad and send.attr == "call":
                    flag(send, f"RpcError from {send.op!r} (classified "
                               f"{c}) is swallowed without consulting "
                               f"maybe_applied — a possibly-applied "
                               f"mutation is silently dropped")
    return out


def analyze(meta_sf: SourceFile, protocol_sf: SourceFile,
            dispatchers: Dict[str, SourceFile],
            clients: List[SourceFile]) -> List[Finding]:
    ct = load_contract(meta_sf)
    findings = check_totality(ct, meta_sf, protocol_sf, dispatchers)
    findings.extend(check_dedup_claims(ct, meta_sf, dispatchers))
    findings.extend(check_client_sends(ct, clients))
    return findings
