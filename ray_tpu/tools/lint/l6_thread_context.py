"""L6 — thread-context discipline: APIs that only work (or only work
safely) on particular threads.

Three checks, each encoding a bug this repo has already hit or a
CPython footgun one refactor away:

``signal-off-main``
    ``signal.signal`` / ``signal.setitimer`` / ``signal.alarm`` raise
    ``ValueError`` when called off the main thread. PR 7's actor-pool
    bug was exactly this: a handler installed from a pool thread, with
    the raise silently swallowed — preemption ride-through never
    armed. The call is allowed at module top level, inside a function
    whose name marks it as a process entrypoint (``main``, ``*_main``),
    or under an explicit lexical guard::

        if threading.current_thread() is threading.main_thread():
            signal.signal(...)

    Wrapping the call in ``try/except ValueError`` does NOT satisfy
    the rule — that idiom is how the PR 7 bug hid. A site that is
    genuinely main-thread-by-construction gets a per-site waiver with
    a justification.

``fork-under-lock``
    ``os.fork`` (and fork-based spawn helpers) while this thread holds
    a lock: the child inherits every *other* lock in whatever state it
    was at fork time, and any thread holding one of them does not
    exist in the child — first acquire there deadlocks forever. Held
    sets come from the same interprocedural walk as L5.

``sync-in-async``
    Blocking synchronous calls (``time.sleep``, sync socket ops,
    ``subprocess.run``-family, ``.result()``/``.join()``) inside an
    ``async def`` body stall the entire event loop — every request on
    the serve/dag path, not just this one. Use the async equivalent or
    push the work to a thread.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Tuple

from ray_tpu.tools.lint.base import Finding, SourceFile
from ray_tpu.tools.lint.l5_lock_order import _collect_module, \
    _terminal_attr

#: functions whose name marks them as process entrypoints (run on the
#: main thread by construction)
MAIN_FN_RE = re.compile(r"^main$|_main$")

SIGNAL_CALLS = {"signal", "setitimer", "alarm", "siginterrupt"}

FORK_CALLS = {"fork", "forkpty"}
SPAWN_CALLS = {"Popen", "run", "call", "check_call", "check_output",
               "system", "popen", "spawnv", "spawnvp", "posix_spawn"}

#: (module-ish receiver, attr) pairs that block inside async bodies
_ASYNC_BLOCKING_ATTRS = {"sleep": ("time",),
                         "run": ("subprocess",),
                         "call": ("subprocess",),
                         "check_call": ("subprocess",),
                         "check_output": ("subprocess",)}
_SOCK_OPS = {"recv", "recv_into", "recvfrom", "send", "sendall",
             "accept", "connect"}


def analyze(files: List[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        findings.extend(_signal_findings(sf))
        findings.extend(_fork_findings(sf))
        findings.extend(_async_findings(sf))
    return findings


# ---------------------------------------------------------- signal checks


def _signal_module_aliases(tree: ast.AST) -> set:
    """Names the signal module is imported as (``import signal as
    _signal`` must not evade the check)."""
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "signal":
                    aliases.add(a.asname or "signal")
    return aliases or {"signal"}


def _signal_findings(sf: SourceFile) -> List[Finding]:
    out: List[Finding] = []
    aliases = _signal_module_aliases(sf.tree)
    for call, ctx in _calls_with_context(sf.tree):
        func = call.func
        if not isinstance(func, ast.Attribute) \
                or func.attr not in SIGNAL_CALLS:
            continue
        if _terminal_attr(func.value) not in aliases:
            continue  # only the signal module's API
        fn_name, guarded = ctx
        if fn_name is None:
            continue  # module top level: import runs on the main thread
        if MAIN_FN_RE.search(fn_name):
            continue
        if guarded:
            continue
        out.append(Finding(
            "L6", sf.relpath, call.lineno,
            f"signal.{func.attr} in {fn_name}(): raises ValueError off "
            f"the main thread (the PR 7 actor-pool bug); guard with "
            f"'threading.current_thread() is threading.main_thread()', "
            f"move to a main/*_main entrypoint, or waive with a "
            f"justification — do NOT swallow the ValueError"))
    return out


def _calls_with_context(tree: ast.AST):
    """Yield ``(call, (enclosing_fn_name_or_None, main_thread_guarded))``
    for every call in the module."""

    def visit(node, fn_name: Optional[str], guarded: bool):
        for child in ast.iter_child_nodes(node):
            c_fn, c_guard = fn_name, guarded
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                c_fn, c_guard = child.name, False
            elif isinstance(child, ast.If) and _is_main_thread_guard(
                    child.test):
                # only the if-body is guarded, not orelse
                if isinstance(child.test, ast.AST):
                    for sub in child.body:
                        yield from visit_one(sub, c_fn, True)
                    for sub in child.orelse:
                        yield from visit_one(sub, c_fn, c_guard)
                    yield from _expr_calls(child.test, c_fn, c_guard)
                    continue
            if isinstance(child, ast.Call):
                yield (child, (c_fn, c_guard))
            yield from visit(child, c_fn, c_guard)

    def visit_one(node, fn_name, guarded):
        if isinstance(node, ast.Call):
            yield (node, (fn_name, guarded))
        yield from visit(node, fn_name, guarded)

    def _expr_calls(expr, fn_name, guarded):
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                yield (sub, (fn_name, guarded))

    yield from visit(tree, None, False)


def _is_main_thread_guard(test: ast.AST) -> bool:
    """``threading.current_thread() is threading.main_thread()`` (either
    operand order, == also accepted)."""
    if not isinstance(test, ast.Compare) or len(test.comparators) != 1:
        return False
    sides = (test.left, test.comparators[0])
    names = set()
    for side in sides:
        if isinstance(side, ast.Call):
            attr = _terminal_attr(side.func)
            if attr:
                names.add(attr)
    return {"current_thread", "main_thread"} <= names


# ------------------------------------------------------- fork under lock


def _fork_findings(sf: SourceFile) -> List[Finding]:
    out: List[Finding] = []
    m = _collect_module(sf)
    for fi in m.fns.values():
        for ev in fi.events:
            if not ev.held:
                continue
            func = ev.call.func
            attr = _terminal_attr(func)
            if attr in FORK_CALLS or (
                    attr in SPAWN_CALLS
                    and isinstance(func, ast.Attribute)
                    and _terminal_attr(func.value) in ("subprocess",
                                                       "os")):
                held = ", ".join(repr(h) for h in ev.held)
                out.append(Finding(
                    "L6", sf.relpath, ev.line,
                    f"{fi.key}: {attr}() while holding {held} — the "
                    f"child inherits every lock's state but not the "
                    f"threads that would release them; first "
                    f"contended acquire in the child deadlocks. Spawn "
                    f"outside the critical section"))
    return out


# --------------------------------------------------------- sync in async


def _async_findings(sf: SourceFile) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.AsyncFunctionDef):
            continue
        for call in _async_body_calls(node):
            reason = _blocking_reason(call)
            if reason is not None:
                out.append(Finding(
                    "L6", sf.relpath, call.lineno,
                    f"blocking {reason} inside async def "
                    f"{node.name}(): stalls the event loop for every "
                    f"in-flight request; use the async equivalent or "
                    f"run_in_executor"))
    return out


def _async_body_calls(fn: ast.AsyncFunctionDef):
    """Calls lexically inside the async body, excluding nested (sync or
    async) function definitions — those run on their own schedule."""

    def scan(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Call):
                yield child
            yield from scan(child)

    yield from scan(fn)


def _blocking_reason(call: ast.Call) -> Optional[str]:
    func = call.func
    if not isinstance(func, ast.Attribute):
        return None
    recv = _terminal_attr(func.value)
    mods = _ASYNC_BLOCKING_ATTRS.get(func.attr)
    if mods and recv in mods:
        return f"{recv}.{func.attr}()"
    if func.attr in _SOCK_OPS and recv and "sock" in recv.lower():
        return f"sync socket op {recv}.{func.attr}()"
    if func.attr == "result" and recv and (
            "future" in recv.lower() or "fut" in recv.lower()):
        return f"{recv}.result()"
    return None
