"""L5 — whole-program lock order and callback-under-lock discipline.

L2 is lexical: it sees a blocking call *textually* inside a ``with
<lock>:`` block and nothing else. The two concurrency bug classes this
repo has already shipped hand-fixes for are invisible to it:

- PR 5: ``_enqueue`` fired a dep-ready callback while holding the
  non-reentrant runtime lock; the callback re-entered ``_queue_ready``
  which re-acquired the same lock — a guaranteed self-deadlock, two
  calls deep, in another function.
- ABBA inversions: thread 1 takes A then B, thread 2 takes B then A.
  Each site is locally innocent; only the *global* acquisition-order
  graph shows the cycle.

This analyzer builds a per-module call graph over the runtime's
concurrency surface and propagates held-lock sets interprocedurally
(bounded depth ``DEPTH``), recognizing both ``with <lock>:`` blocks and
paired ``.acquire()``/``.release()`` statements. Three finding shapes:

``reacquire``
    A function (or a callee up to ``DEPTH`` calls away) acquires a
    non-reentrant lock the caller already holds — self-deadlock. The
    message names the call chain.
``lock-order``
    An acquisition edge A -> B whose reverse order B -> ... -> A also
    exists in the global graph (merged across every module in scope) —
    two threads interleaving the paths deadlock.
``callback-under-lock``
    A *foreign callable* — a stored callback attribute, a callable
    argument, a name iterated from a callbacks/hooks/waiters
    collection, or a resolver — invoked while any lock is held. The
    analyzer cannot see inside a foreign callable, and the PR 5
    deadlock was exactly a callback that turned out to need the held
    lock: swap out under the lock, fire after release, or waive with
    justification.

Approximations (deliberate, documented in the README):

- Lock identity is the qualified attribute: ``self.X`` in class ``C``
  of module ``m`` is ``m.C.X``; module globals ``m.X``; function
  locals share a token per outermost function (so closures that
  capture an outer lock match); attributes of non-self receivers
  collapse to ``m.*.X`` — wildcard tokens never produce reacquire
  findings (two distinct instances may legitimately nest), only order
  edges.
- Calls resolve by name within the module only: ``self.m()`` to a
  method of the enclosing class, bare names to nested defs then module
  functions. Cross-module calls are not followed; the order graph is
  still merged globally so cross-module ABBA cycles surface.
- ``threading.Condition(self._lock)`` aliases the condition attribute
  to the underlying lock token; ``RLock``/``make_rlock``/
  ``make_condition``/bare ``Condition()`` construction marks a token
  reentrant.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ray_tpu.tools.lint.base import Finding, SourceFile

#: interprocedural propagation depth (caller + DEPTH transitive callees)
DEPTH = 4

LOCK_RE = re.compile(r"lock|cond|cv|mutex", re.IGNORECASE)

#: attribute / variable names that denote stored callables
CB_RE = re.compile(
    r"(^|_)(cb|cbs|callback|callbacks|hook|hooks|resolver|resolvers|"
    r"waiter|waiters|listener|listeners|on_[a-z0-9_]+)$")

#: method names that are never foreign callables, even under a lock
_SAFE_CALLS = {
    "append", "pop", "popleft", "appendleft", "add", "discard",
    "remove", "clear", "get", "items", "keys", "values", "update",
    "setdefault", "extend", "copy", "insert", "index", "count",
    "split", "rsplit", "join", "strip", "encode", "decode", "format",
    "startswith", "endswith", "hex", "binary", "is_set", "set",
    "wait", "wait_for", "notify", "notify_all", "acquire", "release",
    "locked",
}

_BODY_FIELDS = ("body", "orelse", "finalbody")


# ------------------------------------------------------------- lock tokens


def _terminal_attr(expr: object) -> Optional[str]:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


class _Scope:
    """Names a function's world: module stem, enclosing class, qualname,
    parameter names, and the module-global name set."""

    def __init__(self, mod: str, cls: Optional[str], fqn: str, root: str,
                 params: Set[str], module_globals: Set[str]):
        self.mod = mod
        self.cls = cls
        self.fqn = fqn          # e.g. Runtime._enqueue.on_ready
        self.root = root        # outermost function: Runtime._enqueue
        self.params = params
        self.module_globals = module_globals

    def lock_token(self, expr: ast.AST) -> Optional[str]:
        """Global-graph identity of a lock expression, or None when the
        expression does not look like a lock."""
        attr = _terminal_attr(expr)
        if attr is None or not LOCK_RE.search(attr):
            return None
        if isinstance(expr, ast.Attribute):
            recv = expr.value
            if isinstance(recv, ast.Name) and recv.id in ("self", "cls"):
                owner = self.cls or self.root
                return f"{self.mod}.{owner}.{attr}"
            return f"{self.mod}.*.{attr}"
        if attr in self.module_globals:
            return f"{self.mod}.{attr}"
        # function-local: one namespace per outermost function, so a
        # closure capturing the outer function's lock gets the same token
        return f"{self.mod}.{self.root}.<{attr}>"


def _is_wildcard(token: str) -> bool:
    return ".*." in token


# ------------------------------------------------------------ function IR


class _Event:
    """One call made while ``held`` locks were held."""

    __slots__ = ("held", "call", "line")

    def __init__(self, held: Tuple[str, ...], call: ast.Call):
        self.held = held
        self.call = call
        self.line = call.lineno


class _Acquire:
    __slots__ = ("held", "token", "line")

    def __init__(self, held: Tuple[str, ...], token: str, line: int):
        self.held = held
        self.token = token
        self.line = line


class _FnInfo:
    def __init__(self, key: str, node: ast.AST, scope: _Scope,
                 sf: SourceFile):
        self.key = key
        self.node = node
        self.scope = scope
        self.sf = sf
        self.events: List[_Event] = []
        self.acquires: List[_Acquire] = []
        self.nested: Dict[str, str] = {}  # bare name -> fn key


class _Module:
    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.mod = os.path.splitext(os.path.basename(sf.relpath))[0]
        self.fns: Dict[str, _FnInfo] = {}
        self.methods: Dict[str, Dict[str, str]] = {}  # cls -> name -> key
        self.module_fns: Dict[str, str] = {}          # name -> key
        self.globals: Set[str] = set()
        self.reentrant: Set[str] = set()   # reentrant lock tokens
        self.alias: Dict[str, str] = {}    # condition token -> lock token


def _fn_params(node) -> Set[str]:
    a = node.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


def _collect_module(sf: SourceFile) -> _Module:
    m = _Module(sf)
    for node in sf.tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    m.globals.add(tgt.id)
        elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name):
            m.globals.add(node.target.id)
        elif isinstance(node, ast.ClassDef):
            meths = m.methods.setdefault(node.name, {})
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    meths[item.name] = f"{node.name}.{item.name}"

    _scan_lock_ctors(m)

    for node in sf.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            m.module_fns[node.name] = _walk_fn(m, node, None, "",
                                               node.name, None)
        elif isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    _walk_fn(m, item, node.name, f"{node.name}.",
                             f"{node.name}.{item.name}", None)
    return m


def _scan_lock_ctors(m: _Module) -> None:
    """Reentrancy + Condition aliasing from assignment shapes: walk the
    whole tree once, tracking the enclosing class lexically."""

    def visit(node: ast.AST, cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            ccls = child.name if isinstance(child, ast.ClassDef) else cls
            if isinstance(child, ast.Assign) and len(child.targets) == 1 \
                    and isinstance(child.value, ast.Call):
                _note_ctor(m, child, ccls)
            visit(child, ccls)

    visit(m.sf.tree, None)


def _note_ctor(m: _Module, node: ast.Assign, cls: Optional[str]) -> None:
    scope = _Scope(m.mod, cls, "<module>", "<module>", set(), m.globals)
    token = scope.lock_token(node.targets[0])
    if token is None:
        return
    ctor = _terminal_attr(node.value.func) or ""
    if ctor in ("RLock", "make_rlock", "make_condition"):
        m.reentrant.add(token)
    elif ctor == "Condition":
        if not node.value.args:
            m.reentrant.add(token)  # bare Condition() wraps an RLock
            return
        arg = node.value.args[0]
        src = scope.lock_token(arg)
        if src is not None:
            # with self._cond: acquires the underlying self._lock
            m.alias[token] = src
        elif isinstance(arg, ast.Call) and _terminal_attr(arg.func) in (
                "RLock", "make_rlock"):
            m.reentrant.add(token)


def _walk_fn(m: _Module, node, cls: Optional[str], prefix: str,
             root: str, parent: Optional[_FnInfo]) -> str:
    key = f"{prefix}{node.name}"
    scope = _Scope(m.mod, cls, key, root, _fn_params(node), m.globals)
    fi = _FnInfo(key, node, scope, m.sf)
    m.fns[key] = fi
    if parent is not None:
        parent.nested[node.name] = key
    _walk_body(node.body, (), fi, m)
    for child in _direct_nested_defs(node):
        _walk_fn(m, child, cls, key + ".", root, fi)
    return key


def _direct_nested_defs(fn_node) -> Iterable[ast.AST]:
    """Function defs directly inside ``fn_node`` (not inside a deeper
    def/class)."""

    def scan(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child
            elif not isinstance(child, ast.ClassDef):
                yield from scan(child)

    yield from scan(fn_node)


def _walk_body(stmts: List[ast.stmt], held: Tuple[str, ...],
               fi: _FnInfo, m: _Module) -> None:
    """Record calls and acquisitions with the held-lock set in effect.
    A ``X.acquire()`` statement holds until a matching ``X.release()``
    later in the same statement list (or the end of the list)."""
    held = tuple(held)
    for stmt in stmts:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue  # nested def bodies are walked as their own fns
        tok = _acq_rel_token(stmt, fi.scope, "acquire")
        if tok is not None:
            tok = m.alias.get(tok, tok)
            fi.acquires.append(_Acquire(held, tok, stmt.lineno))
            if tok not in held:
                held = held + (tok,)
            continue
        tok = _acq_rel_token(stmt, fi.scope, "release")
        if tok is not None and tok in held:
            held = tuple(t for t in held if t != tok)
            continue
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = held
            for item in stmt.items:
                _scan_expr_calls(item.context_expr, held, fi)
                tok = fi.scope.lock_token(item.context_expr)
                if tok is not None:
                    tok = m.alias.get(tok, tok)
                    fi.acquires.append(_Acquire(inner, tok, stmt.lineno))
                    if tok not in inner:
                        inner = inner + (tok,)
            _walk_body(stmt.body, inner, fi, m)
            continue
        # the statement's own expressions (test / iter / targets / value)
        for field, value in ast.iter_fields(stmt):
            if field in _BODY_FIELDS or field == "handlers":
                continue
            if isinstance(value, ast.AST):
                _scan_expr_calls(value, held, fi)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.AST):
                        _scan_expr_calls(v, held, fi)
        # control flow: child statement lists inherit the held set
        for field in _BODY_FIELDS:
            body = getattr(stmt, field, None)
            if body:
                _walk_body(body, held, fi, m)
        for handler in getattr(stmt, "handlers", ()):
            _walk_body(handler.body, held, fi, m)


def _scan_expr_calls(expr: ast.AST, held: Tuple[str, ...],
                     fi: _FnInfo) -> None:
    stack: List[ast.AST] = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Lambda):
            continue  # runs later, not at this program point
        if isinstance(node, ast.Call):
            fi.events.append(_Event(held, node))
        stack.extend(ast.iter_child_nodes(node))


def _acq_rel_token(stmt: ast.stmt, scope: _Scope,
                   which: str) -> Optional[str]:
    if not isinstance(stmt, ast.Expr) or not isinstance(stmt.value,
                                                        ast.Call):
        return None
    func = stmt.value.func
    if not isinstance(func, ast.Attribute) or func.attr != which:
        return None
    return scope.lock_token(func.value)


# -------------------------------------------------------------- resolution


def _resolve(call: ast.Call, fi: _FnInfo, m: _Module) -> Optional[str]:
    func = call.func
    if isinstance(func, ast.Name):
        if func.id in fi.nested:
            return fi.nested[func.id]
        # nested defs of an enclosing function (closure siblings)
        for key, other in m.fns.items():
            if func.id in other.nested and (
                    fi.key == key or fi.key.startswith(key + ".")):
                return other.nested[func.id]
        target = m.module_fns.get(func.id)
        if target in m.fns:
            return target
        return None
    if isinstance(func, ast.Attribute):
        recv = func.value
        if isinstance(recv, ast.Name):
            if recv.id in ("self", "cls") and fi.scope.cls:
                return m.methods.get(fi.scope.cls, {}).get(func.attr)
            if recv.id in m.methods:
                return m.methods[recv.id].get(func.attr)
    return None


def _foreign_reason(call: ast.Call, fi: _FnInfo) -> Optional[str]:
    """Why this call dispatches a foreign callable, or None."""
    func = call.func
    if isinstance(func, ast.Name):
        name = func.id
        if name == "self":
            return None
        if name in fi.scope.params:
            return f"callable argument {name!r}"
        binding = _name_binding(fi.node, name)
        if binding is not None:
            return binding
        if CB_RE.search(name):
            return f"callback-named local {name!r}"
        return None
    if isinstance(func, ast.Attribute):
        if func.attr in _SAFE_CALLS:
            return None
        if CB_RE.search(func.attr):
            recv = _terminal_attr(func.value) or "?"
            return f"stored callback attribute {recv}.{func.attr}"
    return None


def _name_binding(fn_node, name: str) -> Optional[str]:
    """A foreign-callable description when ``name`` is bound from a
    callbacks-shaped source inside this function: a loop target over a
    callbacks collection, or an assignment from a callback attribute."""
    for node in ast.walk(fn_node):
        if isinstance(node, ast.For) and \
                isinstance(node.target, ast.Name) and \
                node.target.id == name:
            src = _terminal_attr(node.iter)
            if src and CB_RE.search(src):
                return f"callback iterated from {src!r}"
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == name:
            src = _terminal_attr(node.value)
            if src and CB_RE.search(src):
                return f"callable loaded from {src!r}"
    return None


# --------------------------------------------------------------- analysis


class _Edge:
    __slots__ = ("src", "dst", "path", "line", "fn", "via")

    def __init__(self, src, dst, path, line, fn, via=""):
        self.src = src
        self.dst = dst
        self.path = path
        self.line = line
        self.fn = fn
        self.via = via


def _summaries(m: _Module) -> Dict[str, Dict[str, str]]:
    """fn key -> {lock token -> call chain} of locks the function may
    acquire within DEPTH calls. Bounded fixpoint: depth-k summaries are
    built from depth-(k-1) callee summaries, so call-graph cycles
    terminate by construction."""
    base: Dict[str, Dict[str, str]] = {}
    callees: Dict[str, Set[str]] = {}
    for key, fi in m.fns.items():
        base[key] = {a.token: "" for a in fi.acquires}
        callees[key] = set()
        for ev in fi.events:
            c = _resolve(ev.call, fi, m)
            if c is not None:
                callees[key].add(c)
    summ = {k: dict(v) for k, v in base.items()}
    for _ in range(DEPTH):
        nxt = {k: dict(v) for k, v in summ.items()}
        changed = False
        for key in summ:
            for c in callees[key]:
                for tok, chain in summ.get(c, {}).items():
                    if tok not in nxt[key]:
                        nxt[key][tok] = f"{c} -> {chain}" if chain else c
                        changed = True
        summ = nxt
        if not changed:
            break
    return summ


def analyze(files: List[SourceFile]) -> List[Finding]:
    modules = [_collect_module(sf) for sf in files]
    findings: List[Finding] = []
    edges: List[_Edge] = []
    reentrant: Set[str] = set()
    for m in modules:
        reentrant |= m.reentrant

    for m in modules:
        summ = _summaries(m)
        for fi in m.fns.values():
            # direct acquisitions: order edges + lexical reacquire
            for acq in fi.acquires:
                for h in acq.held:
                    if h != acq.token:
                        edges.append(_Edge(h, acq.token, fi.sf.relpath,
                                           acq.line, fi.key))
                if acq.token in acq.held \
                        and acq.token not in reentrant \
                        and not _is_wildcard(acq.token):
                    findings.append(Finding(
                        "L5", fi.sf.relpath, acq.line,
                        f"{fi.key}: reacquires non-reentrant lock "
                        f"{acq.token!r} already held by this thread — "
                        f"guaranteed self-deadlock"))
            # calls made while holding locks
            for ev in fi.events:
                if not ev.held:
                    continue
                callee = _resolve(ev.call, fi, m)
                if callee is not None:
                    for tok, chain in summ.get(callee, {}).items():
                        label = f"{callee} -> {chain}" if chain else callee
                        if tok in ev.held and tok not in reentrant \
                                and not _is_wildcard(tok):
                            findings.append(Finding(
                                "L5", fi.sf.relpath, ev.line,
                                f"{fi.key}: call into {label} "
                                f"(re)acquires {tok!r} while this "
                                f"thread already holds it — "
                                f"self-deadlock (PR 5 shape)"))
                        else:
                            for h in ev.held:
                                if h != tok:
                                    edges.append(_Edge(
                                        h, tok, fi.sf.relpath, ev.line,
                                        fi.key, via=label))
                    continue
                reason = _foreign_reason(ev.call, fi)
                if reason is not None:
                    findings.append(Finding(
                        "L5", fi.sf.relpath, ev.line,
                        f"{fi.key}: {reason} invoked while holding "
                        f"{_fmt_held(ev.held)} — a callback that needs "
                        f"the lock deadlocks the holder; swap out under "
                        f"the lock, fire after release"))

    findings.extend(_order_findings(edges))
    return findings


def _fmt_held(held: Tuple[str, ...]) -> str:
    return ", ".join(repr(h) for h in held)


def _order_findings(edges: List[_Edge]) -> List[Finding]:
    graph: Dict[str, Dict[str, _Edge]] = {}
    for e in edges:
        if e.src != e.dst:
            graph.setdefault(e.src, {}).setdefault(e.dst, e)

    def back_path(src: str, dst: str) -> Optional[List[str]]:
        seen = {src}
        stack = [[src]]
        while stack:
            p = stack.pop()
            for nxt in graph.get(p[-1], ()):
                if nxt == dst:
                    return p + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(p + [nxt])
        return None

    out: List[Finding] = []
    reported: Set[Tuple[str, str]] = set()
    for src, dsts in graph.items():
        for dst, e in dsts.items():
            pair = tuple(sorted((src, dst)))
            if pair in reported:
                continue
            back = back_path(dst, src)
            if back is None:
                continue
            reported.add(pair)
            other = graph[dst][back[1]]
            via = f" (via {e.via})" if e.via else ""
            out.append(Finding(
                "L5", e.path, e.line,
                f"{e.fn}: lock-order inversion — acquires {dst!r} "
                f"while holding {src!r}{via}, but the reverse order "
                f"{' -> '.join(back)} is established at {other.path}:"
                f"{other.line} ({other.fn}); two threads interleaving "
                f"these paths deadlock"))
    return out
