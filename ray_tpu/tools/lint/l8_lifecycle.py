"""L8 — resource lifecycle: acquire/release pairs must survive
exception edges and early returns.

The runtime's resources are manual pairs: an shm allocation is live
from ``create_object`` until ``seal`` (and pinned until ``release``),
a channel endpoint from ``create``/``open_endpoint`` until
``close``/``release``, an admission depth slot from ``_admit`` until
``_DepthToken.release``, a socket from ``socket()`` until ``close``.
Python's GC hides a leak behind a ``__del__`` backstop — until a
reference cycle, an exception traceback, or interpreter shutdown
keeps the object alive and the slot/fd/depth unit is gone.

Three finding shapes, each citing the acquire site and the unreleased
path:

``exception-path``
    A statement that can raise sits between the acquire and its
    release (or the release-carrying ``try``), so that edge leaks.
``early-exit``
    A ``return``/``raise`` between acquire and release.
``generator-handoff``
    The handle is passed into a generator function defined in the
    same module: its ``finally``-release runs only if iteration
    starts, so an abandoned generator leaks until GC.
``del-backstop``
    A class stores an acquired handle on ``self`` and the only method
    releasing it is ``__del__``.

Deliberate outs (kept, with rationale, so the rule stays
low-noise): a handle that ESCAPES — returned, yielded, stored into a
container/attribute, passed to a non-generator call — transfers
ownership the analyzer cannot track, and is skipped (attribute stores
are still covered by the class-level ``del-backstop`` pass); a
``with``-managed acquire is clean by construction.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ray_tpu.tools.lint.base import Finding, SourceFile

#: handle-style acquires: the RESULT is the resource.
#: call name (bare or attribute) -> (release method names, kind)
HANDLE_ACQ: Dict[str, Tuple[FrozenSet[str], str]] = {
    "socket": (frozenset({"close", "detach"}), "socket"),
    "open_endpoint": (frozenset({"close", "release"}),
                      "channel endpoint"),
    "_admit": (frozenset({"release"}), "admission depth token"),
    "_DepthToken": (frozenset({"release"}), "admission depth token"),
}

#: channel constructors: ``<X>Channel.create(...)``
_CHANNEL_RELEASES = frozenset({"close", "release"})

#: key-style acquires: the resource is named by the FIRST ARGUMENT
#: (receiver + key identify it; the result is just a view).
KEY_ACQ: Dict[str, Tuple[FrozenSet[str], str]] = {
    "create_object": (frozenset({"seal", "abort", "delete", "release"}),
                      "shm allocation"),
    "create_object_with_pressure": (
        frozenset({"seal", "abort", "delete", "release"}),
        "shm allocation"),
}


def _call_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _handle_acquire(call: ast.Call) -> Optional[Tuple[FrozenSet[str],
                                                      str]]:
    name = _call_name(call)
    if name in HANDLE_ACQ:
        return HANDLE_ACQ[name]
    if name == "create" and isinstance(call.func, ast.Attribute):
        recv = call.func.value
        if isinstance(recv, ast.Name) and recv.id.endswith("Channel"):
            return _CHANNEL_RELEASES, f"{recv.id} slot"
    return None


def _key_acquire(call: ast.Call) -> Optional[Tuple[FrozenSet[str], str]]:
    name = _call_name(call)
    return KEY_ACQ.get(name)


# ------------------------------------------------------------ functions


def _functions(tree: ast.AST):
    """Every function/method (incl. nested), with its enclosing class
    name (or None) and dotted display name."""

    def visit(node, cls, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from visit(child, child.name, f"{child.name}.")
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                yield child, cls, f"{prefix}{child.name}"
                yield from visit(child, cls, f"{prefix}{child.name}.")
            else:
                yield from visit(child, cls, prefix)

    yield from visit(tree, None, "")


def _is_generator(fn_node) -> bool:
    """Yield in the function's OWN body (nested defs excluded)."""
    for node in ast.walk(fn_node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn_node:
            continue
        if isinstance(node, (ast.Yield, ast.YieldFrom)) and \
                _owner_fn(fn_node, node):
            return True
    return False


def _owner_fn(fn_node, target) -> bool:
    """True when ``target`` belongs to ``fn_node``'s own frame."""

    def visit(node):
        for child in ast.iter_child_nodes(node):
            if child is target:
                return True
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if visit(child):
                return True
        return False

    return visit(fn_node)


# ------------------------------------------------------------ releases


def _releases_var(node: ast.AST, var: str,
                  releases: FrozenSet[str]) -> bool:
    """Any ``var.<release>()`` call (or ``with var:``/``closing(var)``)
    inside ``node``."""
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            recv = n.func.value
            if isinstance(recv, ast.Name) and recv.id == var \
                    and n.func.attr in releases:
                return True
        if isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                ce = item.context_expr
                if isinstance(ce, ast.Name) and ce.id == var:
                    return True
                if isinstance(ce, ast.Call) and \
                        _call_name(ce) == "closing" and ce.args and \
                        isinstance(ce.args[0], ast.Name) and \
                        ce.args[0].id == var:
                    return True
    return False


def _releases_key(node: ast.AST, recv_src: str, key_src: str,
                  releases: FrozenSet[str]) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in releases and n.args:
            try:
                if ast.unparse(n.func.value) == recv_src and \
                        ast.unparse(n.args[0]) == key_src:
                    return True
            except Exception:  # noqa: BLE001 — unparse best-effort
                pass
    return False


def _can_raise(stmt: ast.stmt, releasing) -> Optional[int]:
    """Line of the first thing in ``stmt`` that can raise (a call that
    is not itself the release, an explicit raise, an assert), or
    None."""
    for n in ast.walk(stmt):
        if isinstance(n, (ast.Raise, ast.Assert)):
            return n.lineno
        if isinstance(n, ast.Call) and not releasing(n):
            return n.lineno
    return None


# ------------------------------------------------------------- analysis


class _Ctx:
    """Where one acquire statement sits: its block + index, and the
    chain of enclosing Try statements inside the function."""

    __slots__ = ("block", "index", "trys")

    def __init__(self, block, index, trys):
        self.block = block
        self.index = index
        self.trys = trys


def _locate(fn_node, target_stmt) -> Optional[_Ctx]:
    def visit(block, trys):
        for i, s in enumerate(block):
            if s is target_stmt:
                return _Ctx(block, i, trys)
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for field in ("body", "orelse", "finalbody"):
                child = getattr(s, field, None)
                if child:
                    sub = trys + [s] if (isinstance(s, ast.Try)
                                         and field == "body") else trys
                    found = visit(child, sub)
                    if found:
                        return found
            for h in getattr(s, "handlers", ()):
                found = visit(h.body, trys)
                if found:
                    return found
        return None

    return visit(fn_node.body, [])


def _scan_forward(ctx: _Ctx, releases_in, can_raise_in):
    """Walk the acquire's block forward. Returns one of:
    ("ok",), ("exc", risky_line, release_line),
    ("early", exit_line), ("end", first_risky_line_or_None)."""
    risky: Optional[int] = None
    for j in range(ctx.index + 1, len(ctx.block)):
        s = ctx.block[j]
        if isinstance(s, ast.Try):
            protected = (any(releases_in(t) for t in s.finalbody)
                         or any(releases_in(t) for h in s.handlers
                                for t in h.body))
            if protected:
                return ("ok",) if risky is None else \
                    ("exc", risky, s.lineno)
        if releases_in(s):
            if risky is not None and not isinstance(s, ast.Try):
                return ("exc", risky, s.lineno)
            return ("ok",)
        if isinstance(s, (ast.Return, ast.Raise)):
            return ("early", s.lineno)
        line = can_raise_in(s)
        if line is not None and risky is None:
            risky = line
    return ("end", risky)


def _enclosing_protected(ctx: _Ctx, releases_in) -> bool:
    for t in ctx.trys:
        if any(releases_in(s) for s in t.finalbody):
            return True
        if any(releases_in(s) for h in t.handlers for s in h.body):
            return True
    return False


def _escapes(fn_node, acquire_stmt, var: str, releases: FrozenSet[str],
             module_generators: Dict[str, ast.AST]
             ) -> Tuple[bool, Optional[Tuple[str, int]]]:
    """(escaped, generator_handoff) for ``var`` anywhere in the
    function. A pass into a same-module *generator function* is NOT a
    safe escape — it is reported separately."""
    gen_handoff: Optional[Tuple[str, int]] = None
    escaped = False
    for n in ast.walk(fn_node):
        if n is acquire_stmt:
            continue
        if getattr(n, "lineno", acquire_stmt.lineno) < \
                acquire_stmt.lineno:
            continue  # before this acquire: a different lifetime
        if isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)):
            v = n.value
            if v is not None and _uses(v, var) and \
                    not _is_gen_call(v, module_generators):
                escaped = True
        elif isinstance(n, ast.Call):
            if isinstance(n.func, ast.Attribute) and \
                    isinstance(n.func.value, ast.Name) and \
                    n.func.value.id == var:
                continue  # var.method(...): receiver use, not escape
            args_use = any(_uses(a, var) for a in n.args) or \
                any(_uses(kw.value, var) for kw in n.keywords)
            if args_use:
                gname = _gen_target(n, module_generators)
                if gname is not None:
                    gen_handoff = (gname, n.lineno)
                else:
                    escaped = True
        elif isinstance(n, ast.Assign):
            if _uses(n.value, var) and \
                    not _is_gen_call(n.value, module_generators):
                escaped = True
    return escaped, gen_handoff


def _is_gen_call(node: ast.AST,
                 module_generators: Dict[str, ast.AST]) -> bool:
    """Returning/storing ``self._gen(var)`` is the generator HANDOFF
    itself, not an independent escape into an owner — without this the
    escape-outranks-handoff rule would hide the direct-return case."""
    return isinstance(node, ast.Call) and \
        _gen_target(node, module_generators) is not None


def _uses(node: ast.AST, var: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == var
               for n in ast.walk(node))


def _gen_target(call: ast.Call,
                module_generators: Dict[str, ast.AST]) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name) and f.id in module_generators:
        return f.id
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id in ("self", "cls") \
            and f.attr in module_generators:
        return f.attr
    return None


def analyze(files: List[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        findings.extend(_file_findings(sf))
    return findings


def _file_findings(sf: SourceFile) -> List[Finding]:
    out: List[Finding] = []
    #: function/method NAME -> node, for generator-handoff resolution
    module_generators: Dict[str, ast.AST] = {
        fn.name: fn for fn, _, _ in _functions(sf.tree)
        if _is_generator(fn)}

    #: class -> attr -> (line, kind, releases) for the del-backstop pass
    attr_acq: Dict[str, Dict[str, Tuple[int, str, FrozenSet[str]]]] = {}
    #: class -> attr -> set of method names that release it
    attr_rel: Dict[str, Dict[str, Set[str]]] = {}

    for fn, cls, disp in _functions(sf.tree):
        out.extend(_fn_findings(sf, fn, disp, module_generators))
        if cls is None:
            continue
        meth = fn.name
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Attribute) \
                    and isinstance(node.targets[0].value, ast.Name) \
                    and node.targets[0].value.id == "self" \
                    and isinstance(node.value, ast.Call):
                pair = _handle_acquire(node.value)
                if pair is not None:
                    attr_acq.setdefault(cls, {})[node.targets[0].attr] = \
                        (node.lineno, pair[1], pair[0])
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Attribute) and \
                    isinstance(node.func.value.value, ast.Name) and \
                    node.func.value.value.id == "self":
                attr_rel.setdefault(cls, {}).setdefault(
                    node.func.value.attr, set()).add(meth)

    for cls, attrs in attr_acq.items():
        for attr, (line, kind, releases) in attrs.items():
            rel_methods = {m for m in attr_rel.get(cls, {}).get(attr, ())}
            if rel_methods and rel_methods <= {"__del__"}:
                out.append(Finding(
                    "L8", sf.relpath, line,
                    f"{cls}: self.{attr} ({kind}) acquired at "
                    f"{sf.relpath}:{line} is released only in __del__ — "
                    f"exception paths and interpreter shutdown leak it; "
                    f"release deterministically (close()/context "
                    f"manager) and keep __del__ as backstop"))
    return out


def _fn_findings(sf: SourceFile, fn, disp: str,
                 module_generators: Dict[str, ast.AST]) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            continue  # nested defs analyzed as their own functions
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and _owner_fn(fn, node):
            pair = _handle_acquire(node.value)
            if pair is not None:
                out.extend(_check_handle(sf, fn, disp, node,
                                         node.targets[0].id, pair,
                                         module_generators))
        if isinstance(node, ast.Expr) and isinstance(node.value,
                                                     ast.Call) \
                and _owner_fn(fn, node):
            pair = _key_acquire(node.value)
            if pair is not None:
                out.extend(_check_key(sf, fn, disp, node, node.value,
                                      pair))
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call) \
                and _owner_fn(fn, node):
            pair = _key_acquire(node.value)
            if pair is not None:
                out.extend(_check_key(sf, fn, disp, node, node.value,
                                      pair))
    return out


def _with_managed(fn, var: str) -> bool:
    for n in ast.walk(fn):
        if isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                ce = item.context_expr
                if isinstance(ce, ast.Name) and ce.id == var:
                    return True
    return False


def _check_handle(sf, fn, disp, stmt, var, pair,
                  module_generators) -> List[Finding]:
    releases, kind = pair
    line = stmt.lineno
    call = _call_name(stmt.value) or "?"

    def releasing(n: ast.Call) -> bool:
        return (isinstance(n.func, ast.Attribute)
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id == var and n.func.attr in releases)

    escaped, gen_handoff = _escapes(fn, stmt, var, releases,
                                    module_generators)
    # an escape into a NON-generator sink (e.g. a wrapper object that
    # owns the release) outranks a generator handoff: the handle's
    # lifetime no longer depends solely on generator finalization
    if escaped or _with_managed(fn, var):
        return []
    if gen_handoff is not None:
        gname, gline = gen_handoff
        return [Finding(
            "L8", sf.relpath, line,
            f"{disp}: {kind} {var!r} acquired at {sf.relpath}:{line} "
            f"({call}) is handed to generator function {gname!r} at "
            f"line {gline}; its finally-release runs only if iteration "
            f"starts — an abandoned generator leaks the {kind} until "
            f"GC runs __del__")]

    ctx = _locate(fn, stmt)
    if ctx is None:
        return []

    def releases_in(s: ast.AST) -> bool:
        return _releases_var(s, var, releases)

    def can_raise_in(s: ast.stmt) -> Optional[int]:
        return _can_raise(s, releasing)

    if _enclosing_protected(ctx, releases_in):
        return []
    verdict = _scan_forward(ctx, releases_in, can_raise_in)
    return _verdict_finding(sf, disp, line, call, kind, var, verdict)


def _check_key(sf, fn, disp, stmt, call_node, pair) -> List[Finding]:
    releases, kind = pair
    if not call_node.args or not isinstance(call_node.func,
                                            ast.Attribute):
        return []
    try:
        recv_src = ast.unparse(call_node.func.value)
        key_src = ast.unparse(call_node.args[0])
    except Exception:  # noqa: BLE001 — unparse best-effort
        return []
    if not isinstance(call_node.args[0], (ast.Name, ast.Attribute)):
        return []
    line = stmt.lineno
    call = _call_name(call_node) or "?"

    ctx = _locate(fn, stmt)
    if ctx is None:
        return []

    def releases_in(s: ast.AST) -> bool:
        return _releases_key(s, recv_src, key_src, releases)

    def releasing(n: ast.Call) -> bool:
        return (isinstance(n.func, ast.Attribute)
                and n.func.attr in releases)

    def can_raise_in(s: ast.stmt) -> Optional[int]:
        return _can_raise(s, releasing)

    if _enclosing_protected(ctx, releases_in):
        return []
    verdict = _scan_forward(ctx, releases_in, can_raise_in)
    return _verdict_finding(sf, disp, line, call, kind, key_src, verdict)


def _verdict_finding(sf, disp, line, call, kind, what,
                     verdict) -> List[Finding]:
    shape = verdict[0]
    if shape == "ok":
        return []
    site = f"{kind} {what!r} acquired at {sf.relpath}:{line} ({call})"
    if shape == "exc":
        _, risky, rel = verdict
        return [Finding(
            "L8", sf.relpath, line,
            f"{disp}: {site} leaks if line {risky} raises before the "
            f"release at line {rel} — move the release into a "
            f"try/finally or context manager")]
    if shape == "early":
        return [Finding(
            "L8", sf.relpath, line,
            f"{disp}: {site} leaks on the early exit at line "
            f"{verdict[1]} before any release")]
    # "end": fell off the block without a release in sight
    risky = verdict[1]
    path = (f"the fall-through path (first raising statement: line "
            f"{risky})" if risky is not None else "the fall-through "
            "path")
    return [Finding(
        "L8", sf.relpath, line,
        f"{disp}: {site} has no reachable release on {path}")]
