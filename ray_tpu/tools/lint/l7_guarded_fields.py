"""L7 — inferred lock protection for instance fields (GUARDED_BY).

The reference runtime's C++ core gets this from Clang's thread-safety
annotations: a field marked ``GUARDED_BY(mu_)`` makes any access
without ``mu_`` held a compile error. This pass recovers the
capability for the Python reproduction by *inference*: for each class
it tallies every ``self._x`` access together with the lock set held at
that program point (reusing L5's held-lock propagation — ``with
<lock>:`` blocks, paired ``.acquire()``/``.release()`` statements,
``Condition(lock)`` aliasing — plus an interprocedural entry-held
fixpoint for private helpers only ever called under a lock). When a
majority of a field's accesses hold the same lock, that lock is the
field's inferred guard and every access without it is flagged, citing
the guard and a witness guarded site.

Explicit intent beats inference: a class-body annotation

    _guarded_by_ = {"_depth": "_lock",     # every access needs _lock
                    "_stats": None}        # declared single-thread

overrides the tally for the listed fields — ``None`` documents
single-thread ownership and silences the rule for that field, a lock
attribute name makes the rule *total* (every non-``__init__`` access
without that lock is flagged, majority or not).

Approximations (deliberate):

- ``__init__`` bodies are skipped — pre-publication, no other thread
  can see the object — but nested defs inside ``__init__`` (watcher
  thread bodies, callbacks) are walked with an EMPTY entry lock set:
  they run later, when construction locks are long released.
- Nested defs anywhere are treated as callbacks: lexical ``with``
  blocks inside them count, the enclosing method's held set does not.
- A private method's entry-held set is the intersection of
  ``held-at-call-site ∪ entry(caller)`` over every intra-module call
  site (optimistic fixpoint). Public methods, dunders, and methods
  referenced as values (thread targets, stored callbacks) start at
  the empty set — external callers hold nothing.
- Fields whose name looks like a lock (L5's ``LOCK_RE``) are exempt:
  locks guard fields, nothing guards a lock.
- Inheritance is not modelled: each class tallies its own accesses.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ray_tpu.tools.lint.base import Finding, SourceFile
from ray_tpu.tools.lint.l5_lock_order import (
    LOCK_RE, _acq_rel_token, _collect_module, _is_wildcard, _Module,
    _resolve, _Scope)

#: inference needs this many guarded accesses ...
MIN_GUARDED = 2
#: ... and strictly more guarded than unguarded ones (majority rule)

#: entry-held fixpoint iterations (call graphs here are shallow)
FIXPOINT_ITERS = 8

_TOP = None  # lattice top for the optimistic entry-held fixpoint


class _Access:
    __slots__ = ("cls", "field", "fn_key", "line", "write", "nested",
                 "held")

    def __init__(self, cls: str, field: str, fn_key: str, line: int,
                 write: bool, nested: bool, held: Tuple[str, ...]):
        self.cls = cls
        self.field = field
        self.fn_key = fn_key
        self.line = line
        self.write = write
        self.nested = nested
        self.held = held


class _ClassInfo:
    def __init__(self, sf: SourceFile, m: _Module, name: str):
        self.sf = sf
        self.m = m
        self.name = name
        self.accesses: List[_Access] = []
        #: field -> lock attr name | None, from _guarded_by_
        self.declared: Dict[str, Optional[str]] = {}
        self.declared_line: int = 0


def _parse_guarded_by(cls_node: ast.ClassDef, ci: _ClassInfo) -> None:
    for item in cls_node.body:
        if not (isinstance(item, ast.Assign) and len(item.targets) == 1
                and isinstance(item.targets[0], ast.Name)
                and item.targets[0].id == "_guarded_by_"
                and isinstance(item.value, ast.Dict)):
            continue
        ci.declared_line = item.lineno
        for k, v in zip(item.value.keys, item.value.values):
            if not (isinstance(k, ast.Constant) and isinstance(k.value,
                                                               str)):
                continue
            if isinstance(v, ast.Constant) and v.value is None:
                ci.declared[k.value] = None
            elif isinstance(v, ast.Constant) and isinstance(v.value, str):
                ci.declared[k.value] = v.value


class _Walker:
    """Held-set walker over ONE method body that records self-attribute
    accesses (L5's ``_walk_body`` records calls; same propagation)."""

    def __init__(self, ci: _ClassInfo, scope: _Scope,
                 value_refs: Set[str]):
        self.ci = ci
        self.scope = scope
        self.value_refs = value_refs
        self.methods = ci.m.methods.get(ci.name, {})

    def walk(self, stmts: List[ast.stmt], held: Tuple[str, ...],
             fn_key: str, nested: bool, record: bool) -> None:
        held = tuple(held)
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # callback body: runs later, enclosing locks released
                self.walk(stmt.body, (), f"{fn_key}.{stmt.name}",
                          True, True)
                continue
            tok = _acq_rel_token(stmt, self.scope, "acquire")
            if tok is not None:
                tok = self.ci.m.alias.get(tok, tok)
                if tok not in held:
                    held = held + (tok,)
                continue
            tok = _acq_rel_token(stmt, self.scope, "release")
            if tok is not None:
                tok = self.ci.m.alias.get(tok, tok)
                if tok in held:
                    held = tuple(t for t in held if t != tok)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = held
                for item in stmt.items:
                    self._scan(item.context_expr, held, fn_key, nested,
                               record)
                    tok = self.scope.lock_token(item.context_expr)
                    if tok is not None:
                        tok = self.ci.m.alias.get(tok, tok)
                        if tok not in inner:
                            inner = inner + (tok,)
                self.walk(stmt.body, inner, fn_key, nested, record)
                continue
            for field, value in ast.iter_fields(stmt):
                if field in ("body", "orelse", "finalbody", "handlers"):
                    continue
                vals = value if isinstance(value, list) else [value]
                for v in vals:
                    if isinstance(v, ast.AST):
                        self._scan(v, held, fn_key, nested, record)
            for field in ("body", "orelse", "finalbody"):
                body = getattr(stmt, field, None)
                if body:
                    self.walk(body, held, fn_key, nested, record)
            for handler in getattr(stmt, "handlers", ()):
                self.walk(handler.body, held, fn_key, nested, record)

    def _scan(self, expr: ast.AST, held: Tuple[str, ...], fn_key: str,
              nested: bool, record: bool) -> None:
        stack: List[ast.AST] = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.Lambda, ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                continue  # runs later, not at this program point
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute):
                    # self._meth(...) is a call, not a field access —
                    # but self._cb() on a non-method reads a stored field
                    self._record(f, held, fn_key, nested, record,
                                 is_call=True)
                    stack.append(f.value)
                else:
                    stack.append(f)
                stack.extend(node.args)
                stack.extend(kw.value for kw in node.keywords)
                continue
            if isinstance(node, ast.Attribute):
                self._record(node, held, fn_key, nested, record,
                             is_call=False)
                stack.append(node.value)
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _record(self, node: ast.Attribute, held: Tuple[str, ...],
                fn_key: str, nested: bool, record: bool,
                is_call: bool) -> None:
        if not (isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return
        name = node.attr
        if name in self.methods:
            if not is_call:
                # method used as a value: thread target / callback —
                # external callers invoke it holding nothing
                self.value_refs.add(self.methods[name])
            return
        if not record:
            return
        if not name.startswith("_") or name.startswith("__"):
            return
        if name == "_guarded_by_" or LOCK_RE.search(name):
            return
        write = isinstance(node.ctx, (ast.Store, ast.Del))
        self.ci.accesses.append(_Access(
            self.ci.name, name, fn_key, node.lineno, write, nested, held))


def _entry_held(m: _Module,
                value_refs: Set[str]) -> Dict[str, FrozenSet[str]]:
    """Lock set every caller of a method is known to hold at entry.
    Optimistic intersection fixpoint over intra-module call sites."""
    sites: Dict[str, List[Tuple[str, Tuple[str, ...]]]] = {}
    for key, fi in m.fns.items():
        for ev in fi.events:
            callee = _resolve(ev.call, fi, m)
            if callee is not None:
                sites.setdefault(callee, []).append((key, ev.held))

    def external(key: str) -> bool:
        name = key.rsplit(".", 1)[-1]
        head = key.split(".", 1)[0]
        top_method = key.count(".") == 1 and head in m.methods
        return (not top_method                 # module fn / nested def
                or not name.startswith("_")    # public: called bare
                or (name.startswith("__") and name.endswith("__"))
                or key in value_refs           # thread target / callback
                or key not in sites)           # callers unknown

    entry: Dict[str, object] = {
        key: (frozenset() if external(key) else _TOP) for key in m.fns}
    internal = [k for k in m.fns if entry[k] is _TOP]

    for _ in range(FIXPOINT_ITERS):
        changed = False
        for key in internal:
            acc: object = _TOP
            for caller, held in sites[key]:
                ce = entry.get(caller, frozenset())
                if ce is _TOP:
                    continue  # unresolved caller contributes top
                contrib = frozenset(held) | ce
                acc = contrib if acc is _TOP else (acc & contrib)
            if acc is not _TOP and acc != entry[key]:
                entry[key] = acc
                changed = True
        if not changed:
            break
    return {k: (v if v is not _TOP else frozenset())
            for k, v in entry.items()}


def _collect_class(sf: SourceFile, m: _Module, node: ast.ClassDef,
                   value_refs: Set[str]) -> _ClassInfo:
    ci = _ClassInfo(sf, m, node.name)
    _parse_guarded_by(node, ci)
    for item in node.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        scope = _Scope(m.mod, node.name, f"{node.name}.{item.name}",
                       f"{node.name}.{item.name}", set(), m.globals)
        w = _Walker(ci, scope, value_refs)
        # __init__ is pre-publication — direct accesses are exempt, its
        # nested defs (watcher threads, callbacks) are not
        w.walk(item.body, (), f"{node.name}.{item.name}", False,
               record=item.name != "__init__")
    return ci


def _guard_token(ci: _ClassInfo, lock_attr: str) -> str:
    tok = f"{ci.m.mod}.{ci.name}.{lock_attr}"
    return ci.m.alias.get(tok, tok)


def _effective(a: _Access, entry: Dict[str, FrozenSet[str]]
               ) -> FrozenSet[str]:
    held = frozenset(a.held)
    if not a.nested:
        held |= entry.get(a.fn_key, frozenset())
    return held


def analyze(files: List[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        m = _collect_module(sf)
        value_refs: Set[str] = set()
        classes: List[_ClassInfo] = []
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef):
                classes.append(_collect_class(sf, m, node, value_refs))
        entry = _entry_held(m, value_refs)
        for ci in classes:
            findings.extend(_class_findings(ci, entry))
    return findings


def _class_findings(ci: _ClassInfo,
                    entry: Dict[str, FrozenSet[str]]) -> List[Finding]:
    out: List[Finding] = []
    by_field: Dict[str, List[_Access]] = {}
    for a in ci.accesses:
        by_field.setdefault(a.field, []).append(a)
    for field in sorted(set(by_field) | set(ci.declared)):
        accesses = by_field.get(field, [])
        if field in ci.declared:
            lock_attr = ci.declared[field]
            if lock_attr is None:
                continue  # declared single-thread ownership
            guard = _guard_token(ci, lock_attr)
            out.extend(_flag(ci, field, guard, accesses, entry,
                             declared=True))
            continue
        guard, g, u = _infer(ci, field, accesses, entry)
        if guard is None:
            continue
        out.extend(_flag(ci, field, guard, accesses, entry,
                         declared=False, tally=(g, g + u)))
    return out


def _infer(ci: _ClassInfo, field: str, accesses: List[_Access],
           entry) -> Tuple[Optional[str], int, int]:
    counts: Dict[str, int] = {}
    for a in accesses:
        for tok in _effective(a, entry):
            if not _is_wildcard(tok):
                counts[tok] = counts.get(tok, 0) + 1
    if not counts:
        return None, 0, 0
    guard = max(counts, key=lambda t: (counts[t], t))
    g = counts[guard]
    u = sum(1 for a in accesses if guard not in _effective(a, entry))
    if g >= MIN_GUARDED and g > u:
        return guard, g, u
    return None, g, u


def _flag(ci: _ClassInfo, field: str, guard: str,
          accesses: List[_Access], entry, declared: bool,
          tally: Optional[Tuple[int, int]] = None) -> List[Finding]:
    witness = next((a for a in accesses
                    if guard in _effective(a, entry)), None)
    if witness is not None:
        cite = f"witness guarded site {ci.sf.relpath}:{witness.line}"
    elif declared:
        cite = (f"declared by _guarded_by_ at {ci.sf.relpath}:"
                f"{ci.declared_line}")
    else:
        return []
    how = ("declared guard" if declared else
           "inferred guard (%d of %d accesses hold it)" % tally)
    out = []
    for a in accesses:
        if guard in _effective(a, entry):
            continue
        verb = "write to" if a.write else "read of"
        nested_note = (" — inside a nested def that may run after the "
                       "enclosing lock is released" if a.nested else "")
        out.append(Finding(
            "L7", ci.sf.relpath, a.line,
            f"{a.fn_key}: {verb} self.{field} without holding "
            f"{guard!r}, its {how}; {cite}{nested_note}"))
    return out
