"""L2 — lock discipline.

The driver's recv loop, dispatcher, and spill machinery share a handful
of ``threading.Lock``s. A blocking call made while one is held is the
deadlock shape that hangs the whole event loop (every other thread
piles up behind the lock while the holder waits on I/O that may itself
need the lock to complete). This analyzer flags calls that can block
indefinitely made *lexically* inside a ``with <...lock...>:`` block:

- ``time.sleep`` (and a bare imported ``sleep``)
- connection/socket ops: ``recv``/``recv_bytes``/``accept``/
  ``connect``/``send``/``send_bytes``/``sendall``
- ``subprocess`` module calls
- zero-argument ``Queue.get`` (receiver name looks like a queue;
  ``d.get(key)`` passes the key positionally and is not flagged)
- ``Future.result``
- zero-argument ``.join()`` (thread/process join without timeout;
  ``sep.join(parts)`` always has an argument and is not flagged)

Nested ``def``/``lambda`` bodies are skipped — they execute later, not
under the lock. Deliberate holds (e.g. a send lock whose entire purpose
is serializing ``conn.send``) are waived per-site with
``# rtpu-lint: disable=L2`` plus a justification.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional

from ray_tpu.tools.lint.base import Finding, SourceFile, \
    enclosing_function_name

_CONN_OPS = {"recv", "recv_bytes", "accept", "connect", "send",
             "send_bytes", "sendall"}


def _lock_name(expr: ast.AST) -> Optional[str]:
    """The lock's name when expr looks like a lock acquisition."""
    if isinstance(expr, ast.Attribute) and "lock" in expr.attr.lower():
        return expr.attr
    if isinstance(expr, ast.Name) and "lock" in expr.id.lower():
        return expr.id
    return None


def _receiver_name(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _blocking_reason(call: ast.Call) -> Optional[str]:
    func = call.func
    if isinstance(func, ast.Name):
        if func.id == "sleep":
            return "sleep()"
        return None
    if not isinstance(func, ast.Attribute):
        return None
    attr = func.attr
    recv = _receiver_name(func.value)
    if attr == "sleep" and recv == "time":
        return "time.sleep()"
    if recv == "subprocess":
        return f"subprocess.{attr}()"
    if attr in _CONN_OPS:
        return f".{attr}() on a connection/socket"
    if attr == "result":
        return ".result() on a future"
    if (attr == "get" and not call.args
            and ("queue" in recv.lower() or recv == "q")):
        # zero positional args: Queue.get(); a dict .get(key) always
        # passes the key positionally
        return ".get() on a queue"
    if attr == "join" and not call.args and not call.keywords:
        return ".join() without a timeout"
    return None


def _walk_lock_body(stmts: List[ast.stmt]) -> Iterator[ast.Call]:
    """Calls lexically executed under the lock: skip nested function
    and lambda bodies (they run later)."""
    for stmt in stmts:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue  # the whole statement is a deferred body
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                # exclude calls nested inside an inner def/lambda
                if _inside_deferred(stmt, node):
                    continue
                yield node


def _inside_deferred(root: ast.AST, target: ast.Call) -> bool:
    """True when target sits inside a def/lambda nested under root."""
    found = []

    def visit(node, deferred):
        if node is target:
            found.append(deferred)
            return True
        for child in ast.iter_child_nodes(node):
            d = deferred or isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda))
            if visit(child, d):
                return True
        return False

    visit(root, False)
    return bool(found and found[0])


def analyze_file(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        lock = None
        for item in node.items:
            lock = _lock_name(item.context_expr)
            if lock:
                break
        if not lock:
            continue
        for call in _walk_lock_body(node.body):
            reason = _blocking_reason(call)
            if reason is None:
                continue
            fn = enclosing_function_name(sf.tree, node)
            findings.append(Finding(
                "L2", sf.relpath, call.lineno,
                f"{fn}: blocking call {reason} while holding "
                f"{lock!r} — move the blocking work outside the "
                f"critical section or narrow it"))
    return findings


def analyze(files: List[SourceFile]) -> List[Finding]:
    out: List[Finding] = []
    for sf in files:
        out.extend(analyze_file(sf))
    return out
