"""L3 — config/env hygiene.

``core/config.py`` is this codebase's analogue of the reference's
``RAY_CONFIG`` x-macro table (src/ray/common/ray_config_def.h), where an
unknown flag is a *build error*. Python gives us no such check, so an
attribute typo (``config.task_max_retrys``) silently reads nothing and
a renamed flag silently strands every env override. This analyzer
closes the gap, entirely from the AST (no imports of product code):

- every ``config.<attr>`` access in a module that imports the config
  singleton must resolve to a declared ``Flag`` row (or a table method);
- every declared flag must be read somewhere in the package — directly
  or via its ``RTPU_<NAME>`` env var (dead-flag report, anchored at the
  ``Flag(...)`` row so the finding survives unrelated edits);
- every literal ``os.environ``/``os.getenv`` read of an ``RTPU_*`` name
  must map to a flag's env var, a fault-injection site
  (``RTPU_FAULT_<SITE>``, sites parsed from
  ``core/fault_injection.py``), or a wiring variable registered in
  ``config.WIRING_ENV_VARS`` (per-process plumbing injected by the
  spawner — addresses, auth keys, ids — which are not user tunables).

Dynamic keys (f-strings) are out of scope; keep env names literal.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.tools.lint.base import Finding, SourceFile

CONFIG_MODULE = "ray_tpu.core.config"
#: non-flag attributes of the config singleton
CONFIG_METHODS = {"reload", "to_dict", "describe"}


def parse_flag_table(config_sf: SourceFile) -> Dict[str, int]:
    """flag name -> line of its Flag(...) row."""
    flags: Dict[str, int] = {}
    for node in ast.walk(config_sf.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "Flag"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            flags[node.args[0].value] = node.lineno
    return flags


def parse_wiring_env(config_sf: SourceFile) -> Set[str]:
    """Keys of the WIRING_ENV_VARS dict literal in config.py."""
    wiring: Set[str] = set()
    for node in ast.walk(config_sf.tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):  # WIRING: Dict[...] = {...}
            targets = [node.target]
        else:
            continue
        if (any(isinstance(t, ast.Name) and t.id == "WIRING_ENV_VARS"
                for t in targets)
                and isinstance(node.value, ast.Dict)):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    wiring.add(k.value)
    return wiring


def parse_fault_sites(fault_sf: Optional[SourceFile]) -> Set[str]:
    """SITES tuple from core/fault_injection.py -> RTPU_FAULT_* names."""
    sites: Set[str] = set()
    if fault_sf is None:
        return sites
    for node in ast.walk(fault_sf.tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "SITES"
                        for t in node.targets)
                and isinstance(node.value, (ast.Tuple, ast.List))):
            for e in node.value.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    sites.add(f"RTPU_FAULT_{e.value.upper()}")
    return sites


def fault_site_coverage(fault_sf: Optional[SourceFile],
                        test_files: List[SourceFile]) -> List[Finding]:
    """Every site in ``fault_injection.SITES`` must be exercised by at
    least one test: an armed site nothing fires is dead chaos
    instrumentation — the product hook can rot (or be deleted) without
    any signal. A test exercises a site by arming it through any of the
    three mechanisms: in-process ``inject("<site>", ...)``, the
    ``RTPU_FAULT_<SITE>`` env var, or a ``fault_injection`` config-flag
    spec containing ``<site>=``. Findings anchor at the ``SITES`` row so
    they survive unrelated edits."""
    if fault_sf is None:
        return []
    sites: Dict[str, int] = {}
    for node in ast.walk(fault_sf.tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "SITES"
                        for t in node.targets)
                and isinstance(node.value, (ast.Tuple, ast.List))):
            for e in node.value.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    sites[e.value] = node.lineno
    corpus = "\n".join(sf.text for sf in test_files)
    findings: List[Finding] = []
    for site, lineno in sorted(sites.items()):
        # the flag-spec pattern is quote-anchored ("task=exit:1") so
        # e.g. site "get" does not match every "target=..." kwarg
        patterns = (f'inject("{site}"', f"inject('{site}'",
                    f"RTPU_FAULT_{site.upper()}", f'"{site}=',
                    f"'{site}=")
        if any(p in corpus for p in patterns):
            continue
        if fault_sf.suppressed(lineno, "L3"):
            continue
        findings.append(Finding(
            "L3", fault_sf.relpath, lineno,
            f"fault site {site!r} is declared in SITES but no test "
            f"under tests/ arms it (inject(\"{site}\", ...), "
            f"RTPU_FAULT_{site.upper()}, or a fault_injection flag "
            f"spec); an unexercised site is dead chaos instrumentation"))
    return findings


def netem_policy_coverage(netem_sf: Optional[SourceFile],
                          test_files: List[SourceFile]) -> List[Finding]:
    """Every fault kind in ``netem.KINDS`` must be armed by at least one
    test — same contract as :func:`fault_site_coverage` for the wire-
    level chaos shim: a policy kind no test ever arms is dead chaos
    machinery whose product weave (rpc.py) can rot silently. A test arms
    a kind via a quoted literal (``add_rule(..., "drop")``, a control
    op, a parse_spec string) or an ``=<kind>`` rule in an ``RTPU_NETEM``
    spec. Findings anchor at the ``KINDS`` row."""
    if netem_sf is None:
        return []
    kinds: Dict[str, int] = {}
    for node in ast.walk(netem_sf.tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "KINDS"
                        for t in node.targets)
                and isinstance(node.value, (ast.Tuple, ast.List))):
            for e in node.value.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    kinds[e.value] = node.lineno
    corpus = "\n".join(sf.text for sf in test_files)
    findings: List[Finding] = []
    for kind, lineno in sorted(kinds.items()):
        # quote- or spec-anchored so e.g. kind "drop" does not match the
        # word "drop" in a comment: a quoted literal covers add_rule /
        # control / parse_spec call sites, "=<kind>" covers rules inside
        # an RTPU_NETEM spec string ("a->b=drop,p=0.5" / "...=drop;")
        patterns = (f'"{kind}"', f"'{kind}'", f"={kind},", f"={kind};",
                    f'={kind}"', f"={kind}'")
        if any(p in corpus for p in patterns):
            continue
        if netem_sf.suppressed(lineno, "L3"):
            continue
        findings.append(Finding(
            "L3", netem_sf.relpath, lineno,
            f"netem fault kind {kind!r} is declared in KINDS but no test "
            f"under tests/ arms it (add_rule/control/parse_spec literal "
            f"or an '=<kind>' RTPU_NETEM spec rule); an unexercised "
            f"policy is dead chaos machinery"))
    return findings


def _config_aliases(tree: ast.AST) -> Set[str]:
    """Names the config singleton is bound to in this module."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == CONFIG_MODULE:
            for a in node.names:
                if a.name == "config":
                    aliases.add(a.asname or "config")
    return aliases


def config_attr_reads(sf: SourceFile) -> List[Tuple[str, int]]:
    """(attr, line) for every attribute access on the config singleton."""
    aliases = _config_aliases(sf.tree)
    if not aliases:
        return []
    reads: List[Tuple[str, int]] = []
    for node in ast.walk(sf.tree):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id in aliases):
            reads.append((node.attr, node.lineno))
    return reads


def env_reads(sf: SourceFile) -> List[Tuple[str, int]]:
    """(name, line) for literal os.environ/os.getenv reads."""
    reads: List[Tuple[str, int]] = []

    def is_environ(node: ast.AST) -> bool:
        return ((isinstance(node, ast.Attribute) and node.attr == "environ")
                or (isinstance(node, ast.Name) and node.id == "environ"))

    for node in ast.walk(sf.tree):
        if (isinstance(node, ast.Subscript) and is_environ(node.value)
                and isinstance(node.ctx, ast.Load)
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)):
            reads.append((node.slice.value, node.lineno))
        elif isinstance(node, ast.Call) and isinstance(node.func,
                                                       ast.Attribute):
            f = node.func
            key = (node.args[0].value
                   if node.args and isinstance(node.args[0], ast.Constant)
                   and isinstance(node.args[0].value, str) else None)
            if key is None:
                continue
            if f.attr == "get" and is_environ(f.value):
                reads.append((key, node.lineno))
            elif (f.attr == "getenv" and isinstance(f.value, ast.Name)
                  and f.value.id == "os"):
                reads.append((key, node.lineno))
    return reads


def analyze(config_sf: SourceFile, fault_sf: Optional[SourceFile],
            files: List[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    flags = parse_flag_table(config_sf)
    env_of_flag = {"RTPU_" + name.upper(): name for name in flags}
    wiring = parse_wiring_env(config_sf)
    fault_env = parse_fault_sites(fault_sf)

    read_flags: Set[str] = set()
    for sf in files:
        is_config = sf.relpath == config_sf.relpath
        for attr, lineno in config_attr_reads(sf):
            if attr in flags:
                read_flags.add(attr)
            elif attr not in CONFIG_METHODS and not is_config:
                if not sf.suppressed(lineno, "L3"):
                    findings.append(Finding(
                        "L3", sf.relpath, lineno,
                        f"config.{attr} does not resolve to any declared "
                        f"Flag row in core/config.py (typo, or a flag "
                        f"that was removed/renamed)"))
        for name, lineno in env_reads(sf):
            if not name.startswith("RTPU_"):
                continue
            if name in env_of_flag:
                read_flags.add(env_of_flag[name])
                continue
            if name in wiring or name in fault_env:
                continue
            if not sf.suppressed(lineno, "L3"):
                findings.append(Finding(
                    "L3", sf.relpath, lineno,
                    f"env read of {name} is not declared: no flag has "
                    f"this env_var, it is not RTPU_FAULT_<site>, and it "
                    f"is not registered in config.WIRING_ENV_VARS"))
    for name, lineno in sorted(flags.items()):
        if name not in read_flags and \
                not config_sf.suppressed(lineno, "L3"):
            findings.append(Finding(
                "L3", config_sf.relpath, lineno,
                f"flag {name!r} is declared but never read anywhere in "
                f"the package (dead flag: delete the row or wire it up)"))
    return findings
