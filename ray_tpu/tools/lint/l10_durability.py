"""L10 — durability & resync coverage for the GCS WAL.

The GCS survives restarts by replaying ``snapshot.pkl`` +
``wal.pkl`` through the very same ``_op_*`` bodies that applied the
ops live, and the cluster re-converges through ``resync_node`` plus the
``gcs_info`` cursor clamps. Four invariants keep that machinery honest,
and each one is a hand-synchronized pair of tables today — this rule
checks them against each other:

1. **Snapshot coverage** — every table a ``_WAL_OPS`` member mutates
   (including ``_WAL_KV_MUTATORS`` sub-ops, via ``_op_kv``) must be
   serialized by ``_snapshot_state`` and restored by
   ``_restore_state``; otherwise compaction silently DROPS the state
   the WAL was supposed to protect (the WAL truncates at snapshot
   time).
2. **WAL coverage** — conversely, an ``_op_*`` arm that writes a
   persisted table while absent from ``_WAL_OPS`` produces writes that
   exist in snapshots only by luck of compaction timing and never in
   the log.
3. **Replay determinism** — WAL replay re-executes apply bodies, so
   wall-clock reads, ``random``, ``os.urandom``, and env reads inside
   them (or helpers they call, or constructors they run) make a
   replayed GCS diverge from the live one.
4. **Resync coverage** — every WAL op must declare, in
   ``RESYNC_COVERAGE`` (protocol_meta.py), how its state re-converges
   when the head restarts EMPTY: re-pushed by ``resync_node``
   (``resync:<literal>`` / ``helper:<fn>``), re-cut at a ``gcs_info``
   cursor (``cursor:<key>``), or snapshot-only (``durable``, justified
   in the table). Declarations are verified against the code they
   name; drift (a stale entry, a renamed cursor, a helper that no
   longer sends the op) is flagged.

Approximations (deliberate): mutation detection sees direct
assignments/augments/deletes on ``self._x`` (including subscripts),
mutating method calls (``.append``/``.update``/...), ``self._x``
passed positionally to a non-builtin function, and recurses into
same-class ``self._helper()`` calls — it does not track aliases bound
to locals or follow ``Thread(target=...)`` values. Time reads that are
genuinely transient (drain grace deadlines, liveness stamps) are
waived per site with the argument why replay divergence is harmless.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.tools.lint.base import Finding, SourceFile

#: transient GCS bookkeeping that is rebuilt, not persisted — mutating
#: these from any op is fine and never a durability gap
EXEMPT_ATTRS = frozenset({
    "_wal", "_wal_pending", "_wal_count", "_peer_reports", "_drivers",
    "_fenced", "_fenced_by", "_next_orphan_scan", "_recovering_until",
    "_epoch", "_epoch_seq", "_stop", "_lock", "_wal_lock", "_cond",
})

#: container methods that mutate their receiver
MUTATORS = frozenset({
    "append", "add", "update", "setdefault", "pop", "popitem", "clear",
    "remove", "extend", "insert", "discard", "appendleft",
})

#: calls that only read their arguments — passing self._x to these is
#: not a mutation
SAFE_CALLS = frozenset({
    "list", "dict", "tuple", "set", "frozenset", "len", "sorted", "str",
    "int", "float", "bool", "bytes", "max", "min", "sum", "enumerate",
    "zip", "map", "filter", "iter", "next", "repr", "print",
    "isinstance", "any", "all", "id", "hash", "getattr", "hasattr",
    "reversed", "range",
})

#: dotted call patterns that read wall clock / randomness / environment
NONDET_CALLS = {
    ("time", "time"), ("time", "monotonic"), ("time", "time_ns"),
    ("time", "monotonic_ns"), ("time", "perf_counter"),
    ("datetime", "now"), ("datetime", "utcnow"), ("date", "today"),
    ("os", "urandom"), ("os", "getenv"), ("os", "getpid"),
    ("random", "random"), ("random", "randint"), ("random", "choice"),
    ("random", "shuffle"), ("random", "uniform"), ("random", "randrange"),
    ("random", "getrandbits"), ("uuid", "uuid1"), ("uuid", "uuid4"),
    ("secrets", "token_bytes"), ("secrets", "token_hex"),
}

#: WAL records that replay through a helper instead of an ``_op_``
#: (gcs.py _load_persisted special-cases them)
PSEUDO_WAL_HELPERS = ("_mark_dead_locked",)


# ------------------------------------------------------------- gcs model

def frozenset_literal(tree: ast.AST, name: str) -> Dict[str, int]:
    """Module-level ``NAME = frozenset({...})`` -> {value: line}."""
    out: Dict[str, int] = {}
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name
                and isinstance(node.value, ast.Call)):
            continue
        for arg in node.value.args:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Constant) and isinstance(
                        sub.value, str):
                    out.setdefault(sub.value, sub.lineno)
    return out


def _find_fn(tree: ast.AST, name: str) -> Optional[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            return node
    return None


def _methods(tree: ast.AST) -> Dict[str, ast.FunctionDef]:
    out: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    out.setdefault(item.name, item)
    return out


def _classes(tree: ast.AST) -> Dict[str, ast.ClassDef]:
    return {node.name: node for node in ast.walk(tree)
            if isinstance(node, ast.ClassDef)}


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self._x`` or ``self._x[...]`` -> ``_x``."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and isinstance(node.value,
                                                      ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def snapshot_attrs(gcs_sf: SourceFile) -> Set[str]:
    fn = _find_fn(gcs_sf.tree, "_snapshot_state")
    out: Set[str] = set()
    if fn is not None:
        for node in ast.walk(fn):
            attr = _self_attr(node)
            if attr is not None:
                out.add(attr)
    return out - {"_lock"}


def restored_attrs(gcs_sf: SourceFile) -> Set[str]:
    fn = _find_fn(gcs_sf.tree, "_restore_state")
    out: Set[str] = set()
    if fn is not None:
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    elts = t.elts if isinstance(t, ast.Tuple) else [t]
                    for e in elts:
                        attr = _self_attr(e)
                        if attr is not None:
                            out.add(attr)
    return out


# --------------------------------------------------------- mutation scan

def mutated_attrs(fn: ast.FunctionDef, methods: Dict[str, ast.FunctionDef],
                  visited: Optional[Set[str]] = None) -> Dict[str, int]:
    """attr -> witness line for every ``self._x`` this function (or a
    same-class helper it calls) mutates."""
    if visited is None:
        visited = set()
    if fn.name in visited:
        return {}
    visited.add(fn.name)
    out: Dict[str, int] = {}

    def note(attr: Optional[str], line: int) -> None:
        if attr is not None:
            out.setdefault(attr, line)

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for e in (t.elts if isinstance(t, ast.Tuple) else [t]):
                    note(_self_attr(e), node.lineno)
        elif isinstance(node, ast.AugAssign):
            note(_self_attr(node.target), node.lineno)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                note(_self_attr(t), node.lineno)
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                if f.attr in MUTATORS:
                    note(_self_attr(f.value), node.lineno)
                helper = None
                if isinstance(f.value, ast.Name) and f.value.id == "self":
                    helper = f.attr
                if helper in methods and helper not in visited:
                    for attr, line in mutated_attrs(
                            methods[helper], methods, visited).items():
                        note(attr, line)
            elif isinstance(f, ast.Name) and f.id not in SAFE_CALLS:
                # note_freed(self._freed, ids): positional self-attr
                # args handed to an unknown callable count as writes
                for arg in node.args:
                    note(_self_attr(arg), node.lineno)
    return out


# --------------------------------------------------- nondeterminism scan

def nondet_sites(fn: ast.FunctionDef, methods: Dict[str, ast.FunctionDef],
                 classes: Dict[str, ast.ClassDef],
                 visited: Optional[Set[str]] = None
                 ) -> List[Tuple[int, str]]:
    if visited is None:
        visited = set()
    key = "fn:" + fn.name
    if key in visited:
        return []
    visited.add(key)
    out: List[Tuple[int, str]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) and node.attr == "environ" \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "os":
            out.append((node.lineno, "os.environ read"))
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            if (f.value.id, f.attr) in NONDET_CALLS:
                out.append((node.lineno, f"{f.value.id}.{f.attr}()"))
            elif f.value.id == "self" and f.attr in methods \
                    and "fn:" + f.attr not in visited:
                out.extend(nondet_sites(methods[f.attr], methods,
                                        classes, visited))
        elif isinstance(f, ast.Name) and f.id in classes \
                and "cls:" + f.id not in visited:
            visited.add("cls:" + f.id)
            init = next(
                (i for i in classes[f.id].body
                 if isinstance(i, ast.FunctionDef)
                 and i.name == "__init__"), None)
            if init is not None:
                for _, what in nondet_sites(init, methods, classes,
                                            visited):
                    out.append((node.lineno,
                                f"{f.id}() constructor runs {what}"))
    return out


# -------------------------------------------------------- resync surface

def _resync_literals(ha_sf: SourceFile) -> Set[str]:
    fn = _find_fn(ha_sf.tree, "resync_node")
    out: Set[str] = set()
    if fn is not None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Constant) and isinstance(node.value,
                                                             str):
                out.add(node.value)
    return out


def _resync_called(ha_sf: SourceFile) -> Set[str]:
    fn = _find_fn(ha_sf.tree, "resync_node")
    out: Set[str] = set()
    if fn is not None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute):
                    out.add(node.func.attr)
                elif isinstance(node.func, ast.Name):
                    out.add(node.func.id)
    return out


def _gcs_info_keys(gcs_sf: SourceFile) -> Set[str]:
    fn = _find_fn(gcs_sf.tree, "_op_gcs_info")
    out: Set[str] = set()
    if fn is not None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Dict):
                for k in node.keys:
                    if isinstance(k, ast.Constant) and isinstance(
                            k.value, str):
                        out.add(k.value)
    return out


def load_resync_coverage(meta_sf: SourceFile) -> Dict[str, Tuple[str,
                                                                 int]]:
    out: Dict[str, Tuple[str, int]] = {}
    for node in meta_sf.tree.body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name) and node.value is not None:
            target, value = node.target.id, node.value
        if target != "RESYNC_COVERAGE" or not isinstance(value, ast.Dict):
            continue
        for k, v in zip(value.keys, value.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str) \
                    and isinstance(v, ast.Constant) \
                    and isinstance(v.value, str):
                out[k.value] = (v.value, k.lineno)
    return out


# --------------------------------------------------------------- checks

def analyze(meta_sf: SourceFile, gcs_sf: SourceFile, ha_sf: SourceFile,
            node_server_sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    wal_ops = frozenset_literal(gcs_sf.tree, "_WAL_OPS")
    persisted = snapshot_attrs(gcs_sf)
    restored = restored_attrs(gcs_sf)
    methods = _methods(gcs_sf.tree)
    classes = _classes(gcs_sf.tree)

    # persisted/restored drift is its own gap
    for attr in sorted(persisted - restored):
        findings.append(Finding(
            "L10", gcs_sf.relpath, 1,
            f"_snapshot_state serializes self.{attr} but _restore_state "
            f"never restores it — snapshots silently drop it on reload"))

    # (1) WAL op mutations must round-trip through the snapshot
    for op in sorted(wal_ops):
        fn = methods.get(f"_op_{op}")
        if fn is None:
            findings.append(Finding(
                "L10", gcs_sf.relpath, wal_ops[op],
                f"_WAL_OPS lists {op!r} but no _op_{op} handler exists "
                f"— replay of its records is a no-op"))
            continue
        for attr, line in sorted(mutated_attrs(fn, methods).items()):
            if attr in EXEMPT_ATTRS:
                continue
            if attr not in persisted:
                findings.append(Finding(
                    "L10", gcs_sf.relpath, line,
                    f"WAL op {op!r} mutates self.{attr}, which "
                    f"_snapshot_state does not serialize — compaction "
                    f"discards the state the WAL protects"))
            elif attr not in restored:
                findings.append(Finding(
                    "L10", gcs_sf.relpath, line,
                    f"WAL op {op!r} mutates self.{attr}, which "
                    f"_restore_state never restores"))

    # (2) non-WAL ops must not write persisted tables
    for name, fn in sorted(methods.items()):
        if not name.startswith("_op_") or name[4:] in wal_ops:
            continue
        for attr, line in sorted(mutated_attrs(fn, methods).items()):
            if attr in EXEMPT_ATTRS or attr not in persisted:
                continue
            findings.append(Finding(
                "L10", gcs_sf.relpath, line,
                f"{name} writes persisted table self.{attr} but "
                f"{name[4:]!r} is not in _WAL_OPS — the write reaches "
                f"snapshots only by compaction timing and never the "
                f"log"))

    # (3) replay determinism
    replayed = [(op, methods.get(f"_op_{op}")) for op in sorted(wal_ops)]
    replayed += [(h, methods.get(h)) for h in PSEUDO_WAL_HELPERS]
    for op, fn in replayed:
        if fn is None:
            continue
        for line, what in sorted(set(nondet_sites(fn, methods, classes))):
            findings.append(Finding(
                "L10", gcs_sf.relpath, line,
                f"WAL-replayed body of {fn.name} reaches {what} — "
                f"replay must be deterministic or the rehydrated GCS "
                f"diverges from the live one"))

    # (4) resync coverage
    coverage = load_resync_coverage(meta_sf)
    resync_lits = _resync_literals(ha_sf)
    resync_calls = _resync_called(ha_sf)
    cursor_keys = _gcs_info_keys(gcs_sf)
    ns_methods = _methods(node_server_sf.tree)
    for op in sorted(wal_ops):
        if op not in coverage:
            findings.append(Finding(
                "L10", gcs_sf.relpath, wal_ops[op],
                f"WAL op {op!r} has no RESYNC_COVERAGE entry — declare "
                f"how its state re-converges after a restart from "
                f"EMPTY (resync:/helper:/cursor:/durable)"))
    for op, (decl, line) in sorted(coverage.items()):
        if op not in wal_ops:
            findings.append(Finding(
                "L10", meta_sf.relpath, line,
                f"RESYNC_COVERAGE entry {op!r} is not a _WAL_OPS "
                f"member — stale entry"))
            continue
        scheme, _, arg = decl.partition(":")
        if scheme == "durable":
            continue
        if scheme == "resync":
            if arg not in resync_lits:
                findings.append(Finding(
                    "L10", meta_sf.relpath, line,
                    f"RESYNC_COVERAGE claims {op!r} is re-pushed as "
                    f"{arg!r} but resync_node (ha.py) never sends that "
                    f"op"))
        elif scheme == "helper":
            helper = ns_methods.get(arg) or (
                _find_fn(node_server_sf.tree, arg))
            sends = set()
            if helper is not None:
                sends = {n.value for n in ast.walk(helper)
                         if isinstance(n, ast.Constant)
                         and isinstance(n.value, str)}
            if arg not in resync_calls:
                findings.append(Finding(
                    "L10", meta_sf.relpath, line,
                    f"RESYNC_COVERAGE claims {op!r} resyncs via helper "
                    f"{arg!r} but resync_node never calls it"))
            elif helper is None or op not in sends:
                findings.append(Finding(
                    "L10", meta_sf.relpath, line,
                    f"RESYNC_COVERAGE claims {op!r} resyncs via helper "
                    f"{arg!r} but that helper builds no {op!r} message"))
        elif scheme == "cursor":
            if arg not in cursor_keys:
                findings.append(Finding(
                    "L10", meta_sf.relpath, line,
                    f"RESYNC_COVERAGE claims {op!r} re-cuts at gcs_info "
                    f"cursor {arg!r}, which _op_gcs_info does not "
                    f"report"))
        else:
            findings.append(Finding(
                "L10", meta_sf.relpath, line,
                f"RESYNC_COVERAGE entry {op!r} uses unknown scheme "
                f"{decl!r}"))
    return findings
