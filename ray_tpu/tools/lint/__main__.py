"""CLI for rtpu-lint. Exit codes: 0 clean, 1 findings, 2 usage or
internal error."""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from ray_tpu.tools.lint import RULES, runner


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.tools.lint",
        description="AST-based invariant checker for ray_tpu "
                    "(rules: %s)" % ", ".join(
                        f"{k}={v.split(':')[0]}" for k, v in RULES.items()))
    parser.add_argument("--root", default=None,
                        help="repo root to lint (default: the tree "
                             "containing the installed ray_tpu package)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run "
                             "(default: all)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit a JSON object: {'findings': [...], "
                             "'rule_wall_ms': {rule: ms}}")
    parser.add_argument("--sarif", action="store_true", dest="as_sarif",
                        help="emit a SARIF 2.1.0 log; waived sites are "
                             "included with suppressions kind=inSource "
                             "(they never count toward the exit code)")
    parser.add_argument("--jobs", type=int, default=1, metavar="N",
                        help="run rules in parallel on N threads "
                             "(default: 1, serial)")
    parser.add_argument("--diff", default=None, metavar="GIT_REF",
                        help="report findings only in files changed vs "
                             "GIT_REF (committed + working tree); "
                             "whole-program rules still analyze the "
                             "full tree — the fast pre-commit gate")
    parser.add_argument("--baseline", default=None, metavar="FILE",
                        help="only fail on findings NOT in this baseline "
                             "file (grandfather existing ones)")
    parser.add_argument("--write-baseline", default=None, metavar="FILE",
                        help="write current findings to FILE and exit 0")
    args = parser.parse_args(argv)

    rules = [r for r in (args.rules or "").split(",") if r] or None
    if args.jobs < 1:
        print("rtpu-lint: --jobs must be >= 1", file=sys.stderr)
        return 2
    if args.as_sarif and args.as_json:
        print("rtpu-lint: --sarif and --json are mutually exclusive",
              file=sys.stderr)
        return 2
    changed = None
    if args.diff is not None:
        try:
            changed = runner.changed_files(
                args.root or runner.default_root(), args.diff)
        except RuntimeError as e:
            print(f"rtpu-lint: --diff: {e}", file=sys.stderr)
            return 2
        if not changed:
            print("rtpu-lint: --diff: no .py files changed, 0 "
                  "finding(s)")
            return 0
    try:
        findings, wall_ms = runner.collect_findings_timed(
            root=args.root, rules=rules, jobs=args.jobs,
            changed_only=changed, include_suppressed=args.as_sarif)
    except runner.RuleCrash as e:
        # a rule blew up mid-analysis: name the rule and the file it
        # was chewing on — an actionable exit 2, not a silent pass
        print(f"rtpu-lint: {e}", file=sys.stderr)
        return 2
    except Exception as e:  # noqa: BLE001 — CLI boundary: fold any
        # analyzer crash into the documented exit-2 contract
        print(f"rtpu-lint: internal error: {e!r}", file=sys.stderr)
        return 2

    # waived sites only reach `findings` under --sarif (annotated, so
    # viewers show them as suppressed-in-source); everything that
    # gates — baselines, the exit code — sees open findings only
    open_findings = [f for f in findings if not f.suppressed]

    if args.write_baseline:
        runner.write_baseline(args.write_baseline, open_findings)
        print(f"rtpu-lint: wrote {len(open_findings)} finding key(s) to "
              f"{args.write_baseline}")
        return 0

    if args.baseline:
        try:
            baseline = runner.load_baseline(args.baseline)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"rtpu-lint: cannot read baseline {args.baseline}: "
                  f"{e}", file=sys.stderr)
            return 2
        findings = runner.apply_baseline(findings, baseline)
        open_findings = [f for f in findings if not f.suppressed]

    if args.as_sarif:
        print(json.dumps(runner.to_sarif(findings), indent=1))
    elif args.as_json:
        print(json.dumps({"findings": [f.to_dict() for f in findings],
                          "rule_wall_ms": wall_ms}, indent=1))
    else:
        for f in findings:
            print(f.render())
        word = "new finding(s)" if args.baseline else "finding(s)"
        print(f"rtpu-lint: {len(findings)} {word}")
    return 1 if open_findings else 0


if __name__ == "__main__":
    sys.exit(main())
